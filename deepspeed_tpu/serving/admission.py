"""Knee-seeking admission control + brownout degradation ladder.

The measurement half of the stack (windowed series rates, queue-wait /
TTFT histograms, the goodput ledger) exists so something can ACT on it.
This module is that actor: an :class:`AdmissionController` that holds
offered load at the capacity knee — shedding at the door *before* SLOs
blow — and degrades quality-of-service in ordered, hysteresis-gated
brownout levels instead of collapsing past the knee the way
``serve_capacity`` shows the uncontrolled engine does.

Three cooperating pieces:

  * **Knee-seeking door (AIMD).** The controller owns an admission
    window ``W`` — the ``max_live``-style concurrency bound the
    open-loop driver already understands. Evidence is ONLY existing
    registry state: the windowed ``rate()`` of admitted/completed
    requests and tokens, plus a *windowed* queue-wait p99 recovered
    from the cumulative streaming histograms by bucket-delta snapshots
    (:class:`_WindowQuantile` — two same-gamma DDSketches subtract
    exactly, so the delta sketch IS the last window's distribution).
    While the windowed queue-wait p99 exceeds the SLO the window
    multiplicatively decreases (``md``); after ``hysteresis_s`` of
    continuous health it additively recovers (``ai``) back toward the
    slot capacity. Offers beyond ``W`` are rejected AT THE DOOR with a
    TYPED rejection record (reason ``admission_overload``) carrying a
    computed ``retry_after_s`` hint — never queued into a collapse.
  * **Brownout ladder.** Ordered pressure levels, each trading a little
    quality for stability, entered at most one rung per control tick
    and exited one rung per ``hysteresis_s`` of continuous health (the
    no-flap discipline):

      ====  ==============  ==============================================
      L0    ``normal``      nothing actuated
      L1    ``defer_promote``  hierarchical-KV promote-ahead head start
                              stretched (``StateManager.promote_defer_
                              ticks``) — token-stream-invariant
      L2    ``spec_brownout``  speculative decoding bypassed and
                              ``spec_k`` shrunk — spec decode is
                              token-identical to greedy, so toggling it
                              preserves parity while freeing verify
                              FLOPs for committed tokens
      L3    ``throughput_cap`` decode burst depth capped (driver-side)
                              and the prefill chunk cap SHRUNK
                              (compile-safe: the scheduler already
                              emits every chunk length below
                              ``chunk_size``)
      L4    ``shed_lowclass``  lowest-class traffic (``Request.klass >
                              0``, e.g. batch) shed at the door first,
                              preserving interactive goodput
      ====  ==============  ==============================================

    Every transition is a flight-recorder event plus a catalogued
    ``brownout_transitions`` counter, and the current level/window ride
    the ``admission_level`` / ``admission_window`` gauges — so
    ``dstpu_top`` shows which level the fleet is in and why.
  * **Retry contract.** Door rejections carry ``retry_after_s`` ≈
    ``tick_s · 2^level · overload_ratio`` (capped at ``retry_cap_s``).
    The loadgen client honors it with jittered exponential backoff
    under a bounded retry budget; retries keep their ORIGINAL arrival
    identity so goodput accounting stays honest (docs/serving.md
    "Overload control" has the full contract).

Fleet integration: against a :class:`~.pool.ReplicaPool` the controller
reads every live replica's registry, feeds the router a per-replica
``admission_headroom`` term and makes browned-out replicas advertise
reduced slots (``Replica.slot_frac`` scales ``queue_frac``'s
denominator, so the router's full-replica gate trips earlier).

``DSTPU_ADMISSION=0`` (or telemetry off — the controller is blind
without registry evidence) disables everything: :func:`build_admission`
returns None, no actuation attribute is ever written, and the serving
path is bit-identical to pre-controller behavior (tier-1 asserts token
parity and zero fresh compiles either way).

The driver-facing hooks (:meth:`AdmissionController.poll`,
:meth:`~AdmissionController.door`, :meth:`~AdmissionController.reject`)
are dslint DSL001-registered: they run on the admission path between
the engines' overlapped pipelines and must stay pure host arithmetic —
one device sync there would serialize the very pipeline the controller
exists to protect.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from ..telemetry.registry import (Histogram, MetricsRegistry,
                                  new_registry, telemetry_enabled)

#: ladder level -> name (docs/serving.md "Overload control")
BROWNOUT_LEVELS = ("normal", "defer_promote", "spec_brownout",
                   "throughput_cap", "shed_lowclass")

#: overload-ratio thresholds: level L is warranted while the windowed
#: queue-wait p99 exceeds threshold[L] x the SLO (entered one rung per
#: tick, exited one rung per hysteresis window — never instantly)
_LEVEL_RATIOS = (0.0, 1.0, 1.5, 2.0, 3.0)


def admission_enabled() -> bool:
    """The controller kill switch: ``DSTPU_ADMISSION=0`` (or
    ``false``/``off``) disables admission control entirely — the exact
    pre-controller serving path."""
    return os.environ.get("DSTPU_ADMISSION", "1") \
        not in ("0", "false", "off")


def build_admission(target, **kwargs) -> Optional["AdmissionController"]:
    """The serving layer's attach point: an :class:`AdmissionController`
    over ``target`` (an ``InferenceEngineV2`` or a ``ReplicaPool``), or
    None when ``DSTPU_ADMISSION=0`` **or** telemetry is off — the
    controller consumes only registry evidence, so without a registry
    it would be flying blind; None keeps the path bit-identical to the
    uncontrolled engine."""
    if not admission_enabled() or not telemetry_enabled():
        return None
    return AdmissionController(target, **kwargs)


class _WindowQuantile:
    """Windowed quantiles over a CUMULATIVE streaming histogram.

    The registry's histograms only ever grow, so their p99 never
    recovers after a spike — useless as a control signal. This helper
    keeps a rotating bucket snapshot of the source sketch and answers
    quantiles over the *delta* since that snapshot: two same-gamma
    DDSketches hold integer counts on one bucket lattice, so the
    bucket-wise difference is EXACTLY the sketch a stream of only the
    window's observations would have built. The snapshot rotates every
    ``window_s``, so the delta always covers between 1x and 2x the
    window — recent enough to steer on, wide enough to hold a p99.

    Pure host arithmetic over dict copies; no registry mutation.
    """

    __slots__ = ("window_s", "_t_snap", "_buckets", "_zero", "_count",
                 "_sum")

    def __init__(self, window_s: float):
        self.window_s = float(window_s)
        self._t_snap = 0.0
        self._buckets: Dict[int, int] = {}
        self._zero = 0
        self._count = 0
        self._sum = 0.0

    def observe(self, src: Histogram, q: float,
                now: float) -> Optional[float]:
        """Quantile ``q`` of ``src``'s observations since the previous
        snapshot (None when the window saw nothing), rotating the
        snapshot when ``window_s`` has elapsed."""
        buckets = getattr(src, "buckets", None)
        if buckets is None:          # NullRegistry handle: no evidence
            return None
        dcount = src.count - self._count
        val: Optional[float] = None
        if dcount > 0:
            delta = Histogram(alpha=src.alpha)
            db = {i: n - self._buckets.get(i, 0)
                  for i, n in buckets.items()
                  if n - self._buckets.get(i, 0) > 0}
            delta.buckets = db
            delta.zero = max(0, src.zero - self._zero)
            delta.count = dcount
            delta.sum = src.sum - self._sum
            # min/max are not windowable on a cumulative sketch; the
            # source's envelope is the conservative clamp (quantile()
            # only uses them to bound the bucket-midpoint estimate)
            delta.min = src.min
            delta.max = src.max
            val = delta.quantile(q)
        if now - self._t_snap >= self.window_s:
            self._t_snap = now
            self._buckets = dict(buckets)
            self._zero = src.zero
            self._count = src.count
            self._sum = src.sum
        return val


class AdmissionController:
    """Knee-seeking admission window + brownout ladder over one engine
    or a replica pool (module docstring has the control law).

    Built through :func:`build_admission`; all knobs are env-mirrored
    with LITERAL names (dslint DSL004/5 scan, docs/CONFIG.md catalog):

      * ``DSTPU_ADMISSION``               on/off kill switch (default 1)
      * ``DSTPU_ADMISSION_WINDOW_S``      evidence window (default 2.0 s)
      * ``DSTPU_ADMISSION_QW_SLO_S``      queue-wait p99 SLO (default 0.5 s)
      * ``DSTPU_ADMISSION_TICK_S``        control-loop period (default 0.25 s)
      * ``DSTPU_ADMISSION_MIN_LIVE``      window floor (default 1)
      * ``DSTPU_ADMISSION_AI``            additive increase (default 1)
      * ``DSTPU_ADMISSION_MD``            multiplicative decrease (default 0.7)
      * ``DSTPU_ADMISSION_HYSTERESIS_S``  health dwell before recovery
        (default 2.0 s)
      * ``DSTPU_ADMISSION_RETRY_CAP_S``   retry-hint ceiling (default 5.0 s)
    """

    def __init__(self, target,
                 window_s: Optional[float] = None,
                 qw_slo_s: Optional[float] = None,
                 tick_s: Optional[float] = None,
                 min_live: Optional[int] = None,
                 ai: Optional[int] = None,
                 md: Optional[float] = None,
                 hysteresis_s: Optional[float] = None,
                 retry_cap_s: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None):
        def _env(name: str, default: str) -> str:
            return os.environ.get(name, default) or default

        self.target = target
        self._is_pool = hasattr(target, "replicas")
        self.window_s = float(_env("DSTPU_ADMISSION_WINDOW_S", "2.0")) \
            if window_s is None else float(window_s)
        self.qw_slo_s = float(_env("DSTPU_ADMISSION_QW_SLO_S", "0.5")) \
            if qw_slo_s is None else float(qw_slo_s)
        self.tick_s = float(_env("DSTPU_ADMISSION_TICK_S", "0.25")) \
            if tick_s is None else float(tick_s)
        self.min_live = max(1, int(
            _env("DSTPU_ADMISSION_MIN_LIVE", "1"))
            if min_live is None else int(min_live))
        self.ai = max(1, int(_env("DSTPU_ADMISSION_AI", "1"))
                      if ai is None else int(ai))
        self.md = float(_env("DSTPU_ADMISSION_MD", "0.7")) \
            if md is None else float(md)
        if not 0.0 < self.md < 1.0:
            raise ValueError(
                f"admission md must be in (0, 1), got {self.md}")
        self.hysteresis_s = float(
            _env("DSTPU_ADMISSION_HYSTERESIS_S", "2.0")) \
            if hysteresis_s is None else float(hysteresis_s)
        self.retry_cap_s = float(
            _env("DSTPU_ADMISSION_RETRY_CAP_S", "5.0")) \
            if retry_cap_s is None else float(retry_cap_s)
        #: stderr trace of every control tick (evidence, window,
        #: level) — the first thing to turn on when a controller
        #: misbehaves in a drill or in production
        self._debug = _env("DSTPU_ADMISSION_DEBUG", "0").lower() \
            not in ("0", "false", "off", "")
        #: slot capacity = the fleet's max_seqs sum — the window's
        #: ceiling and the AIMD recovery target
        self.cap = max(self.min_live, sum(
            eng.config.max_seqs for _, eng, _ in self._engines()) or 1)
        self.window = self.cap
        self.level = 0
        self.transitions = 0
        self.rejected = 0
        self.last_ratio = 0.0
        self.last_qw_p99: Optional[float] = None
        #: driver-side decode-burst ceiling (L3); harmlessly huge at L0
        self.decode_burst_cap = 1 << 30
        self._last_tick = 0.0
        self._last_bad = 0.0
        # -inf: the FIRST bad evidence window always cuts, regardless
        # of where the caller's clock starts
        self._last_cut = float("-inf")
        self._last_exit = 0.0
        self._wq: Dict[str, _WindowQuantile] = {}
        #: per-engine actuation baselines, captured lazily BEFORE the
        #: first brownout write so exits restore the exact prior state
        self._base: Dict[int, Dict[str, Any]] = {}
        if registry is not None:
            self.registry = registry
        else:
            regs = [eng.metrics for _, eng, _ in self._engines()
                    if eng.metrics is not None]
            self.registry = regs[0] if regs \
                else new_registry("admission")
        r = self.registry
        self.g_window = r.gauge("admission_window")
        self.g_level = r.gauge("admission_level")
        self.c_rejected = r.counter("admission_rejected")
        self.h_retry = r.histogram("admission_retry_after_s")
        self._c_trans = {d: r.counter("brownout_transitions",
                                      direction=d)
                         for d in ("enter", "exit")}
        # pool-level door rejections never reach an engine observer, so
        # the controller owns their outcome counter; engine-level ones
        # ride engine._reject -> ServeObserver.on_reject as usual
        self._count_rejects = self._is_pool
        self.g_window.set(self.window)
        self.g_level.set(0)

    # ------------------------------------------------------------------ #
    # evidence plumbing
    # ------------------------------------------------------------------ #

    def _engines(self) -> List[Tuple[str, Any, Any]]:
        """Live (id, engine, replica-or-None) actuation targets —
        re-enumerated per use so joiners/drains are picked up."""
        if self._is_pool:
            return [(rep.replica_id, rep.engine, rep)
                    for rep in self.target.replicas()
                    if rep.state != "dead"]
        return [("engine", self.target, None)]

    def _flight(self):
        fl = getattr(self.target, "flight", None)
        return fl

    # ------------------------------------------------------------------ #
    # the control loop
    # ------------------------------------------------------------------ #

    def poll(self, now: Optional[float] = None) -> None:
        """Run one control tick iff ``tick_s`` elapsed — the driver
        calls this from every admission poll. Registered DSL001 hot
        path: one time read and a compare in the common case."""
        now = time.monotonic() if now is None else now
        if now - self._last_tick >= self.tick_s:
            self.tick(now)

    def tick(self, now: Optional[float] = None) -> None:
        """One control-law step: gather windowed evidence, move the
        AIMD window, move the brownout ladder (≤ one rung), actuate.
        Pure host arithmetic over registry state — tests drive it with
        an explicit ``now`` against synthetic series."""
        now = time.monotonic() if now is None else now
        self._last_tick = now
        worst: Optional[float] = None
        for rid, eng, rep in self._engines():
            m = eng.metrics
            if m is None or not m.enabled:
                continue
            # keep the sampled series fresh even when the engine is too
            # stalled to reach its own commit-boundary sampling — the
            # overloaded case is exactly when evidence matters most
            m.maybe_sample()
            wq = self._wq.get(rid)
            if wq is None:
                wq = self._wq[rid] = _WindowQuantile(self.window_s)
            p99 = wq.observe(m.histogram("serve_queue_wait_s"), 0.99,
                             now)
            if p99 is not None and (worst is None or p99 > worst):
                worst = p99
            if rep is not None:
                rep.admission_headroom = None if p99 is None else \
                    max(-1.0, 1.0 - p99 / self.qw_slo_s)
        self.last_qw_p99 = worst
        ratio = 0.0 if worst is None else worst / self.qw_slo_s
        self.last_ratio = ratio
        # one multiplicative cut per EVIDENCE window, not per tick: the
        # windowed p99 only refreshes when its snapshot rotates (every
        # window_s), so cutting every tick would punish a single bad
        # burst window_s/tick_s times over (TCP cuts once per RTT for
        # the same reason)
        fresh_bad = ratio > 1.0 and now - self._last_cut >= self.window_s
        if ratio > 1.0:
            self._last_bad = now
            if fresh_bad:
                # overloaded: multiplicative decrease; recovery then
                # needs hysteresis_s of CONTINUOUS health
                self._last_cut = now
                self.window = max(self.min_live,
                                  int(self.window * self.md))
        elif self.window < self.cap \
                and now - self._last_bad >= self.hysteresis_s:
            self.window = min(self.cap, self.window + self.ai)
        # ladder: warranted level from the overload ratio; rise one
        # rung per evidence window, fall one rung per hysteresis window
        # of health (its OWN dwell clock, so rung exits do not stall
        # the window's additive recovery)
        want = 0
        for lvl in range(len(BROWNOUT_LEVELS) - 1, 0, -1):
            if ratio > _LEVEL_RATIOS[lvl]:
                want = lvl
                break
        new = self.level
        if want > self.level and fresh_bad:
            new = self.level + 1
        elif want < self.level \
                and now - self._last_bad >= self.hysteresis_s \
                and now - self._last_exit >= self.hysteresis_s:
            new = self.level - 1
            self._last_exit = now
        if new != self.level:
            self._transition(self.level, new, ratio)
        self._apply(new)
        self.level = new
        self.g_window.set(self.window)
        self.g_level.set(self.level)
        if self._debug:
            import sys
            p = "-" if worst is None else f"{worst * 1e3:.1f}ms"
            print(f"[admission] t={now:.3f} qw_p99={p} "
                  f"ratio={ratio:.2f} window={self.window} "
                  f"level={BROWNOUT_LEVELS[self.level]}",
                  file=sys.stderr)

    def prime(self, now: Optional[float] = None) -> None:
        """Rotate the windowed-evidence snapshots past ALL prior
        registry history and reset the control state. The histograms
        are cumulative, so a controller attached to an engine that has
        already served traffic would spend its first window steering on
        stale evidence — the overload drill calls this between its
        controller-off and controller-on passes."""
        now = time.monotonic() if now is None else now
        for rid, eng, _rep in self._engines():
            m = eng.metrics
            if m is None or not m.enabled:
                continue
            wq = self._wq.get(rid)
            if wq is None:
                wq = self._wq[rid] = _WindowQuantile(self.window_s)
            src = m.histogram("serve_queue_wait_s")
            buckets = getattr(src, "buckets", None)
            if buckets is not None:
                wq._t_snap = now
                wq._buckets = dict(buckets)
                wq._zero = src.zero
                wq._count = src.count
                wq._sum = src.sum
        if self.level:
            self._apply(0)
        self.level = 0
        self.window = self.cap
        self.transitions = 0
        self.last_ratio = 0.0
        self.last_qw_p99 = None
        self._last_bad = 0.0
        self._last_cut = float("-inf")
        self._last_exit = 0.0
        self._last_tick = 0.0
        self.g_window.set(self.window)
        self.g_level.set(0)

    def apply_level(self, level: int) -> None:
        """Force the ladder actuation for ``level`` without waiting for
        evidence (idempotent; baselines are captured on first use, so a
        later ``apply_level(0)`` restores the exact prior config).

        Intended for PRE-WARMING: the degraded modes change program
        shapes (spec decode off, prefill chunk halved), so the first
        real brownout would otherwise pay a fresh XLA compile on the
        step path — at the exact moment the engine is overloaded, and
        the resulting stall feeds back into the controller's own
        queue-wait evidence. Deploy-time warmup runs a few requests at
        the deepest compiled level and restores normal before serving.
        """
        self._apply(int(level))
        self.level = int(level)
        self.g_level.set(self.level)

    def _transition(self, old: int, new: int, ratio: float) -> None:
        """Record one ladder move: catalogued counter + flight event
        (the ``dstpu_top`` / postmortem evidence of WHY)."""
        self.transitions += 1
        direction = "enter" if new > old else "exit"
        self._c_trans[direction].inc()
        fl = self._flight()
        if fl is not None:
            fl.event("admission_level", level=new,
                     level_name=BROWNOUT_LEVELS[new],
                     prev=BROWNOUT_LEVELS[old],
                     ratio=round(ratio, 3), window=self.window)

    def _apply(self, level: int) -> None:
        """Actuate the ladder idempotently: every knob is derived from
        its lazily-captured baseline, so repeated application is a
        no-op and exit restores the exact prior state. All writes are
        host attributes the engines re-read per plan/decode call —
        SHRINK-only where compiled shapes are concerned (the scheduler
        already emits every chunk length the shrunken cap produces), so
        no brownout level can trigger a fresh compile.

        Phase-specialist fleets (docs/serving.md "Disaggregated
        serving") actuate per ROLE: decode-side knobs (L2 spec
        brownout, the L3 decode-burst cap) are meaningless on a replica
        that never decodes, and the L3 prefill-chunk halving is
        meaningless on one that never prefills — skipping them keeps a
        specialist's baseline config untouched (and its compiled shapes
        warm) while the knobs that DO apply still bite. ``mixed``
        replicas (the default, and every replica under
        ``DSTPU_DISAGG=0``) actuate everything, exactly as before."""
        for _, eng, rep in self._engines():
            role = getattr(rep, "role", "mixed") if rep is not None \
                else "mixed"
            base = self._base.get(id(eng))
            if base is None:
                base = self._base[id(eng)] = {
                    "promote_defer_ticks": getattr(
                        eng.state, "promote_defer_ticks", 1),
                    "spec_mode": eng.spec_mode,
                    "spec_k": eng.spec_k,
                    "prefill_chunk_cap": eng.config.prefill_chunk_cap,
                }
            # L1: stretch the hierarchical-KV promote-ahead head start —
            # promotions yield more scheduler ticks to decode chunks
            # (token-stream-invariant: only WHEN a prefill chunk runs)
            eng.state.promote_defer_ticks = 4 if level >= 1 \
                else base["promote_defer_ticks"]
            # L2: bypass speculation (spec is token-identical to greedy,
            # so parity holds) and shrink the draft depth for when it
            # comes back partway through recovery. Prefill specialists
            # never run verify rounds — leave their spec config alone
            if level >= 2 and role != "prefill":
                eng.spec_mode = "off"
                eng.spec_k = max(1, min(base["spec_k"], 2))
            else:
                eng.spec_mode = base["spec_mode"]
                eng.spec_k = base["spec_k"]
            # L3: halve the prefill chunk depth (decode latency wins
            # over prefill throughput under pressure); shrink-only.
            # Decode specialists run no prefill chunks — and on a
            # PREFILL specialist there is no colocated decode to
            # protect, so halving would only cut its throughput
            if level >= 3 and role == "mixed":
                cs = eng.config.chunk_size
                cap = base["prefill_chunk_cap"] or cs
                eng.config.prefill_chunk_cap = max(1, min(cap, cs) // 2)
            else:
                eng.config.prefill_chunk_cap = base["prefill_chunk_cap"]
            if rep is not None:
                # browned-out replicas advertise reduced slots: the
                # router's queue_frac denominator shrinks, so its
                # full-replica gate trips earlier fleet-wide
                rep.slot_frac = max(0.25, self.window / self.cap) \
                    if level >= 1 else 1.0
        self.decode_burst_cap = 2 if level >= 3 else (1 << 30)

    # ------------------------------------------------------------------ #
    # the door (driver-facing, DSL001-registered)
    # ------------------------------------------------------------------ #

    def door(self, live: int, klass: int = 0) -> bool:
        """Admit or refuse one offer given ``live`` in-flight requests:
        True = admit. Registered DSL001 hot path — two compares."""
        if self.level >= 4 and klass > 0:
            return False
        return live < self.window

    def retry_after_s(self) -> float:
        """The retry hint a door rejection carries: backs off with the
        ladder level and the measured overload ratio, capped. At level
        0 a rejection only means the window was momentarily full, so
        the hint stays one tick — burning a large slice of a tight
        deadline on the first backoff wastes goodput the engine could
        have delivered."""
        return min(self.retry_cap_s,
                   self.tick_s * (2.0 ** self.level)
                   * max(1.0, self.last_ratio))

    def reject(self, uid: int, klass: int = 0) -> Dict[str, Any]:
        """Record one typed door rejection on the target (the same
        ``rejections`` record shape every other refusal uses, so
        report breakdowns unify) and return the record. Registered
        DSL001 hot path — dict stores and pre-bound counter adds."""
        hint = self.retry_after_s()
        self.rejected += 1
        self.c_rejected.inc()
        self.h_retry.observe(hint)
        if self._count_rejects:
            # pool-level records bypass every engine observer; the
            # controller owns their outcome counter (single engines
            # count through engine._reject -> on_reject as usual)
            self.registry.counter(
                "serve_requests_rejected_admission").inc()
        self.target._reject(uid, "admission_overload",
                            retry_after_s=round(hint, 4),
                            level=self.level, window=self.window,
                            klass=klass)
        return self.target.rejections[uid]

    # ------------------------------------------------------------------ #

    def state(self) -> Dict[str, Any]:
        """Structured controller state for reports and drills."""
        return {
            "window": self.window,
            "cap": self.cap,
            "level": self.level,
            "level_name": BROWNOUT_LEVELS[self.level],
            "transitions": self.transitions,
            "rejected": self.rejected,
            "last_overload_ratio": round(self.last_ratio, 4),
            "last_qw_p99_s": round(self.last_qw_p99, 6)
            if self.last_qw_p99 is not None else None,
            "qw_slo_s": self.qw_slo_s,
            "knobs": {
                "window_s": self.window_s, "tick_s": self.tick_s,
                "min_live": self.min_live, "ai": self.ai,
                "md": self.md, "hysteresis_s": self.hysteresis_s,
                "retry_cap_s": self.retry_cap_s,
            },
        }
