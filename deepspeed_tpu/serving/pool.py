"""Replica pool — the serving layer above ``InferenceEngineV2``.

One :class:`ReplicaPool` owns N v2 ragged engines over disjoint device
sets and presents ONE engine-shaped serving surface (``put`` /
``decode_pipelined`` / ``flush`` / ``state`` / ``rejections`` /
``slo_report``), so every driver written against a single engine — the
open-loop loadgen (:func:`~deepspeed_tpu.telemetry.loadgen.run_open_loop`
and its capacity sweep), the fault drills, the benches — drives a whole
fleet unchanged. This is the DeepSpeed-MII/FastGen deployment shape
(PAPER.md: a load-balanced pool of engine replicas behind one endpoint)
composed from pieces earlier PRs built:

  * **Routing** (:mod:`.router`): each fresh request is placed by a
    pluggable policy; ``prefix_aware`` scores replicas by cached-prefix
    overlap (PR 5 chain keys), queue depth and SLO headroom (PR 8
    per-engine registries).
  * **Elastic membership**: a preempted replica (SIGTERM →
    ``PreemptionHandler`` → ``engine.draining``) is absorbed
    transparently — the pool drains it through the PR 7 manifest,
    routes every manifested sequence onto survivors (whose warm prefix
    caches eat most of the re-prefill), and splices the survivors'
    replay tokens into the caller's streams so they stay gapless and
    token-identical. Late joiners ``add_replica`` and start taking
    traffic on the next routing decision.
  * **Fleet rollup**: per-replica registries merge into one fleet
    snapshot through the exact PR 9 histogram merge, with ``source``
    labels keyed by STABLE replica ids (each replica's registry is
    renamed to its id at registration), so repeated rollups of the same
    fleet are idempotent. The cross-process path is unchanged: each
    replica process exports its snapshot file and
    ``telemetry.merge_snapshots`` (or ``bin/dstpu_top file1 file2`` /
    a glob) rolls them up without shared memory.

Deployment shapes (docs/serving.md "Replica pool"):

  * **in-process** (this module's direct mode, the CPU-harness and
    single-host path): N engines in one process, each built over its
    own device subset (the ``data`` mesh axis position); the pool
    dispatches to them sequentially from the host thread.
  * **multi-host**: one engine per process; the pool abstraction runs
    degenerate (N=1) in each process and the FLEET view exists only in
    telemetry — snapshot files rolled up via ``merge_snapshots``.

Everything here is host-side bookkeeping (dict lookups, list grouping)
around the engines' own overlapped pipelines; the pool's ``put`` /
``decode_pipelined`` and the replica scoring accessors are dslint
DSL001-registered — a blocking device sync in the dispatch path would
serialize every replica behind one readback.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..inference.v2.blocked_allocator import OutOfBlocksError
from ..inference.v2.drain import EngineDrainingError
from ..telemetry.flight_recorder import (FlightRecorder,
                                         atomic_json_dump,
                                         merge_chrome_traces,
                                         register_recorder)
from ..telemetry.registry import Histogram, MetricsRegistry, \
    telemetry_enabled
from ..telemetry.serve import slo_report_from_registry
from .router import NoServingReplicaError, Router

#: replica lifecycle states (docs/serving.md "Membership protocol")
REPLICA_SERVING = "serving"
REPLICA_DRAINING = "draining"
REPLICA_DEAD = "dead"

#: phase-specialist roles (docs/serving.md "Disaggregated serving"):
#: ``mixed`` replicas serve both phases (the pre-disagg behavior and
#: the default), ``prefill`` specialists take fresh admissions and hand
#: each sequence off after its first token, ``decode`` specialists
#: adopt the handoffs and run the decode stream
REPLICA_ROLES = ("prefill", "decode", "mixed")


class Replica:
    """One pool member: an ``InferenceEngineV2`` plus its fleet
    identity and lifecycle state. The scoring accessors below are the
    router's only view of the engine — all pure host reads
    (DSL001-registered)."""

    __slots__ = ("replica_id", "engine", "state", "joined_at", "manifest",
                 "pending_routed", "slot_frac", "admission_headroom",
                 "role", "lock")

    def __init__(self, replica_id: str, engine, role: str = "mixed"):
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"replica role must be one of {REPLICA_ROLES}, "
                f"got {role!r}")
        self.replica_id = replica_id
        self.engine = engine
        self.state = REPLICA_SERVING
        self.joined_at = time.time()
        #: phase specialism (docs/serving.md "Disaggregated serving");
        #: the router's ``phase`` filter reads it, the pool's post-put
        #: migration moves fresh sequences OFF ``prefill`` replicas
        self.role = role
        #: advertised-slots scale in (0, 1] — the AdmissionController
        #: shrinks it while this replica is browned out, so
        #: :meth:`queue_frac`'s denominator contracts and the router's
        #: full-replica gate trips earlier (1.0 = full slots)
        self.slot_frac = 1.0
        #: 1 - windowed queue-wait p99 / SLO, written by the admission
        #: controller's tick (None = controller off or no evidence) —
        #: an additive routing-score term steering toward replicas with
        #: door headroom
        self.admission_headroom: Optional[float] = None
        #: requests routed here in the CURRENT admission batch but not
        #: yet admitted by the engine — counted into :meth:`queue_frac`
        #: so consecutive placements in one batch see each other (a
        #: burst of arrivals must spread by the post-batch load, not
        #: all score the same stale pre-batch state and pile onto one
        #: replica past its slots)
        self.pending_routed = 0
        #: serializes every engine call on this replica — the pool's
        #: concurrency contract (docs/serving.md "Disaggregated
        #: serving"): independent driver threads may call ``put`` and
        #: ``decode_pipelined`` concurrently; each engine is
        #: single-threaded, so the pool takes this lock around every
        #: engine entry point. Reentrant because drain/replay paths
        #: nest engine calls under one holder.
        self.lock = threading.RLock()
        #: the drain manifest once this replica died (None while alive);
        #: ``manifest["pool"]["fully_recovered"]`` is the leak oracle the
        #: fleet drill asserts on
        self.manifest: Optional[Dict[str, Any]] = None
        m = engine.metrics
        if m is not None:
            # stable rollup identity: the engine's registry takes the
            # replica id as its name, so fleet merges label gauges
            # source=<replica id> (idempotent across repeated rollups)
            # and the engine's own snapshot exports self-identify
            m.name = replica_id

    @property
    def available(self) -> bool:
        """Routable: serving and not already unwinding toward a drain
        (the engine's drain flag flips on SIGTERM before the pool hears
        about it — the router must see it immediately)."""
        return self.state == REPLICA_SERVING and not self.engine.draining

    # ------------- routing signals (host-only, DSL001) ---------------- #

    def prefix_overlap(self, tokens: Sequence[int]) -> int:
        """Prompt tokens this replica's prefix cache would serve from
        already-written KV blocks: full matched chain blocks plus the
        copy-on-write tail span. A pure (side-effect-free) trie walk —
        ``PrefixCache.match`` neither acquires nor stats-bumps."""
        dev, host = self.prefix_overlap_tiered(tokens)
        return dev + host

    def prefix_overlap_tiered(self, tokens: Sequence[int]
                              ) -> Tuple[int, int]:
        """(device_tokens, host_tokens) split of :meth:`prefix_overlap`
        — the router scores demoted (host-tier) overlap at a discount:
        a demoted hit still skips the prefill FLOPs but pays the
        promotion copies, so a replica holding the chain on DEVICE
        should win the placement over one that would have to promote
        it. Same pure trie walk, DSL001-clean."""
        pc = self.engine._prefix
        if pc is None:
            return 0, 0
        entries, cow, cow_len = pc.match(tokens)
        bs = pc.block_size
        dev = sum(bs for e in entries if e.tier == "device")
        host = sum(bs for e in entries if e.tier != "device")
        if cow is not None:
            if cow.tier == "device":
                dev += cow_len
            else:
                host += cow_len
        return dev, host

    def queue_frac(self) -> float:
        """(Live + batch-routed) sequences over ADVERTISED slots — the
        load half of the routing score (can exceed 1.0 when the engine
        oversubscribes its pool with paused/queued sequences, which is
        exactly when the replica should repel traffic). Browned-out
        replicas advertise ``slot_frac`` of their physical slots, so
        pressure here rises and the router's full gate trips earlier."""
        ms = self.engine.config.max_seqs * self.slot_frac
        if ms <= 0:
            return 0.0
        return (len(self.engine.state.sequences)
                + self.pending_routed) / ms

    def slo_headroom(self, slo_ttft_s: float) -> float:
        """1 − (this replica's TTFT p99 / the fleet target), clamped to
        [−1, 1]: positive while the replica meets its SLO, negative once
        it violates. Neutral (1.0) with telemetry off or before any
        request completed."""
        m = self.engine.metrics
        if m is None or not m.enabled:
            return 1.0
        p99 = m.histogram("serve_ttft_s").quantile(0.99)
        if p99 is None:
            return 1.0
        h = 1.0 - p99 / slo_ttft_s
        return h if h > -1.0 else -1.0

    def describe(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "role": self.role,
            # the replica's seq-parallel mesh width (1 = single-chip):
            # long-context pools give prefill specialists a wider seq
            # axis than decode ones (docs/serving.md "Long-context
            # serving"), and the fleet drills assert the shape took
            "seq_size": max(1, int(getattr(
                self.engine.config, "seq_size", 1) or 1)),
            "live_sequences": len(self.engine.state.sequences),
            "queue_frac": round(self.queue_frac(), 4),
            "free_blocks": self.engine.kv_cache.free_blocks,
            "draining": bool(self.engine.draining),
        }


class _FleetStateView:
    """The pool's ``.state`` facade — just enough of ``StateManager``'s
    read surface (``sequences``, ``get``) for single-engine drivers
    (the loadgen, the drills) to run against the fleet unchanged."""

    __slots__ = ("_pool",)

    def __init__(self, pool: "ReplicaPool"):
        self._pool = pool

    @property
    def sequences(self) -> Dict[int, Any]:
        out: Dict[int, Any] = {}
        for rep in self._pool.replicas():
            if rep.state != REPLICA_DEAD:
                out.update(rep.engine.state.sequences)
        return out

    def get(self, uid: int):
        rep = self._pool.owner_of(uid)
        return rep.engine.state.get(uid) if rep is not None else None


class ReplicaPool:
    """N engine replicas behind one router (module docstring has the
    architecture; docs/serving.md "Replica pool" the protocol)."""

    def __init__(self, engines: Sequence[Any] = (),
                 policy: Optional[str] = None,
                 seed: Optional[int] = None,
                 slo_ttft_s: Optional[float] = None,
                 ledger: Any = None, name: str = "fleet",
                 replica_ids: Optional[Sequence[str]] = None,
                 roles: Optional[Sequence[str]] = None,
                 role_mesh: Optional[Dict[str, int]] = None):
        # env knobs read with LITERAL names (dslint DSL004/5 scan):
        # DSTPU_FLEET_POLICY is the operational routing kill-switch
        # (prefix_aware -> round_robin/random without a rebuild),
        # DSTPU_FLEET_SEED pins tie-break reproducibility,
        # DSTPU_FLEET_SLO_TTFT_S arms the router's headroom term,
        # DSTPU_FLEET_ROLES assigns per-replica phase specialisms
        # (comma list, e.g. "prefill,decode" — docs/serving.md
        # "Disaggregated serving"), DSTPU_DISAGG=0 is the kill switch
        # that forces every replica mixed (the exact pre-disagg pool
        # path: no phase filter, no migration)
        if policy is None:
            policy = os.environ.get("DSTPU_FLEET_POLICY") \
                or "prefix_aware"
        if seed is None:
            seed = int(os.environ.get("DSTPU_FLEET_SEED") or "0")
        if slo_ttft_s is None:
            slo_ttft_s = float(
                os.environ.get("DSTPU_FLEET_SLO_TTFT_S") or "0")
        if roles is None:
            rv = os.environ.get("DSTPU_FLEET_ROLES")
            if rv:
                roles = [r.strip() for r in rv.split(",")]
        # per-role mesh shapes (docs/serving.md "Long-context serving"):
        # DSTPU_FLEET_ROLE_MESH = "prefill=2,decode=1" gives each ROLE its
        # seq-parallel width — prefill specialists take a wide seq axis
        # for context-parallel prefill, decode ones stay narrow. Advisory
        # to engine builders (build_replica_engines hands out matching
        # device slices); the pool validates and publishes it.
        if role_mesh is None:
            rmv = os.environ.get("DSTPU_FLEET_ROLE_MESH")
            if rmv:
                role_mesh = {}
                for part in rmv.split(","):
                    rname, _, width = part.partition("=")
                    role_mesh[rname.strip()] = int(width)
        self.role_mesh: Dict[str, int] = dict(role_mesh or {})
        for rname, width in self.role_mesh.items():
            if rname not in REPLICA_ROLES:
                raise ValueError(
                    f"role_mesh role must be one of {REPLICA_ROLES}, "
                    f"got {rname!r}")
            if width < 1:
                raise ValueError(
                    f"role_mesh width for {rname!r} must be >= 1, "
                    f"got {width}")
        self._disagg = os.environ.get("DSTPU_DISAGG", "1") != "0"
        if not self._disagg:
            roles = None
        self.name = name
        self.router = Router(policy=policy, seed=seed,
                             slo_ttft_s=slo_ttft_s)
        self._replicas: Dict[str, Replica] = {}
        self._owner: Dict[int, str] = {}          # uid -> replica id
        #: replay tokens a drained replica's sequences earned on their
        #: new survivor before the caller's next decode call — spliced
        #: into that call's result so caller streams stay gapless
        self._replayed: Dict[int, List[int]] = {}
        #: drain manifests still owed a survivor (every replica died
        #: before a replay target existed) — replayed as soon as a
        #: joiner registers; until then fresh work gets the structured
        #: no_serving_replica rejection, never a crash
        self._orphans: List[Dict[str, Any]] = []
        #: pool-level structured rejections (no serving replica); the
        #: engines' own rejection records merge in via :attr:`rejections`
        self._pool_rejections: Dict[int, Dict[str, Any]] = {}
        self._executor = None        # lazy per-replica worker threads
        self._exec_lock = threading.Lock()
        #: serializes :meth:`absorb_draining` across concurrent driver
        #: threads — exactly one caller runs the drain→replay sweep;
        #: the loser sees the flags already cleared and returns
        self._absorb_lock = threading.Lock()
        #: guards the shared routing maps (_owner, _replayed,
        #: _trace_ids/_trace_n, _pool_rejections) — mutated from the
        #: admission path (put), the absorb sweep and the decode driver
        #: concurrently (dslint DSL007). Leaf lock by construction:
        #: critical sections are dict/list splices only, NEVER an engine
        #: call or another lock acquisition, so the only nesting is
        #: _absorb_lock -> _route_lock (one direction, no inversion).
        self._route_lock = threading.Lock()
        #: fleet-wide trace contexts (docs/observability.md "Distributed
        #: tracing"): uid -> the trace id minted at admission. A monotone
        #: counter disambiguates uid reuse, so a retried uid starts a
        #: FRESH logical track instead of splicing onto the old one.
        self._trace_ids: Dict[int, str] = {}
        self._trace_n = 0
        #: the pool's own flight ring — routing-decision spans
        #: (``req_route`` with the per-replica scores) land here, on the
        #: same clock discipline as the engines' rings, so a merged
        #: fleet trace shows WHY a request went where it went. None when
        #: telemetry is off (zero overhead, like the engines).
        self.flight: Optional[FlightRecorder] = None
        if telemetry_enabled():
            self.flight = FlightRecorder()
            register_recorder(self.flight)
        self.state = _FleetStateView(self)
        if ledger is None and os.environ.get("DSTPU_RESTART_LEDGER"):
            from ..resilience.ledger import RestartLedger
            ledger = RestartLedger(os.environ["DSTPU_RESTART_LEDGER"])
        self._ledger = ledger
        ids = list(replica_ids) if replica_ids is not None else [
            f"r{i}" for i in range(len(engines))]
        if len(ids) != len(engines):
            raise ValueError(
                f"{len(ids)} replica_ids for {len(engines)} engines")
        rls = list(roles) if roles is not None \
            else ["mixed"] * len(engines)
        if len(rls) != len(engines):
            raise ValueError(
                f"{len(rls)} roles for {len(engines)} engines")
        for rid, eng, role in zip(ids, engines, rls):
            self.add_replica(eng, replica_id=rid, role=role)

    # ------------------------------------------------------------------ #
    # membership
    # ------------------------------------------------------------------ #

    def replicas(self) -> List[Replica]:
        """Members in join order (the router's candidate order)."""
        return list(self._replicas.values())

    def replica(self, replica_id: str) -> Replica:
        return self._replicas[replica_id]

    def owner_of(self, uid: int) -> Optional[Replica]:
        rid = self._owner.get(uid)
        return self._replicas.get(rid) if rid is not None else None

    @property
    def serving_count(self) -> int:
        return sum(1 for r in self._replicas.values() if r.available)

    @property
    def _phase_routing(self) -> bool:
        """Disaggregated placement is live: the kill switch is on AND at
        least one member declares a specialism. An all-``mixed`` fleet
        (or ``DSTPU_DISAGG=0``) short-circuits to the exact pre-disagg
        path — no phase filter, no post-put migration."""
        return self._disagg and any(
            r.role != "mixed" for r in self._replicas.values())

    def add_replica(self, engine, replica_id: Optional[str] = None,
                    role: str = "mixed") -> Replica:
        """Register a (late-)joining replica: it becomes a routing
        candidate immediately — a fresh joiner has an empty queue, so
        the score's load term starts steering traffic its way on the
        very next placement. ``role`` declares a phase specialism
        (docs/serving.md "Disaggregated serving"); with
        ``DSTPU_DISAGG=0`` it is forced to ``mixed`` so the pool runs
        the exact pre-disagg path."""
        if replica_id is None:
            replica_id = f"r{len(self._replicas)}"
        if replica_id in self._replicas:
            raise ValueError(f"replica id {replica_id!r} already joined")
        if not self._disagg:
            role = "mixed"
        rep = Replica(replica_id, engine, role=role)
        self._replicas[replica_id] = rep
        if self._ledger is not None:
            self._ledger.record("fleet_join", replica=replica_id,
                                pool=self.name, role=role,
                                serving=self.serving_count)
        return rep

    def drain_replica(self, replica_id: str,
                      path: Optional[str] = None) -> Dict[str, Any]:
        """Cooperatively drain one replica through the PR 7 protocol:
        its live sequences land in a replay manifest, ALL its engine
        state is released (``manifest["pool"]["fully_recovered"]`` is
        the exactness verdict), and the replica leaves the routing set
        for good. Idempotent on an already-dead replica (returns its
        manifest). Does NOT replay — pair with
        :meth:`replay_manifest`, or let :meth:`absorb_draining` do both."""
        rep = self._replicas[replica_id]
        if rep.state == REPLICA_DEAD:
            return rep.manifest or {}
        rep.state = REPLICA_DRAINING
        with rep.lock:
            rep.engine.request_drain()
            manifest = rep.engine.drain(path)
        rep.manifest = manifest
        rep.state = REPLICA_DEAD
        if self._ledger is not None:
            self._ledger.record(
                "fleet_drain", replica=replica_id, pool=self.name,
                sequences=len(manifest.get("sequences", ())),
                fully_recovered=manifest.get("pool", {}).get(
                    "fully_recovered"),
                survivors=self.serving_count)
        return manifest

    def replay_manifest(self, manifest: Dict[str, Any]
                        ) -> Dict[int, Any]:
        """Route a dead replica's manifested sequences onto survivors —
        each sequence is placed by the router scoring its FULL chain
        (prompt + generated), so on shared-prefix workloads the replica
        already holding the preamble's blocks wins and the re-prefill is
        mostly cache hits. Returns {uid: next committed greedy token}
        (the same continuation the dead replica would have emitted —
        replay parity is PR 7's oracle). Raises
        :class:`NoServingReplicaError` with no survivors."""
        recs = manifest.get("sequences", [])
        if not recs:
            return {}
        groups: Dict[str, List[Dict[str, Any]]] = {}
        try:
            for rec in recs:
                chain = list(rec["prompt"]) + list(rec["generated"])
                # the re-placement is itself a traced routing decision:
                # the request's track shows the drain-time hop and the
                # scores that picked its survivor
                # dslint: allow(DSL007): manifest uid is a host int
                # from the drain JSON — no device handle in reach, the
                # coercion cannot sync under _absorb_lock
                rep = self._route(int(rec["uid"]), chain,
                                  replay_rec=rec)
                rep.pending_routed += 1
                groups.setdefault(rep.replica_id, []).append(rec)
        finally:
            for rep in self._replicas.values():
                rep.pending_routed = 0
        out: Dict[int, Any] = {}
        for rid, rs in groups.items():
            rep = self._replicas[rid]
            sub = {"version": manifest.get("version", 1),
                   "source": "fleet_replay", "sequences": rs}
            with rep.lock:
                res = rep.engine.replay(sub)
            for rec in rs:
                # dslint: allow(DSL007): manifest uid is a host int
                # from the drain JSON — no device handle in reach, the
                # coercion cannot sync under _absorb_lock
                uid = int(rec["uid"])
                with self._route_lock:
                    self._owner[uid] = rid
                if uid in res:
                    out[uid] = res[uid]
        if self._ledger is not None:
            self._ledger.record(
                "fleet_replay", pool=self.name, sequences=len(recs),
                placement={rid: len(rs) for rid, rs in groups.items()})
        return out

    def absorb_draining(self) -> None:
        """Drain-and-replay every replica whose engine has flipped its
        drain flag (SIGTERM between engine calls): survivors absorb the
        manifested sequences, and the replay tokens are stashed for the
        caller's next :meth:`decode_pipelined`, which splices them into
        its result. With NO survivor the manifests wait as orphans —
        published to disk by the drain as usual — and replay onto the
        first joiner. Called automatically at every pool entry point;
        cheap (one flag read per replica) when nothing is draining.
        Serialized pool-wide (``_absorb_lock``) so concurrent driver
        threads cannot double-drain one victim."""
        with self._absorb_lock:
            for rep in list(self._replicas.values()):
                if rep.state == REPLICA_SERVING and rep.engine.draining:
                    self._orphans.append(
                        self.drain_replica(rep.replica_id))
            if not self._orphans \
                    or not any(r.available
                               for r in self._replicas.values()):
                return
            orphans, self._orphans = self._orphans, []
            for manifest in orphans:
                for uid, tok in self.replay_manifest(manifest).items():
                    self._stash_replay(uid, tok)

    # ------------------------------------------------------------------ #
    # request tracing (docs/observability.md "Distributed tracing")
    # ------------------------------------------------------------------ #

    def _mint_trace(self, uid: int) -> str:
        """Mint the fleet-wide trace context for one admitted request —
        the id every lifecycle span (router decision, replica execution,
        spec rounds, drain→replay continuation) carries so a merged
        multi-replica flight dump reconstructs one gapless track per
        request. Registered DSL001 hot path: a counter and two dict
        stores."""
        with self._route_lock:
            self._trace_n += 1
            tid = f"{self.name}/{uid}#{self._trace_n}"
            self._trace_ids[uid] = tid
        return tid

    def _route(self, uid: int, toks: Sequence[int],
               replay_rec: Optional[Dict[str, Any]] = None,
               phase: Optional[str] = None):
        """One routing decision, traced: select a replica and — with
        telemetry on — record the ``req_route`` decision span carrying
        the per-replica scores the router saw, tagged with the request's
        trace context (minted here for fresh requests; a replayed or
        handed-off sequence keeps the trace its record carried).
        ``phase`` applies the router's role filter (disaggregated
        serving — fresh work to prefill-capable replicas, migrations to
        decode-capable ones). Registered DSL001 hot path — pure host
        scoring plus one ring append."""
        if self.flight is None:
            return self.router.select(self.replicas(), toks,
                                      phase=phase)
        ex: Dict[str, Any] = {}
        t0 = time.perf_counter()
        rep = self.router.select(self.replicas(), toks, explain=ex,
                                 phase=phase)
        if replay_rec is not None:
            trace = replay_rec.get("trace")
            if trace is not None:
                with self._route_lock:
                    self._trace_ids[uid] = trace
            ex["handoff" if phase == "decode" else "replay"] = True
        else:
            trace = self._mint_trace(uid)
        args = {"uid": uid, **ex}
        if trace is not None:
            args["trace"] = trace
        self.flight.record("req_route", t0, time.perf_counter(),
                           args=args)
        return rep

    def dump_merged_trace(self, path: str) -> Optional[str]:
        """Merge the pool's routing spans with EVERY member's engine
        flight ring — dead replicas included: their pre-drain spans are
        the first half of a drained request's track — into one fleet
        Chrome trace (:func:`~..telemetry.flight_recorder.
        merge_chrome_traces` namespaces tracks by source and stitches
        trace-context spans), atomically published at ``path``. None
        when telemetry is off."""
        if self.flight is None:
            return None
        dumps = [self.flight.to_chrome_trace(reason="fleet")]
        srcs = [f"{self.name}.router"]
        for rid, rep in self._replicas.items():
            fl = rep.engine.flight
            if fl is not None:
                dumps.append(fl.to_chrome_trace(reason="fleet"))
                srcs.append(rid)
        atomic_json_dump(path, merge_chrome_traces(dumps, srcs))
        return path

    # ------------------------------------------------------------------ #
    # the engine-shaped serving surface (DSL001-registered hot paths)
    # ------------------------------------------------------------------ #

    def put(self, batch_uids: Sequence[int],
            batch_tokens: Sequence[Sequence[int]],
            _greedy: bool = False,
            arrivals: Optional[Dict[int, float]] = None,
            deadlines: Optional[Dict[int, float]] = None,
            sampling: Optional[Dict[int, Any]] = None
            ) -> Dict[int, Any]:
        """Fleet admission. Placement is SEQUENTIAL per request (pure
        host scoring — each decision sees the queue/ownership state the
        previous one created), then the routed per-replica prompt
        batches PREFILL CONCURRENTLY, one worker thread per replica,
        exactly like the decode rounds — admission wall time stays that
        of the busiest replica, not the sum. Continuations go to their
        owner. Returns the merged {uid: result} map; refusals surface
        through :attr:`rejections` exactly like a single engine's.
        ``sampling`` ({uid: SamplingParams}) passes through to each
        owning engine unchanged-shape — per-request sampling and
        speculative decode work identically behind the fleet surface."""
        self.absorb_draining()
        done: Dict[int, Any] = {}
        groups: Dict[str, List[int]] = {}
        fresh: Dict[str, List[int]] = {}
        toks_of: Dict[int, Sequence[int]] = {}
        # disaggregated placement (docs/serving.md): fresh requests go
        # to prefill-capable replicas; after the batch prefills, the
        # migration step below moves each sequence that landed on a
        # prefill SPECIALIST onto a decode-capable replica, invisibly
        # to the caller (results are computed before the move)
        phase = "prefill" if self._phase_routing else None
        try:
            for uid, toks in zip(batch_uids, batch_tokens):
                rep = self.owner_of(uid)
                live = rep is not None \
                    and rep.engine.state.get(uid) is not None
                if not live:
                    # fresh request (or a reused/stale uid): route it.
                    # A LIVE continuation stays with its owner even
                    # mid-drain — the sequence rides that replica's
                    # manifest; rerouting its tokens would re-admit
                    # them as a bogus new prompt elsewhere
                    try:
                        rep = self._route(uid, toks, phase=phase)
                    except NoServingReplicaError:
                        self._reject(uid, "no_serving_replica")
                        continue
                    with self._route_lock:
                        self._owner[uid] = rep.replica_id
                    rep.pending_routed += 1
                    fresh.setdefault(rep.replica_id, []).append(uid)
                    # a uid retried after an earlier refusal sheds its
                    # stale records EVERYWHERE — a present record must
                    # only ever mean THIS admission failed. The engine
                    # clears only its own on re-admission, but a retry
                    # may land on a different replica while the old
                    # record (possibly on a now-dead replica) would
                    # keep polluting the merged :attr:`rejections` view
                    with self._route_lock:
                        self._pool_rejections.pop(uid, None)
                    for other in self._replicas.values():
                        other.engine.rejections.pop(uid, None)
                groups.setdefault(rep.replica_id, []).append(uid)
                toks_of[uid] = toks
        finally:
            for rep in self._replicas.values():
                rep.pending_routed = 0

        def run_one(rid: str) -> Dict[int, Any]:
            rep = self._replicas[rid]
            members = groups[rid]
            tr = {u: self._trace_ids[u] for u in members
                  if u in self._trace_ids}
            with rep.lock:
                return rep.engine.put(
                    members, [toks_of[u] for u in members],
                    _greedy=_greedy, arrivals=arrivals,
                    deadlines=deadlines, sampling=sampling,
                    traces=tr or None)

        results = self._run_groups(run_one, groups)
        for res in results:
            done.update(res)
        if phase is not None and fresh:
            self._migrate_prefill(fresh)
        return done

    def _run_groups(self, fn, groups: Dict[str, Any]) -> List[Any]:
        """Run ``fn(replica_id)`` for every routed group — concurrently
        on the pool's persistent per-replica worker threads when more
        than one replica is involved (each worker blocks only on ITS
        engine's device, GIL released, so replica device work overlaps);
        inline for a single group."""
        if len(groups) <= 1:
            return [fn(rid) for rid in groups]
        with self._exec_lock:
            # creation is serialized (concurrent driver threads must
            # not race two executors into existence); the map itself
            # runs unlocked — the workers serialize per replica on the
            # replica locks, which is the intended contention surface
            if self._executor is None \
                    or self._executor._max_workers < len(groups):
                from concurrent.futures import ThreadPoolExecutor
                if self._executor is not None:
                    self._executor.shutdown(wait=False)
                self._executor = ThreadPoolExecutor(
                    max_workers=max(len(groups), len(self._replicas)),
                    thread_name_prefix=f"{self.name}-replica")
            ex = self._executor
        return list(ex.map(fn, groups))

    def _migrate_prefill(self, fresh: Dict[str, List[int]]) -> None:
        """The disaggregated handoff splice (docs/serving.md
        "Disaggregated serving"): every sequence the admission batch
        landed on a PREFILL specialist migrates to a decode-capable
        replica before the caller's next decode call. The move is
        invisible — the caller's first tokens were computed before it,
        ownership flips underneath, and the destination continues the
        stream from the exact same KV content and committed token
        chain, so per-uid streams stay byte-identical to colocated
        serving.

        Shape of the move: the source's :meth:`handoff_out` dispatches
        one non-blocking exact-length gather per sequence and releases
        its state; each record's destination is a traced routing
        decision (``phase="decode"`` — prefix affinity and load still
        score the candidates); ALL payloads then materialize in ONE
        batched ``jax.device_get`` whose wall is the handoff's EXPOSED
        transfer cost (the gathers themselves overlapped the batch's
        remaining device work — observed into
        ``serve_handoff_exposed_s``); the destination's
        :meth:`handoff_in` scatters and adopts. Records the
        destination cannot cover (block pressure) or that a dying
        destination refuses fall back to drain-style replay from the
        SAME records — token-identical, just paying a re-prefill
        (counted in ``serve_handoff_fallback_replays``). Each adopted
        sequence's ``req_handoff`` span lands on the pool ring tagged
        with its trace context, joining the prefill- and decode-side
        lanes in the merged fleet trace. Registered DSL001 hot path —
        dispatch plus the one materialize wait."""
        t0 = time.perf_counter()
        routed: Dict[str, List[Dict[str, Any]]] = {}
        src_of: Dict[int, str] = {}
        fallback: List[Dict[str, Any]] = []
        for rid, uids in fresh.items():
            src = self._replicas[rid]
            if src.role != "prefill" or src.state != REPLICA_SERVING:
                continue
            live = [u for u in uids
                    if src.engine.state.get(u) is not None]
            if not live:
                continue
            try:
                with src.lock:
                    manifest = src.engine.handoff_out(live)
            except Exception:
                # a fault mid-gather (the during_handoff_gather drill
                # site, or a SIGTERM unwinding the source) aborts the
                # whole handoff BEFORE any source state was released:
                # every sequence is still live on the prefill replica —
                # it decodes colocated, or rides the source's drain
                # manifest onto a survivor token-identically
                continue
            for rec in manifest.get("sequences", ()):
                # dslint: allow(DSL001): manifest uid is a host int
                uid = int(rec["uid"])
                src_of[uid] = rid
                chain = list(rec["prompt"]) + list(rec["generated"])
                dst = self._route(uid, chain, replay_rec=rec,
                                  phase="decode")
                routed.setdefault(dst.replica_id, []).append(rec)
        if not routed:
            return
        import jax
        recs_flat = [r for rs in routed.values() for r in rs]
        tg = time.perf_counter()
        # the ONE sanctioned blocking materialize of the handoff: every
        # destination's payloads in a single batched transfer, timed as
        # the migration's exposed cost (serve_handoff_exposed_s)
        # dslint: allow(DSL001): the handoff's one batched materialize
        host = jax.device_get([r["kv"] for r in recs_flat])
        exposed_s = time.perf_counter() - tg
        for r, h in zip(recs_flat, host):
            r["kv"] = h
        observed = False
        for rid, rs in routed.items():
            dst = self._replicas[rid]
            try:
                with dst.lock:
                    res = dst.engine.handoff_in(
                        {"version": 1, "source": "handoff",
                         "sequences": rs},
                        # the one batched materialize covered EVERY
                        # destination's payloads: observe its wall once
                        exposed_s=0.0 if observed else exposed_s)
            except EngineDrainingError:
                # destination flipped draining between the routing
                # decision and the adopt (refused BEFORE any state
                # change): replay these records on a survivor
                fallback.extend(rs)
                continue
            observed = True
            acc = set(res["accepted"])
            t1 = time.perf_counter()
            for rec in rs:
                # dslint: allow(DSL001): manifest uid is a host int
                uid = int(rec["uid"])
                if uid not in acc:
                    fallback.append(rec)
                    continue
                with self._route_lock:
                    self._owner[uid] = rid
                if self.flight is not None:
                    args: Dict[str, Any] = {
                        "uid": uid, "src": src_of.get(uid), "dst": rid,
                        "blocks": rec.get("blocks"),
                        "exposed_s": round(exposed_s, 6)}
                    if rec.get("trace") is not None:
                        args["trace"] = rec["trace"]
                    self.flight.record("req_handoff", t0, t1,
                                       args=args)
        if fallback:
            for rec in fallback:
                rec.pop("kv", None)     # replay needs only the chain
            replayed = self.replay_manifest(
                {"version": 1, "sequences": fallback})
            for uid, tok in replayed.items():
                self._stash_replay(uid, tok)
                rep = self.owner_of(uid)
                if rep is not None and rep.engine._obs is not None:
                    rep.engine._obs.on_handoff_replay(1)

    def decode_pipelined(self, batch_uids: Sequence[int],
                         first_tokens: Sequence[int], n,
                         eos_token_id: Optional[int] = None
                         ) -> Dict[int, List[int]]:
        """One fleet decode round: group uids by owning replica and run
        every replica's overlapped ``decode_pipelined`` batch
        CONCURRENTLY — one worker thread per replica, because that is
        what replicas over disjoint device sets are: each thread blocks
        only on ITS engine's commit readbacks (releasing the GIL), so
        the replicas' device work overlaps instead of serializing
        behind one host loop, and fleet throughput scales with replica
        count on the in-process path too. Engines share no mutable
        state (each owns its pool, scheduler and staging buffers), and
        per-engine token streams stay deterministic — thread
        interleaving can reorder nothing inside one engine.

        A replica SIGTERMed before or during the round is absorbed
        (drain → survivor replay) and the replay tokens are spliced
        into this round's result — the caller's per-uid stream stays
        gapless and token-identical through the membership change."""
        self.absorb_draining()
        if isinstance(n, (list, tuple)):
            budgets = {u: b for u, b in zip(batch_uids, n)}
        else:
            budgets = {u: n for u in batch_uids}
        out: Dict[int, List[int]] = {u: [] for u in batch_uids}
        rem: Dict[int, int] = {}
        last: Dict[int, int] = {}
        for u, t in zip(batch_uids, first_tokens):
            took = self._take_stash(u, budgets[u], out)
            rem[u] = budgets[u] - took
            last[u] = out[u][-1] if out[u] else t
        groups: Dict[str, List[int]] = {}
        for u in batch_uids:
            if rem[u] <= 0:
                continue
            rep = self.owner_of(u)
            if rep is None or not rep.available:
                continue              # absorbed: the stash carries it
            groups.setdefault(rep.replica_id, []).append(u)

        def run_one(rid: str) -> Dict[int, List[int]]:
            with self._replicas[rid].lock:
                return run_locked(rid)

        def run_locked(rid: str) -> Dict[int, List[int]]:
            eng = self._replicas[rid].engine
            members = groups[rid]
            if getattr(eng, "spec_enabled", False) or any(
                    (s := eng.state.get(u)) is not None
                    and s.sampling is not None
                    and not s.sampling.greedy for u in members):
                # speculative / sampled members ride decode_pipelined,
                # which routes to decode_spec (greedy batches) or the
                # per-slot sampler pipeline — both budget-exact
                return eng.decode_pipelined(
                    members, [last[u] for u in members],
                    [rem[u] for u in members],
                    eos_token_id=eos_token_id)
            if eos_token_id is None and hasattr(eng.runner,
                                               "decode_loop"):
                # fused fleet decode: bucket the replica's batch by
                # budget and run ONE device program per bucket
                # (token-identical to the per-step path — PR 3's
                # parity oracle). Host python per token drops to ~one
                # dispatch per burst, so N replicas' decode rounds
                # genuinely overlap instead of contending for the
                # interpreter; block-pressure falls back to the
                # incremental pipelined path, which can shed.
                res: Dict[int, List[int]] = {}
                by_budget: Dict[int, List[int]] = {}
                for u in members:
                    by_budget.setdefault(rem[u], []).append(u)
                for b, us in by_budget.items():
                    if len(us) <= eng.config.max_seqs:
                        try:
                            res.update(eng.decode_batch(
                                us, [last[u] for u in us], b))
                            continue
                        except (OutOfBlocksError, ValueError):
                            # pool pressure / paused member / oversized
                            # batch: the incremental path paces it
                            pass
                    res.update(eng.decode_pipelined(
                        us, [last[u] for u in us], b))
                return res
            return eng.decode_pipelined(
                members, [last[u] for u in members],
                [rem[u] for u in members], eos_token_id=eos_token_id)

        results = self._run_groups(run_one, groups)
        for rid, res in zip(groups, results):
            for u in groups[rid]:
                got = res.get(u) or []
                out[u].extend(got)
                rem[u] -= len(got)
        # a SIGTERM mid-round: the victim unwound with partial output —
        # absorb now so its replay tokens land in THIS result (budget
        # permitting; the rest waits in the stash)
        self.absorb_draining()
        for u in batch_uids:
            if rem[u] > 0:
                self._take_stash(u, rem[u], out)
        return out

    def _stash_replay(self, uid: int, tok: int) -> None:
        """Append one replayed token to the stash under ``_route_lock``
        — the absorb sweep and the handoff fallback both feed the stash
        while a decode driver may be splicing it out via
        :meth:`_take_stash`; an unlocked setdefault().append() here
        loses tokens to the pop/reinsert window (dslint DSL007)."""
        with self._route_lock:
            self._replayed.setdefault(uid, []).append(tok)

    def _take_stash(self, uid: int, budget: int,
                    out: Dict[int, List[int]]) -> int:
        """Move up to ``budget`` stashed replay tokens for ``uid`` into
        ``out``; leftovers stay stashed. Pure host list work; the whole
        pop/splice/reinsert is one ``_route_lock`` critical section so
        a concurrent :meth:`_stash_replay` cannot land between the pop
        and the reinsert and be lost."""
        with self._route_lock:
            stash = self._replayed.pop(uid, None)
            if not stash:
                return 0
            if budget <= 0:
                self._replayed[uid] = stash
                return 0
            take = stash[:budget]
            if stash[budget:]:
                self._replayed[uid] = stash[budget:]
        out[uid].extend(take)
        return len(take)

    def flush(self, uid: int) -> None:
        with self._route_lock:
            self._replayed.pop(uid, None)
            self._trace_ids.pop(uid, None)
            rid = self._owner.pop(uid, None)
        rep = self._replicas.get(rid) if rid is not None else None
        if rep is not None:
            with rep.lock:
                if rep.engine.state.get(uid) is not None:
                    rep.engine.flush(uid)

    def _reject(self, uid: int, reason: str, **fields) -> None:
        # same record shape as the engine's _reject — retry_after_s is
        # a first-class (if usually None) field so door rejections can
        # carry the admission controller's backoff hint and report
        # readers never need a reason-specific schema
        with self._route_lock:
            self._pool_rejections[uid] = {
                "uid": uid, "reason": reason, "time": time.time(),
                "retry_after_s": fields.pop("retry_after_s", None),
                **fields}

    @property
    def rejections(self) -> Dict[int, Dict[str, Any]]:
        """Merged structured-rejection view: pool-level refusals plus
        every replica's engine records (a uid lives on exactly one
        replica, so the union is collision-free)."""
        out = dict(self._pool_rejections)
        for rep in self._replicas.values():
            out.update(rep.engine.rejections)
        return out

    # ------------------------------------------------------------------ #
    # fleet telemetry rollup
    # ------------------------------------------------------------------ #

    def fleet_registry(self) -> Optional[MetricsRegistry]:
        """Merge live replicas' per-engine registries into one fleet
        registry: counters sum, gauges keep per-replica identity via
        ``source=<replica id>`` labels (STABLE — keyed by id, not
        insertion index, so re-rolling the same fleet is idempotent),
        histograms merge bucket-wise exactly. None when telemetry is
        off. The dead replicas' final stats live in their drain
        manifests (``manifest["telemetry"]``), not here."""
        regs: List[MetricsRegistry] = []
        srcs: List[str] = []
        for rid, rep in self._replicas.items():
            if rep.state == REPLICA_DEAD:
                continue
            m = rep.engine.metrics
            if m is not None:
                # pool/prefix gauges refresh on export boundaries; a
                # rollup must not read stale (or never-set) values
                rep.engine._obs.sync_gauges()
                regs.append(m)
                srcs.append(rid)
        if not regs:
            return None
        return MetricsRegistry.merge(regs, name=self.name, sources=srcs)

    def fleet_snapshot(self) -> Dict[str, Any]:
        """One merged, export-shaped snapshot of the whole pool (the
        in-process analogue of ``telemetry.merge_snapshots`` over
        per-process export files), plus per-replica membership detail
        and the router's dispatch stats."""
        reg = self.fleet_registry()
        snap: Dict[str, Any] = reg.snapshot() if reg is not None else {
            "counters": {}, "gauges": {}, "histograms": {}}
        snap["time"] = time.time()
        snap["registry"] = f"{self.name}({self.serving_count})"
        snap["replicas"] = {rid: rep.describe()
                            for rid, rep in self._replicas.items()}
        snap["router"] = self.router.describe()
        return snap

    def export(self, path: str) -> None:
        """Atomic fleet-snapshot publish (tmp + rename) — same torn-read
        discipline as ``MetricsRegistry.export``; ``bin/dstpu_top``
        renders the file like any single-engine export."""
        atomic_json_dump(path, self.fleet_snapshot())

    def slo_report(self) -> Dict[str, Any]:
        """Fleet-wide SLO summary in the same shape as a single
        engine's ``slo_report()`` — computed from the merged registry,
        so the percentiles are EXACTLY what one stream over every
        replica's requests would report ({} when telemetry is off)."""
        reg = self.fleet_registry()
        if reg is None:
            return {}
        return slo_report_from_registry(reg)


def fleet_prefix_stats(pool: ReplicaPool) -> Dict[str, Any]:
    """Summed host-side prefix-cache counters across live replicas plus
    the fleet-wide skipped-prefill fraction — the number the routing
    bench gates on (prefix-aware must beat random here)."""
    keys = ("matched_tokens", "prefill_tokens", "cow_tokens",
            "matched_blocks", "cow_copies")
    out: Dict[str, Any] = {k: 0 for k in keys}
    for rep in pool.replicas():
        if rep.state == REPLICA_DEAD:
            continue
        st = rep.engine.prefix_stats
        for k in keys:
            out[k] += st.get(k, 0)
    hit, ran = out["matched_tokens"], out["prefill_tokens"]
    out["prefill_chunks_skipped_frac"] = \
        hit / (hit + ran) if hit + ran else 0.0
    return out


def build_replica_engines(engine_factory, n: int,
                          devices: Optional[Sequence[Any]] = None,
                          devices_per_replica: Optional[
                              Sequence[int]] = None) -> List[Any]:
    """Build ``n`` engines for a pool, each pinned to its OWN JAX
    device (cycling ``devices``, default ``jax.devices()``): arrays the
    factory creates under the ``jax.default_device`` scope — params it
    ``device_put``s, the KV pool, the compiled programs' outputs — all
    land on that replica's device, so the replicas' steps execute
    concurrently instead of queueing on one device. This is the
    in-process realization of "N replicas over disjoint device sets":
    on the CPU harness the devices come from
    ``--xla_force_host_platform_device_count``, on real hardware from
    the ``data`` mesh axis. ``engine_factory(i, device)`` returns
    replica ``i``'s engine.

    ``devices_per_replica`` (one int per replica, e.g. derived from
    ``ReplicaPool.role_mesh``) hands replica ``i`` a DISJOINT slice of
    that many devices instead of a single cycled one — the long-context
    shape where a seq-parallel prefill specialist spans ``seq_size``
    chips while decode replicas keep one each. The factory then
    receives the device LIST (its engine builds the seq mesh from it);
    slices never overlap, so replicas still step concurrently."""
    import jax
    devs = list(devices) if devices is not None else jax.devices()
    engines = []
    if devices_per_replica is not None:
        if len(devices_per_replica) != n:
            raise ValueError(
                f"{len(devices_per_replica)} devices_per_replica "
                f"entries for {n} replicas")
        if sum(devices_per_replica) > len(devs):
            raise ValueError(
                f"devices_per_replica wants "
                f"{sum(devices_per_replica)} devices, only "
                f"{len(devs)} available — slices must be disjoint")
        off = 0
        for i, k in enumerate(devices_per_replica):
            sl = devs[off:off + k]
            off += k
            with jax.default_device(sl[0]):
                engines.append(engine_factory(i, sl if k > 1 else sl[0]))
        return engines
    for i in range(n):
        dev = devs[i % len(devs)]
        with jax.default_device(dev):
            engines.append(engine_factory(i, dev))
    return engines


def single_stream_oracle(values: Sequence[float],
                         alpha: float = 0.05) -> Histogram:
    """One histogram fed the union of ``values`` in a single stream —
    the oracle the fleet drill compares the merged rollup against
    (``Histogram.merge`` exactness means the two must agree bucket for
    bucket, hence quantile for quantile)."""
    h = Histogram(alpha=alpha)
    for v in values:
        h.observe(v)
    return h
