"""Serving fleet — replica pool, prefix-aware router, fleet rollup.

The layer above a single ``InferenceEngineV2`` (docs/serving.md
"Replica pool"): a :class:`ReplicaPool` owns N engine replicas over
disjoint device sets behind one engine-shaped surface, a
:class:`Router` places each request by cached-prefix overlap / queue
depth / SLO headroom (``random`` and ``round_robin`` as controls), and
elastic membership drains preempted replicas through the PR 7 manifest
onto survivors whose warm prefix caches absorb the re-prefill. Fleet
telemetry rolls up through the exact histogram merge with stable
``source=<replica id>`` labels.
"""

from .pool import (Replica, ReplicaPool, build_replica_engines,
                   fleet_prefix_stats, single_stream_oracle,
                   slo_report_from_registry)
from .router import ROUTING_POLICIES, NoServingReplicaError, Router

__all__ = [
    "NoServingReplicaError", "ROUTING_POLICIES", "Replica",
    "ReplicaPool", "Router", "build_replica_engines",
    "fleet_prefix_stats", "single_stream_oracle",
    "slo_report_from_registry",
]
