"""Serving fleet — replica pool, prefix-aware router, fleet rollup.

The layer above a single ``InferenceEngineV2`` (docs/serving.md
"Replica pool"): a :class:`ReplicaPool` owns N engine replicas over
disjoint device sets behind one engine-shaped surface, a
:class:`Router` places each request by cached-prefix overlap / queue
depth / SLO headroom (``random`` and ``round_robin`` as controls), and
elastic membership drains preempted replicas through the PR 7 manifest
onto survivors whose warm prefix caches absorb the re-prefill. Fleet
telemetry rolls up through the exact histogram merge with stable
``source=<replica id>`` labels.

Disaggregated serving (docs/serving.md "Disaggregated serving"):
replicas may declare a phase specialism (``REPLICA_ROLES`` —
``prefill`` / ``decode`` / ``mixed``, via ``ReplicaPool(roles=...)`` or
``DSTPU_FLEET_ROLES``). Fresh requests land on prefill-capable
replicas; after the first token each sequence on a prefill SPECIALIST
migrates to a decode-capable replica through a streamed KV handoff the
pool splices invisibly — caller token streams stay byte-identical to
colocated serving. ``DSTPU_DISAGG=0`` forces every replica ``mixed``
(the exact pre-disagg path).

Overload robustness (docs/serving.md "Overload control"): an
:class:`AdmissionController` holds offered load at the capacity knee —
AIMD over the door's admission window on windowed queue-wait p99
evidence — and degrades quality-of-service through the ordered
brownout ladder instead of collapsing. Build one through
:func:`build_admission` (None when ``DSTPU_ADMISSION=0``).
"""

from .admission import (BROWNOUT_LEVELS, AdmissionController,
                        admission_enabled, build_admission)
from .pool import (REPLICA_ROLES, Replica, ReplicaPool,
                   build_replica_engines, fleet_prefix_stats,
                   single_stream_oracle, slo_report_from_registry)
from .router import ROUTING_POLICIES, NoServingReplicaError, Router

__all__ = [
    "AdmissionController", "BROWNOUT_LEVELS", "NoServingReplicaError",
    "REPLICA_ROLES", "ROUTING_POLICIES", "Replica", "ReplicaPool",
    "Router",
    "admission_enabled", "build_admission", "build_replica_engines",
    "fleet_prefix_stats", "single_stream_oracle",
    "slo_report_from_registry",
]
