"""Quantized parameter storage (fp6/fp8/fp12).

Parity with the reference's ``deepspeed/linear/quantization.py``
``QuantizedParameter`` (a tensor subclass that stores fp-quantized bytes and
dequantizes on access, backed by ``csrc/fp_quantizer``): here a pytree node
holding minifloat codes + scales with an explicit ``dequantized()`` view;
XLA fuses the dequant into the consuming matmul.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..ops.fp_quantizer import (
    FPQuantizedTensor, fp_dequantize, fp_quantize)
from .config import QuantizationConfig


class QuantizedParameter:
    """Frozen quantized parameter: quantize once, dequantize per use."""

    def __init__(self, data: jnp.ndarray,
                 quantization_config: Optional[QuantizationConfig] = None):
        cfg = quantization_config or QuantizationConfig()
        self.quantization_config = cfg
        self._qt: FPQuantizedTensor = fp_quantize(
            data, q_bits=cfg.q_bits, group_size=cfg.group_size)
        self.shape = tuple(data.shape)
        self.dtype = data.dtype

    def dequantized(self, dtype=None) -> jnp.ndarray:
        return fp_dequantize(self._qt, dtype or self.dtype)

    @property
    def quantized(self) -> FPQuantizedTensor:
        return self._qt

    def nbytes(self) -> int:
        """Actual storage: bit-packed codes + f32 group scales."""
        return int(self._qt.codes.size * self._qt.codes.dtype.itemsize +
                   self._qt.scale.size * 4)


def quantize_param(data: jnp.ndarray, q_bits: int = 8,
                   group_size: int = 512) -> QuantizedParameter:
    return QuantizedParameter(
        data, QuantizationConfig(q_bits=q_bits, group_size=group_size))
