from .config import LoRAConfig, QuantizationConfig
from .optimized_linear import OptimizedLinear, QuantizedLinear
from .quantization import QuantizedParameter, quantize_param
