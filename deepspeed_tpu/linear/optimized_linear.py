"""OptimizedLinear — LoRA over a frozen (optionally quantized, optionally
sharded) base weight.

Parity with the reference's ``deepspeed/linear/optimized_linear.py``
(``OptimizedLinear`` dispatching to ``LoRAOptimizedLinear`` /
``QuantizedLinear`` by config): a flax module computing

    y = x @ W_base + (x @ A) @ B * (alpha / r)

W_base is created frozen (no gradient: ``stop_gradient``), stored
fp-quantized when a ``QuantizationConfig`` is given, and annotated with a
``data``-axis sharding when ``base_weight_sharding > 1`` (the reference
chunks the base weight across the DP world; here the SPMD partitioner owns
the shards). Only the LoRA factors train — exactly the reference's
memory/comm profile.

Functional helpers for non-flax pytrees:
  ``lora_init(key, in_dim, out_dim, cfg)`` / ``lora_apply(x, base, a, b, cfg)``
  ``fuse_lora(base, a, b, cfg)`` / ``unfuse_lora(fused, a, b, cfg)``
(the fuse/unfuse pair is what the hybrid RLHF engine uses per generate
phase, reference ``runtime/hybrid_engine.py:132-153``).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from .config import LoRAConfig, QuantizationConfig
from ..ops.fp_quantizer import fp_quant_dequant


def lora_init(key, in_dim: int, out_dim: int, cfg: LoRAConfig):
    """(A, B) factors: A ~ He-uniform fan-in, B zeros (standard LoRA)."""
    ka, _ = jax.random.split(key)
    a = jax.random.uniform(ka, (in_dim, cfg.lora_r), jnp.float32,
                           -1.0, 1.0) / jnp.sqrt(in_dim)
    b = jnp.zeros((cfg.lora_r, out_dim), jnp.float32)
    return a, b


def lora_apply(x, base_w, a, b, cfg: LoRAConfig):
    """y = x@W (frozen) + scaled LoRA path."""
    y = x @ jax.lax.stop_gradient(base_w)
    return y + (x @ a) @ b * (cfg.lora_alpha / cfg.lora_r)


def fuse_lora(base_w, a, b, cfg: LoRAConfig):
    return base_w + (a @ b) * (cfg.lora_alpha / cfg.lora_r)


def unfuse_lora(fused_w, a, b, cfg: LoRAConfig):
    return fused_w - (a @ b) * (cfg.lora_alpha / cfg.lora_r)


class OptimizedLinear(nn.Module):
    """Drop-in linear with LoRA and/or fp-quantized frozen base weight."""

    features: int
    lora_config: Optional[LoRAConfig] = None
    quantization_config: Optional[QuantizationConfig] = None
    use_bias: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_dim = x.shape[-1]
        base = self.param("base_weight", nn.initializers.xavier_uniform(),
                          (in_dim, self.features), jnp.float32)
        # the base weight is frozen in EVERY configuration (and quantization
        # rounding would produce garbage gradients anyway)
        base = jax.lax.stop_gradient(base)
        if self.lora_config is not None and \
                self.lora_config.base_weight_sharding > 1:
            from ..parallel.topology import has_topology, get_topology
            if has_topology():
                base = jax.lax.with_sharding_constraint(
                    base, jax.sharding.NamedSharding(
                        get_topology().mesh,
                        jax.sharding.PartitionSpec("data", None)))
        if self.quantization_config is not None:
            # fake-quant view of the frozen base (storage-level quantization
            # is QuantizedParameter; in-module we keep jit-friendliness)
            base = fp_quant_dequant(
                base, q_bits=self.quantization_config.q_bits,
                group_size=self.quantization_config.group_size)

        if self.lora_config is None:
            y = x @ base.astype(self.dtype)
        else:
            cfg = self.lora_config
            a = self.param("lora_a",
                           lambda k, s: lora_init(k, in_dim, self.features,
                                                  cfg)[0], None)
            b = self.param("lora_b",
                           lambda k, s: lora_init(k, in_dim, self.features,
                                                  cfg)[1], None)
            y = lora_apply(x.astype(self.dtype), base.astype(self.dtype),
                           a.astype(self.dtype), b.astype(self.dtype), cfg)
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros,
                               (self.features,), jnp.float32).astype(self.dtype)
        return y


class QuantizedLinear(OptimizedLinear):
    """Quantization-only variant (reference QuantizedLinear)."""

    def __post_init__(self):
        if self.quantization_config is None:
            object.__setattr__(self, "quantization_config",
                               QuantizationConfig())
        super().__post_init__()
