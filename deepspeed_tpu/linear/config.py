"""Configs for OptimizedLinear — parity with reference ``deepspeed/linear/
config.py`` (LoRAConfig, QuantizationConfig)."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class LoRAConfig:
    """LoRA + base-weight-sharding settings.

    ``base_weight_sharding`` shards the frozen base weight over the ``data``
    mesh axis (the reference shards over the DP world the same way); the
    sharding is expressed as a NamedSharding on the param, so ZeRO-style
    memory savings come from the partitioner rather than manual chunking.
    """
    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1
    offload: bool = False
    offload_ratio: float = 0.0
    delay_lora_init: bool = False
    target_mods: tuple = ("attn", "mlp")


@dataclasses.dataclass
class QuantizationConfig:
    """Minifloat quantization settings (fp6/fp8/fp12 via ops/fp_quantizer)."""
    q_bits: int = 8
    mantissa_bits: int = 3
    group_size: int = 512
