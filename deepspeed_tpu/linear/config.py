"""Configs for OptimizedLinear — parity with reference ``deepspeed/linear/
config.py`` (LoRAConfig, QuantizationConfig)."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class LoRAConfig:
    """LoRA + base-weight-sharding settings.

    ``base_weight_sharding`` shards the frozen base weight over the ``data``
    mesh axis (the reference shards over the DP world the same way); the
    sharding is expressed as a NamedSharding on the param, so ZeRO-style
    memory savings come from the partitioner rather than manual chunking.
    """
    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1
    offload: bool = False
    offload_ratio: float = 0.0
    delay_lora_init: bool = False
    target_mods: tuple = ("attn", "mlp")


@dataclasses.dataclass
class QuantizationConfig:
    """Minifloat quantization settings (fp6/fp8/fp12 via ops/fp_quantizer).

    ``mantissa_bits`` is accepted for reference key parity but the
    exponent/mantissa split is fixed per q_bits (6=e3m2, 8=e4m3, 12=e4m7 —
    the reference's fp_quantizer formats); a mismatching value raises."""
    q_bits: int = 8
    mantissa_bits: int = 3
    group_size: int = 512

    def __post_init__(self):
        from ..ops.fp_quantizer import FORMATS
        if self.q_bits in FORMATS:
            _, man = FORMATS[self.q_bits]
            if self.mantissa_bits not in (man, 3):   # 3 is the ds default
                raise ValueError(
                    f"q_bits={self.q_bits} implies mantissa_bits={man} "
                    f"(got {self.mantissa_bits})")
