"""NVMe aio performance sweep — ``ds_nvme_tune`` / ``ds_io`` parity.

The reference's ``deepspeed/nvme/`` sweeps aio knobs (block size, queue
depth, thread count, submit mode) over benchmark reads/writes and reports
the best config for the swap layer. Same here, over the native thread-pool
library (``csrc/aio/ds_aio.cpp``): each candidate writes+reads a test file
through an ``AioHandle`` and the winner is written as the recommended
``aio`` config block.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..io.aio import AioHandle
from ..utils.logging import log_dist

DEFAULT_BLOCK_SIZES = [1 << 18, 1 << 20, 1 << 22]
DEFAULT_THREADS = [2, 4, 8]


def _bench_one(path: str, data: np.ndarray, block_size: int, threads: int
               ) -> Tuple[float, float]:
    """Returns (write_GBps, read_GBps) for one config."""
    mb = data.nbytes >> 20
    h = AioHandle(block_size=block_size, num_threads=threads)
    t0 = time.perf_counter()
    h.sync_pwrite(data, path)
    tw = time.perf_counter() - t0
    back = np.empty_like(data)
    t0 = time.perf_counter()
    h.sync_pread(back, path)
    tr = time.perf_counter() - t0
    if not np.array_equal(data[:4096], back[:4096]):
        raise RuntimeError("aio round-trip corruption during sweep")
    gb = mb / 1024
    return gb / tw, gb / tr


def run_sweep(nvme_dir: str, mb_per_test: int = 64,
              block_sizes: Optional[List[int]] = None,
              thread_counts: Optional[List[int]] = None) -> List[Dict]:
    """Benchmark every (block_size, threads) combination."""
    results = []
    path = os.path.join(nvme_dir, ".ds_tpu_io_sweep.bin")
    data = np.random.default_rng(0).integers(
        0, 255, size=(mb_per_test << 20,), dtype=np.uint8)
    try:
        for bs in block_sizes or DEFAULT_BLOCK_SIZES:
            for th in thread_counts or DEFAULT_THREADS:
                w, r = _bench_one(path, data, bs, th)
                results.append({"block_size": bs, "num_threads": th,
                                "write_GBps": round(w, 3),
                                "read_GBps": round(r, 3)})
                log_dist(f"aio sweep: block={bs} threads={th} "
                         f"write={w:.2f}GB/s read={r:.2f}GB/s")
    finally:
        if os.path.exists(path):
            os.unlink(path)
    return results


def tune(nvme_dir: str, mb_per_test: int = 64,
         output: Optional[str] = None) -> Dict:
    """Run the sweep and return (and optionally write) the best aio config."""
    results = run_sweep(nvme_dir, mb_per_test)
    best = max(results, key=lambda r: r["write_GBps"] + r["read_GBps"])
    rec = {"aio": {"block_size": best["block_size"],
                   "thread_count": best["num_threads"],
                   "queue_depth": best["num_threads"],
                   "single_submit": False, "overlap_events": True},
           "sweep": results}
    if output:
        with open(output, "w") as f:
            json.dump(rec, f, indent=2)
    log_dist(f"aio tune: best {best}")
    return rec


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description="NVMe aio sweep (ds_nvme_tune)")
    ap.add_argument("nvme_dir", help="directory on the device to test")
    ap.add_argument("--mb", type=int, default=64, help="MB per test IO")
    ap.add_argument("-o", "--output", default=None, help="write best config")
    args = ap.parse_args(argv)
    rec = tune(args.nvme_dir, args.mb, args.output)
    print(json.dumps(rec["aio"], indent=2))
    return 0
