from .perf_tune import run_sweep, tune
