"""Deterministic fault injection.

TPU fleets lose runs to preemption mid-save, torn checkpoint files, hung
collectives, and host OOM — failure modes that never occur in a clean CI
box. The :class:`FaultInjector` makes every recovery path in this repo
testable on CPU: it fires a crash (or an I/O error) at a *named site* the
production code passes through, driven either by env vars (subprocess
crash drills — ``bin/dstpu_faultdrill``) or programmatically (in-process
tests).

Sites (see docs/resilience.md):

    ``pre_save``             before any checkpoint byte is written
    ``mid_save``             after the state file is written into the tmp
                             dir: the file is TORN (truncated) first, then
                             the crash fires — simulates a kill mid-write
    ``post_save_pre_latest`` tag dir fully durable, ``latest`` not yet
                             updated — simulates preemption between rename
                             and publish
    ``collective``           inside ``comm._record`` (trace time) — a crash
                             while a collective-bearing program is being
                             built
    ``step``                 at the top of ``Engine.train_batch`` once
                             ``global_steps >= at_step``

Serve sites (the v2 ragged engine's pipeline, docs/resilience.md
"Serving"): each models a replica dying at a different point of the
plan/dispatch/commit overlap window — the serve drill
(``bin/dstpu_faultdrill --mode serve``) crashes at every one and proves
journal/manifest replay is token-identical:

    ``pre_dispatch``         a planned step exists, nothing enqueued yet
    ``mid_commit``           ahead of a commit's blocking readback —
                             tokens journaled so far are durable, the
                             in-flight ring is lost
    ``during_prefill_chunk`` a multi-token prefill chunk was just planned
    ``during_cow_copy``      between a partial-tail prefix match and its
                             copy-on-write block-copy dispatch

Env protocol (read lazily on first :func:`get_fault_injector` call):

    DSTPU_FAULT_SITE       one of the names above (unset = disabled)
    DSTPU_FAULT_MODE       exit | raise | ioerror        (default: exit)
    DSTPU_FAULT_STEP       step gate for the ``step`` site (default: 0)
    DSTPU_FAULT_SKIP       skip the first N arrivals at the site
    DSTPU_FAULT_TIMES      fire at most N times           (default: 1)
    DSTPU_FAULT_EXIT_CODE  exit code for mode=exit        (default: 1)
    DSTPU_FAULT_ONCE_FILE  marker path: if it exists the injector is
                           disarmed; touched right before firing — a
                           restarted worker with the same env recovers
                           instead of crash-looping
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..utils.logging import logger

#: the canonical site names (docs + faultdrill iterate over these)
FAULT_SITES = ("pre_save", "mid_save", "post_save_pre_latest",
               "collective", "step",
               # serve-side sites (InferenceEngineV2's pipeline)
               "pre_dispatch", "mid_commit", "during_prefill_chunk",
               "during_cow_copy",
               # disaggregated-serving site (docs/serving.md): inside a
               # prefill specialist's handoff_out gather loop, BEFORE
               # any source state is released — the drill proves an
               # aborted handoff loses nothing (bin/dstpu_faultdrill
               # --mode disagg)
               "during_handoff_gather")

#: the serve-loop subset (bin/dstpu_faultdrill --mode serve drills these;
#: the train drill keeps its original five). The disagg site is drilled
#: by its own fleet-shaped mode, not the single-engine serve loop —
#: a lone engine never hands off.
TRAIN_FAULT_SITES = FAULT_SITES[:5]
SERVE_FAULT_SITES = FAULT_SITES[5:9]
DISAGG_FAULT_SITE = FAULT_SITES[9]


class InjectedFault(RuntimeError):
    """Raised by mode='raise' injections (in-process tests)."""


class FaultInjector:
    """Fires a configured failure when execution reaches the armed site.

    ``mode``:
      - ``exit``    — ``os._exit(exit_code)``: a hard crash, no atexit /
                      finally blocks run (the realistic preemption model;
                      works from writer threads too)
      - ``raise``   — raise :class:`InjectedFault` (in-process tests)
      - ``ioerror`` — raise ``OSError`` (exercises save retry-with-backoff)
    """

    def __init__(self, site: Optional[str] = None, mode: str = "exit",
                 at_step: int = 0, skip: int = 0, times: int = 1,
                 exit_code: int = 1, once_file: Optional[str] = None):
        if site is not None and site not in FAULT_SITES:
            raise ValueError(f"unknown fault site {site!r}; valid: {FAULT_SITES}")
        if mode not in ("exit", "raise", "ioerror"):
            raise ValueError(f"unknown fault mode {mode!r}")
        self.site = site
        self.mode = mode
        self.at_step = int(at_step)
        self.skip = int(skip)
        self.times = int(times)
        self.exit_code = int(exit_code)
        self.once_file = once_file
        self._fired = 0
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, env=os.environ) -> "FaultInjector":
        return cls(
            site=env.get("DSTPU_FAULT_SITE") or None,
            mode=env.get("DSTPU_FAULT_MODE", "exit"),
            at_step=int(env.get("DSTPU_FAULT_STEP", "0")),
            skip=int(env.get("DSTPU_FAULT_SKIP", "0")),
            times=int(env.get("DSTPU_FAULT_TIMES", "1")),
            exit_code=int(env.get("DSTPU_FAULT_EXIT_CODE", "1")),
            once_file=env.get("DSTPU_FAULT_ONCE_FILE") or None,
        )

    # ------------------------------------------------------------------ #

    def armed(self, site: str) -> bool:
        if self.site != site or self._fired >= self.times:
            return False
        if self.once_file and os.path.exists(self.once_file):
            return False
        return True

    def maybe_fire(self, site: str, step: Optional[int] = None,
                   torn_file: Optional[str] = None) -> None:
        """Fire if ``site`` is armed. ``step`` gates the ``step`` site;
        ``torn_file`` (mid_save) is truncated to half before the crash so
        a torn write really exists on disk when the process dies."""
        if not self.armed(site):
            return
        if site == "step" and step is not None and step < self.at_step:
            return
        with self._lock:
            if self.skip > 0:
                self.skip -= 1
                return
            if self._fired >= self.times:
                return
            self._fired += 1
        if self.once_file:
            # touch BEFORE dying: the restarted worker must not re-fire
            with open(self.once_file, "w") as f:
                f.write(site)
        if torn_file and os.path.exists(torn_file) and self.mode != "ioerror":
            size = os.path.getsize(torn_file)
            with open(torn_file, "r+b") as f:
                f.truncate(max(1, size // 2))
        logger.error(f"FAULT INJECTION: firing {self.mode} at site "
                     f"'{site}' (step={step})")
        try:
            # leave a flight-recorder trace artifact next to the crash
            # (telemetry/flight_recorder.py; no-op unless
            # DSTPU_FLIGHT_DIR is set) — the drill asserts its presence.
            # Must never interfere with the fault being injected.
            from ..telemetry.flight_recorder import auto_dump
            auto_dump(f"fault_{site}")
        except Exception:
            pass
        if self.mode == "ioerror":
            raise OSError(f"injected I/O error at site '{site}'")
        if self.mode == "raise":
            raise InjectedFault(f"injected fault at site '{site}'")
        os._exit(self.exit_code)


class _NoopInjector(FaultInjector):
    def __init__(self):
        super().__init__(site=None)

    def armed(self, site: str) -> bool:
        return False

    def maybe_fire(self, site, step=None, torn_file=None):
        return


_NOOP = _NoopInjector()
_INJECTOR: Optional[FaultInjector] = None


def get_fault_injector() -> FaultInjector:
    """The process-wide injector; built from env on first use. Disabled
    (no-op) unless DSTPU_FAULT_SITE is set or a test installed one."""
    global _INJECTOR
    if _INJECTOR is None:
        if os.environ.get("DSTPU_FAULT_SITE"):
            _INJECTOR = FaultInjector.from_env()
        else:
            _INJECTOR = _NOOP
    return _INJECTOR


def set_fault_injector(inj: Optional[FaultInjector]) -> None:
    """Install an injector (tests), or None to re-read the env lazily."""
    global _INJECTOR
    _INJECTOR = inj
