"""Restart ledger — a JSON audit trail of worker lifecycle events.

The elastic agent appends one record per supervisor event (launch, exit,
restart, backoff, give-up, forwarded signal). Postmortems on a flaky fleet
need exactly this: when did the run start crash-looping, what exit codes,
which world sizes. The file is a single JSON document
``{"events": [...]}`` rewritten atomically on every append — always
parseable, even if the supervisor itself dies mid-write.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import logger


class RestartLedger:
    def __init__(self, path: Optional[str]):
        self.path = path
        self._events: List[Dict[str, Any]] = []
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self._events = json.load(f).get("events", [])
            except (OSError, ValueError) as e:
                logger.warning(f"restart ledger {path} unreadable ({e}); "
                               f"starting fresh")

    def record(self, event: str, **fields) -> Dict[str, Any]:
        rec = {"event": event, "time": time.time(), **fields}
        self._events.append(rec)
        if self.path:
            try:
                tmp = self.path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"events": self._events}, f, indent=2)
                os.replace(tmp, self.path)
            except OSError as e:
                logger.warning(f"restart ledger write failed: {e}")
        return rec

    def replace(self, old: Optional[Dict[str, Any]], event: str,
                **fields) -> Dict[str, Any]:
        """Record ``event`` after removing ``old`` (a record previously
        returned by :meth:`record`/:meth:`replace`) by IDENTITY — the
        bounded-collapse primitive for high-frequency markers whose
        history only needs the latest entry (the train observer's
        ``train_progress`` events)."""
        if old is not None:
            for i in range(len(self._events) - 1, -1, -1):
                if self._events[i] is old:
                    del self._events[i]
                    break
        return self.record(event, **fields)

    @property
    def events(self) -> List[Dict[str, Any]]:
        return list(self._events)
