"""Fault drill — crash a short train or serve loop at every injection
site, then prove it recovers.

``--mode train`` (the PR 1 drill), for each site in
:data:`~.fault_injection.TRAIN_FAULT_SITES`:

  1. run a tiny CPU train-loop worker with ``DSTPU_FAULT_SITE=<site>``
     armed (hard ``os._exit`` crash) and a once-marker file;
  2. re-run the SAME command (the marker disarms the injector — exactly
     what a supervisor restart looks like);
  3. assert the second run completes all its steps, resuming from the
     newest valid checkpoint, and that ``latest`` points at a
     validating tag.

``--mode serve`` (ISSUE 7), for each site in
:data:`~.fault_injection.SERVE_FAULT_SITES` plus the cooperative
``sigterm`` drain:

  1. run a serve worker (v2 ragged engine, prefix cache on, pipelined
     depth 2, write-ahead replay journal armed) over a shared-prefix
     workload once with NO fault to record the uninterrupted greedy
     oracle;
  2. crash it — a hard ``os._exit`` at the armed serve site (the journal
     alone carries the committed state), or for ``sigterm`` a real
     SIGTERM the worker sends itself mid-decode (the engine drains and
     atomically publishes a replay manifest, exiting
     ``MEMBERSHIP_CHANGE_EXIT`` like a preempted replica);
  3. re-run in recovery: ``load_replay_state`` (manifest preferred,
     journal fallback), ``engine.replay`` on a fresh engine, decode
     every sequence to the full budget, and assert the streams are
     TOKEN-IDENTICAL to the oracle with the block pool fully recovered.

``--mode overload`` (ISSUE 16) drills the admission controller instead
of a crash site: calibrate this host's capacity rate-relatively, find
the knee (highest offered rate holding the goodput SLO), then throw a
2.5x-capacity spike at the engine twice — controller off (must
collapse below 0.85x knee goodput) and controller on with rational
retrying clients (must hold >=0.95x, queue-wait p99 inside SLO, retry
balance closed, ladder engaged, steady state silent). See
docs/serving.md "Overload control".

Exit 0 only when every site both crashed and recovered. This is the CI
guard (``bin/dstpu_faultdrill``) that keeps the recovery paths in
``checkpoint/``, ``runtime/engine.py`` and ``inference/v2/drain.py``
honest; tier-1 runs subsets via ``tests/unit/test_resilience.py`` and
``tests/unit/test_serve_drain.py``; ``tools/tpu_round11.sh`` runs both
modes in CI.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
from typing import List, Optional

from .fault_injection import (DISAGG_FAULT_SITE, FAULT_SITES,
                              SERVE_FAULT_SITES, TRAIN_FAULT_SITES)

#: steps the drill worker trains for; the fault fires at DRILL_FAULT_STEP
DRILL_STEPS = 5
DRILL_FAULT_STEP = 3

#: serve drill shape: requests sharing a prefix, tokens served per uid
SERVE_DRILL_REQS = 3
SERVE_DRILL_TOKENS = 8
#: the cooperative-drain pseudo-site (a real SIGTERM, not an injector)
SIGTERM_SITE = "sigterm"

#: fleet drill shape (``--mode fleet``): replicas, shared-prefix groups,
#: requests offered before/after the kill, tokens served per uid
FLEET_REPLICAS = 3
FLEET_GROUPS = 2
FLEET_REQS = 6
FLEET_LATE_REQS = 2
FLEET_TOKENS = 8
FLEET_SITE = "fleet_sigterm"

#: the overload drill's pseudo-site (``--mode overload``): a
#: 2.5x-capacity traffic spike, admission controller on vs off
OVERLOAD_SITE = "serve_overload"

#: disaggregated-serving drill (``--mode disagg``): a prefill+decode
#: specialist pair; one clean handoff wave, one wave whose handoff is
#: killed mid-gather followed by a SIGTERM on the prefill specialist,
#: one post-kill wave — token parity vs a colocated oracle throughout
DISAGG_SITE = DISAGG_FAULT_SITE
DISAGG_WAVE = 3
DISAGG_TOKENS = 6


def _worker() -> int:
    """The drill's training worker (run in a subprocess; configured by
    env). Trains DRILL_STEPS steps on a tiny model, checkpointing every
    step; resumes from the save dir when a checkpoint exists."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, make_model

    save_dir = os.environ["DRILL_SAVE_DIR"]
    progress_file = os.environ["DRILL_PROGRESS_FILE"]

    cfg_model = GPT2Config.tiny(dtype=jnp.float32)
    model, init_fn, loss_fn = make_model(cfg_model)
    params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=17)
    engine, _, _, _ = dstpu.initialize(
        loss_fn=loss_fn, params=params, config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "steps_per_print": 1000,
        })
    engine.load_checkpoint(save_dir)

    # a comm-facade collective each step: the 'collective' site lives in
    # comm._record, which plain data-parallel GSPMD training never crosses
    # (XLA inserts its own collectives) — this is the instrumented path
    # ZeRO++/Ulysses/MoE seams use
    from jax.sharding import PartitionSpec as P

    import deepspeed_tpu.comm.comm as dcomm
    from deepspeed_tpu.utils.jax_compat import shard_map
    dp = engine.topology.axis_size("data")
    comm_probe = shard_map(
        lambda v: dcomm.all_reduce(v, "sum", axis_name="data"),
        mesh=engine.topology.mesh, in_specs=P("data"),
        out_specs=P("data"), check_vma=False)

    # optional per-step wall-stamp log (the goodput drill's INDEPENDENT
    # measurement path: the gate compares the ledger-derived buckets
    # against arithmetic over these stamps) — JSONL append survives the
    # injected crash
    import time as _time
    steplog = os.environ.get("DRILL_STEPLOG")

    def _log(kind, step, t0, t1):
        if steplog:
            with open(steplog, "a") as f:
                f.write(json.dumps({"kind": kind, "step": step,
                                    "t0": t0, "t1": t1}) + "\n")

    while engine.global_steps < DRILL_STEPS:
        rng = np.random.RandomState(engine.global_steps)
        batch = {"tokens": jnp.asarray(
            rng.randint(0, 512, size=(engine.config.train_batch_size, 18)),
            jnp.int32)}
        t0 = _time.time()
        engine.train_batch(batch)
        t1 = _time.time()
        _log("step", engine.global_steps, t0, t1)
        engine.save_checkpoint(save_dir)
        _log("ckpt", engine.global_steps, t1, _time.time())
        comm_probe(jnp.ones((dp,), jnp.float32))
        with open(progress_file, "w") as f:
            json.dump({"global_steps": engine.global_steps}, f)
    return 0


def _serve_worker() -> int:
    """The serve drill's worker (subprocess; configured by env). Serves
    SERVE_DRILL_REQS shared-prefix requests for SERVE_DRILL_TOKENS greedy
    tokens each through a tiny pipelined v2 engine.

    ``DRILL_SERVE_PHASE``:
      - ``oracle``  — uninterrupted run; writes {uid: tokens} to
        ``DRILL_ORACLE_FILE``.
      - ``serve``   — journal armed (``DSTPU_SERVE_JOURNAL`` is set by
        the drill); an armed fault site ``os._exit``s mid-serve, or
        (``DRILL_SIGTERM_AFTER_ROUND``) the worker SIGTERMs itself and
        the PreemptionHandler->drain path publishes the manifest and
        exits ``MEMBERSHIP_CHANGE_EXIT``.
      - ``recover`` — load_replay_state(manifest, journal), replay on a
        fresh engine, decode every sequence to the full budget, write
        {uid: tokens} + pool verdict to ``DRILL_RESULT_FILE``.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import signal

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..elasticity.elastic_agent import MEMBERSHIP_CHANGE_EXIT
    from ..inference.v2 import (InferenceEngineV2, RaggedInferenceConfig,
                                load_replay_state)
    from ..models.gpt2 import GPT2, GPT2Config
    from .preemption import PreemptionHandler

    phase = os.environ["DRILL_SERVE_PHASE"]
    n_tok = SERVE_DRILL_TOKENS

    mcfg = GPT2Config(vocab_size=96, max_seq_len=128, num_layers=2,
                      num_heads=2, hidden_size=32, dtype=jnp.float32)
    params = GPT2(mcfg).init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 8), jnp.int32))["params"]
    cfg = RaggedInferenceConfig(
        max_seqs=4, chunk_size=8, block_size=4, num_blocks=64,
        max_blocks_per_seq=16, dtype="float32", attention_impl="dense",
        decode_loop_steps=0, serve_pipeline_depth=2, prefix_cache=True)
    eng = InferenceEngineV2(mcfg, params, cfg)

    # shared 10-token preamble, block_size 4: two full shared blocks per
    # later request plus a partial-tail CoW copy — every serve fault
    # site is on this workload's path
    rng = np.random.default_rng(55)
    shared = rng.integers(1, 96, 10).tolist()
    prompts = [shared + rng.integers(1, 96, 5).tolist()
               for _ in range(SERVE_DRILL_REQS)]
    uids = list(range(SERVE_DRILL_REQS))

    if phase == "recover":
        state = load_replay_state(os.environ.get("DRILL_MANIFEST"),
                                  os.environ.get("DRILL_JOURNAL"))
        if state is None:
            print("faultdrill serve: no manifest or journal to recover "
                  "from", file=sys.stderr)
            return 2
        out = eng.replay(state)
        toks = {int(s["uid"]): list(s["generated"])
                for s in state["sequences"]}
        for u in list(toks):
            if u in out and len(toks[u]) < n_tok:
                toks[u].append(int(out[u]))
        while True:
            short = [u for u in toks if len(toks[u]) < n_tok]
            if not short:
                break
            outs = eng.decode_pipelined(
                short, [toks[u][-1] for u in short],
                [n_tok - len(toks[u]) for u in short])
            for u in short:
                toks[u].extend(outs[u][:n_tok - len(toks[u])])
        for u in list(toks):
            eng.flush(u)
        with open(os.environ["DRILL_RESULT_FILE"], "w") as f:
            json.dump({"tokens": {str(u): t for u, t in toks.items()},
                       "replayed": len(toks),
                       "pool_recovered":
                           eng.free_blocks == cfg.num_blocks,
                       "prefix_stats": {
                           k: v for k, v in eng.prefix_stats.items()
                           if isinstance(v, (int, float))}}, f)
        return 0

    handler = PreemptionHandler() if phase == "serve" else None
    if handler is not None:
        eng.attach_preemption(handler)
    sigterm_round = int(os.environ.get("DRILL_SIGTERM_AFTER_ROUND", "-1"))

    toks = {}
    for u, p in zip(uids, prompts):
        r = eng.put([u], [list(p)], _greedy=True)
        if u in r:
            toks[u] = [int(r[u])]
    rounds = 0
    while True:
        live = [u for u in toks if len(toks[u]) < n_tok
                and u in eng.state.sequences]
        if not live:
            break
        if rounds == sigterm_round:
            # a REAL preemption signal, delivered with the next decode
            # call's pipeline live: the drive loop polls the handler's
            # flag, commits what's in flight and unwinds
            os.kill(os.getpid(), signal.SIGTERM)
        outs = eng.decode_pipelined(live, [toks[u][-1] for u in live], 2)
        for u in live:
            toks[u].extend(outs[u][:n_tok - len(toks[u])])
        rounds += 1
        if handler is not None and handler.preempted:
            manifest = eng.drain(os.environ.get("DRILL_MANIFEST"))
            print(f"faultdrill serve: drained "
                  f"{len(manifest['sequences'])} sequences after "
                  f"SIGTERM", file=sys.stderr)
            return MEMBERSHIP_CHANGE_EXIT

    if phase == "oracle":
        with open(os.environ["DRILL_ORACLE_FILE"], "w") as f:
            json.dump({str(u): t for u, t in toks.items()}, f)
    return 0


def _fleet_worker() -> int:
    """The fleet drill's worker (subprocess; configured by env): a
    replica POOL under offered load loses one member to a real SIGTERM
    mid-decode and must come out token-identical.

    One process plays the whole drill — the in-process pool is the
    single-host fleet shape, and a process-wide SIGTERM mapped to one
    replica's PreemptionHandler is exactly what a per-host preemption
    looks like from inside that host:

      1. ORACLE: a kill-free pool of FLEET_REPLICAS tiny engines serves
         FLEET_REQS shared-prefix requests (FLEET_GROUPS preambles) plus
         FLEET_LATE_REQS unique late arrivals; records {uid: tokens}.
      2. DRILL: a fresh identical pool serves the same workload; at the
         kill round the BUSIEST replica gets a PreemptionHandler and the
         worker SIGTERMs itself. The pool absorbs the drain — survivors
         replay the manifest with their warm prefix caches — then a
         LATE JOINER registers and the late requests are admitted.
      3. GATES (written to DRILL_RESULT_FILE): token parity for every
         request vs the oracle; ``pool.fully_recovered`` on the victim's
         manifest; the merged survivor rollup's TTFT quantiles EXACTLY
         equal to a single-stream histogram of the driver-observed TTFT
         values (the fleet-rollup exactness oracle, end-to-end through
         real engines); merged admitted == sum of per-replica admitted;
         the joiner took traffic; ledger carries the fleet events.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import signal

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..inference.v2 import InferenceEngineV2, RaggedInferenceConfig
    from ..models.gpt2 import GPT2, GPT2Config
    from ..serving import ReplicaPool, single_stream_oracle
    from ..telemetry.registry import Histogram, merge_snapshots
    from .ledger import RestartLedger
    from .preemption import PreemptionHandler

    n_tok = FLEET_TOKENS
    mcfg = GPT2Config(vocab_size=96, max_seq_len=256, num_layers=2,
                      num_heads=2, hidden_size=32, dtype=jnp.float32)
    params = GPT2(mcfg).init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 8), jnp.int32))["params"]

    def engine():
        cfg = RaggedInferenceConfig(
            max_seqs=4, chunk_size=8, block_size=4, num_blocks=64,
            max_blocks_per_seq=32, dtype="float32",
            attention_impl="dense", decode_loop_steps=0,
            serve_pipeline_depth=2, prefix_cache=True)
        return InferenceEngineV2(mcfg, params, cfg)

    # workload: FLEET_GROUPS shared 12-token preambles (3 full blocks
    # each — the replay lands on a survivor whose cache already holds
    # them) + unique tails; the late arrivals are unique-prompt (the
    # traffic a cold joiner wins on the queue term)
    rng = np.random.default_rng(77)
    prefixes = [rng.integers(1, 96, 12).tolist()
                for _ in range(FLEET_GROUPS)]
    prompts = {u: prefixes[u % FLEET_GROUPS]
               + rng.integers(1, 96, 5).tolist()
               for u in range(FLEET_REQS)}
    late = {100 + i: rng.integers(1, 96, 9).tolist()
            for i in range(FLEET_LATE_REQS)}

    def drive(pool, kill_round=None, joiner=False):
        toks = {}
        ttft = {}

        def admit(batch):
            out = pool.put(list(batch), [batch[u] for u in batch],
                           _greedy=True)
            for u in batch:
                if u in out:
                    toks[u] = [int(out[u])]

        def finish(u):
            seq = pool.state.get(u)
            if seq is not None and seq.first_token_at is not None \
                    and seq.admitted_at is not None:
                rep = pool.owner_of(u)
                ttft[u] = (seq.first_token_at - seq.admitted_at,
                           rep.replica_id if rep is not None else None)
            pool.flush(u)

        admit(prompts)
        rounds = 0
        victim = None
        while True:
            live = [u for u in toks if len(toks[u]) < n_tok
                    and u in pool.state.sequences]
            if not live and len(toks) == len(prompts) + len(late):
                break
            if rounds == kill_round:
                # the busiest replica takes the preemption: a real
                # process-level SIGTERM routed to ITS handler alone —
                # the single-process stand-in for a per-host signal
                busy = {}
                for u in live:
                    rep = pool.owner_of(u)
                    if rep is not None:
                        busy[rep.replica_id] = \
                            busy.get(rep.replica_id, 0) + 1
                vid = max(busy, key=busy.get)
                victim = pool.replica(vid)
                victim.engine.attach_preemption(PreemptionHandler())
                os.kill(os.getpid(), signal.SIGTERM)
            if live:
                outs = pool.decode_pipelined(
                    live, [toks[u][-1] for u in live], 2)
                for u in live:
                    toks[u].extend(outs[u][:n_tok - len(toks[u])])
            if rounds == kill_round and joiner:
                pool.add_replica(engine(), replica_id="joiner")
            if rounds == (kill_round if kill_round is not None else 1) \
                    and len(toks) == len(prompts):
                admit(late)          # offered load continues post-kill
            for u in list(toks):
                if len(toks[u]) >= n_tok and u in pool.state.sequences:
                    finish(u)
            rounds += 1
        for u in list(toks):
            if pool.state.get(u) is not None:
                finish(u)
        return toks, ttft, victim

    oracle_pool = ReplicaPool([engine() for _ in range(FLEET_REPLICAS)],
                              policy="prefix_aware", seed=0)
    oracle, _, _ = drive(oracle_pool)

    ledger = RestartLedger(os.environ.get("DRILL_FLEET_LEDGER"))
    pool = ReplicaPool([engine() for _ in range(FLEET_REPLICAS)],
                       policy="prefix_aware", seed=0, ledger=ledger)
    toks, ttft, victim = drive(pool, kill_round=1, joiner=True)

    result = {
        "replicas": FLEET_REPLICAS,
        "fault_fired": victim is not None and victim.state == "dead",
        "victim": victim.replica_id if victim is not None else None,
        "manifested": len(victim.manifest["sequences"])
        if victim is not None and victim.manifest else 0,
        "pool_recovered": bool(
            victim.manifest["pool"]["fully_recovered"])
        if victim is not None and victim.manifest else False,
        "token_parity": toks == oracle and len(toks) == len(oracle),
        "joiner_requests": sum(
            1 for _u, (_t, rid) in ttft.items() if rid == "joiner"),
    }
    # fleet-rollup exactness: the merged survivors' TTFT histogram must
    # equal a single-stream sketch of the driver-observed TTFT values —
    # same observations through two paths (per-engine registries ->
    # export-shaped states -> exact merge vs one raw-value stream)
    survivors = [r for r in pool.replicas() if r.state == "serving"]
    snaps = [r.engine.metrics.snapshot() for r in survivors]
    merged = merge_snapshots(snaps, sources=[r.replica_id
                                             for r in survivors])
    surv_ids = {r.replica_id for r in survivors}
    values = [t for t, rid in ttft.values() if rid in surv_ids]
    single = single_stream_oracle(values)
    mstate = merged["histograms"].get("serve_ttft_s", {})
    mhist = Histogram.from_state(mstate)
    result["rollup_count_exact"] = mhist.count == single.count
    result["rollup_quantiles_exact"] = all(
        mhist.quantile(q) == single.quantile(q)
        for q in (0.5, 0.9, 0.99))
    result["rollup_admitted_exact"] = (
        merged["counters"].get("serve_requests_admitted", 0)
        == sum(s["counters"].get("serve_requests_admitted", 0)
               for s in snaps))
    events = {e["event"] for e in ledger.events}
    result["ledger_events"] = sorted(events)
    result["ledger_ok"] = {"fleet_drain", "fleet_replay",
                           "fleet_join"} <= events
    with open(os.environ["DRILL_RESULT_FILE"], "w") as f:
        json.dump(result, f)
    ok = (result["fault_fired"] and result["token_parity"]
          and result["pool_recovered"] and result["manifested"] > 0
          and result["rollup_count_exact"]
          and result["rollup_quantiles_exact"]
          and result["rollup_admitted_exact"]
          and result["joiner_requests"] >= 1 and result["ledger_ok"])
    return 0 if ok else 1


def drill_fleet(workdir: str, verbose: bool = True) -> dict:
    """Kill-one-of-N drill for the replica pool: SIGTERM the busiest
    replica mid-decode under offered load, gate on token-identical
    replay on the survivors, exact pool recovery on the victim, an
    exactly-merged fleet rollup, and a late joiner taking traffic."""
    site_dir = os.path.join(workdir, "fleet")
    os.makedirs(site_dir, exist_ok=True)
    result_file = os.path.join(site_dir, "result.json")
    env = _serve_env(site_dir, "fleet",
                     DRILL_RESULT_FILE=result_file,
                     DRILL_FLEET_LEDGER=os.path.join(site_dir,
                                                     "ledger.json"))
    env.pop("DSTPU_RESTART_LEDGER", None)
    rc = _run_worker(env, fn="_fleet_worker")
    result = {"site": FLEET_SITE, "mode": "fleet", "worker_rc": rc}
    if os.path.exists(result_file):
        with open(result_file) as f:
            result.update(json.load(f))
    result["recovered"] = (
        rc == 0 and result.get("fault_fired") is True
        and result.get("token_parity") is True
        and result.get("pool_recovered") is True)
    if verbose:
        print(f"[faultdrill:fleet] rc={rc} "
              f"victim={result.get('victim')} "
              f"manifested={result.get('manifested')} "
              f"parity={result.get('token_parity')} "
              f"rollup_exact={result.get('rollup_quantiles_exact')} "
              f"joiner={result.get('joiner_requests')} "
              f"recovered={result['recovered']}", file=sys.stderr)
    return result


def _disagg_worker() -> int:
    """The disagg drill's worker (subprocess; configured by env): a
    prefill specialist + decode specialist pair must survive BOTH ways
    a handoff can die, token-identical to a colocated oracle.

      wave A  clean: requests land on the prefill specialist, hand off,
              and decode to completion on the decode specialist
      wave B  an injected ``during_handoff_gather`` fault aborts the
              handoff mid-gather — nothing may be lost (the sequences
              stay live on the source); then the prefill specialist
              takes a real SIGTERM mid-decode and the pool absorbs the
              drain (manifest replay onto the decode specialist)
      wave C  fresh post-kill traffic: the phase filter degrades
              gracefully and the survivor takes it

    Gates (written to DRILL_RESULT_FILE): token parity vs a one-replica
    oracle for every wave; the fault fired exactly once; wave A was
    adopted via handoff (``serve_handoff_seqs_in`` on the destination);
    wave B stayed on the source after the abort; the victim's manifest
    reports full pool recovery; wave C landed on the survivor."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import signal

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..inference.v2 import InferenceEngineV2, RaggedInferenceConfig
    from ..models.gpt2 import GPT2, GPT2Config
    from ..serving import ReplicaPool
    from .fault_injection import FaultInjector, set_fault_injector
    from .preemption import PreemptionHandler

    n_tok = DISAGG_TOKENS
    mcfg = GPT2Config(vocab_size=96, max_seq_len=256, num_layers=2,
                      num_heads=2, hidden_size=32, dtype=jnp.float32)
    params = GPT2(mcfg).init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 8), jnp.int32))["params"]

    def engine():
        cfg = RaggedInferenceConfig(
            max_seqs=4, chunk_size=8, block_size=4, num_blocks=64,
            max_blocks_per_seq=32, dtype="float32",
            attention_impl="dense", decode_loop_steps=0,
            serve_pipeline_depth=2, prefix_cache=True)
        return InferenceEngineV2(mcfg, params, cfg)

    rng = np.random.default_rng(11)
    waves = [{w * 10 + i: rng.integers(1, 96, 10 + i).tolist()
              for i in range(DISAGG_WAVE)} for w in range(3)]

    def serve_wave(pool, batch, sigterm_victim=None):
        """Admit one wave, decode every uid to n_tok, flush; returns
        ({uid: tokens}, {uid: final owner id}). ``sigterm_victim``: a
        replica id that takes a PreemptionHandler + a real SIGTERM
        after the first decode round."""
        toks, owners = {}, {}
        out = pool.put(list(batch), [batch[u] for u in batch],
                       _greedy=True)
        for u in batch:
            if u in out:
                toks[u] = [int(out[u])]
        rounds = 0
        while True:
            live = [u for u in toks if len(toks[u]) < n_tok
                    and u in pool.state.sequences]
            if not live:
                break
            if rounds == 1 and sigterm_victim is not None:
                victim = pool.replica(sigterm_victim)
                victim.engine.attach_preemption(PreemptionHandler())
                os.kill(os.getpid(), signal.SIGTERM)
                sigterm_victim = None
            outs = pool.decode_pipelined(
                live, [toks[u][-1] for u in live], 2)
            for u in live:
                toks[u].extend(outs[u][:n_tok - len(toks[u])])
            rounds += 1
        for u in list(toks):
            rep = pool.owner_of(u)
            owners[u] = rep.replica_id if rep is not None else None
            if pool.state.get(u) is not None:
                pool.flush(u)
        return toks, owners

    # oracle: one colocated mixed replica, same waves in the same order
    oracle_pool = ReplicaPool([engine()], policy="prefix_aware", seed=0)
    oracle = {}
    for batch in waves:
        t, _ = serve_wave(oracle_pool, batch)
        oracle.update(t)

    pool = ReplicaPool([engine(), engine()], policy="prefix_aware",
                       seed=0, replica_ids=["pre", "dec"],
                       roles=["prefill", "decode"])
    toks = {}

    # wave A: clean disagg path — prefill on "pre", adopt on "dec"
    t, owners_a = serve_wave(pool, waves[0])
    toks.update(t)
    dec_m = pool.replica("dec").engine.metrics
    adopted = int(dec_m.counter("serve_handoff_seqs_in").value)

    # wave B: abort the handoff mid-gather, then kill the source.
    # mode=raise — the pool's migration loop must catch it and leave
    # every sequence live on the prefill source (nothing released).
    inj = FaultInjector(site=DISAGG_SITE, mode="raise", times=1)
    set_fault_injector(inj)
    out_b = pool.put(list(waves[1]), [waves[1][u] for u in waves[1]],
                     _greedy=True)
    fault_fired = inj._fired == 1
    set_fault_injector(None)
    owners_b0 = {u: pool.owner_of(u).replica_id for u in waves[1]
                 if pool.owner_of(u) is not None}
    abort_safe = bool(owners_b0) and all(
        rid == "pre" for rid in owners_b0.values())
    for u, tk in out_b.items():
        toks[u] = [int(tk)]
    rounds = 0
    while True:
        live = [u for u in toks if len(toks[u]) < n_tok
                and u in pool.state.sequences]
        if not live:
            break
        if rounds == 1:
            victim = pool.replica("pre")
            victim.engine.attach_preemption(PreemptionHandler())
            os.kill(os.getpid(), signal.SIGTERM)
        outs = pool.decode_pipelined(live, [toks[u][-1] for u in live], 2)
        for u in live:
            toks[u].extend(outs[u][:n_tok - len(toks[u])])
        rounds += 1
    victim = pool.replica("pre")
    pool_recovered = bool(
        victim.manifest["pool"]["fully_recovered"]) \
        if victim.manifest else False
    for u in waves[1]:
        if pool.state.get(u) is not None:
            pool.flush(u)

    # wave C: fresh post-kill traffic — the phase filter has no serving
    # prefill candidate left, so placement degrades to the survivor
    t, owners_c = serve_wave(pool, waves[2])
    toks.update(t)

    result = {
        "fault_fired": fault_fired,
        "handoff_adopted": adopted,
        "handoff_wave_on_dest": all(
            rid == "dec" for rid in owners_a.values()),
        "abort_safe": abort_safe,
        "pool_recovered": pool_recovered,
        "post_kill_on_survivor": all(
            rid == "dec" for rid in owners_c.values()),
        "token_parity": toks == oracle and len(toks) == len(oracle),
    }
    with open(os.environ["DRILL_RESULT_FILE"], "w") as f:
        json.dump(result, f)
    ok = (result["fault_fired"] and result["token_parity"]
          and result["abort_safe"] and result["pool_recovered"]
          and result["handoff_adopted"] >= DISAGG_WAVE
          and result["handoff_wave_on_dest"]
          and result["post_kill_on_survivor"])
    return 0 if ok else 1


def drill_disagg(workdir: str, verbose: bool = True) -> dict:
    """Disaggregated-serving drill: abort a KV handoff mid-gather with
    an injected fault (nothing may be lost), then SIGTERM the prefill
    specialist mid-decode (drain replay onto the decode specialist),
    gating on token parity vs a colocated oracle throughout."""
    site_dir = os.path.join(workdir, "disagg")
    os.makedirs(site_dir, exist_ok=True)
    result_file = os.path.join(site_dir, "result.json")
    env = _serve_env(site_dir, "disagg", DRILL_RESULT_FILE=result_file)
    # the drill builds its own role assignment; ambient disagg knobs
    # must not leak into the worker
    env.pop("DSTPU_FLEET_ROLES", None)
    env.pop("DSTPU_DISAGG", None)
    rc = _run_worker(env, fn="_disagg_worker")
    result = {"site": DISAGG_SITE, "mode": "disagg", "worker_rc": rc}
    if os.path.exists(result_file):
        with open(result_file) as f:
            result.update(json.load(f))
    result["recovered"] = (
        rc == 0 and result.get("fault_fired") is True
        and result.get("token_parity") is True
        and result.get("abort_safe") is True
        and result.get("pool_recovered") is True)
    if verbose:
        print(f"[faultdrill:disagg] rc={rc} "
              f"adopted={result.get('handoff_adopted')} "
              f"abort_safe={result.get('abort_safe')} "
              f"parity={result.get('token_parity')} "
              f"survivor={result.get('post_kill_on_survivor')} "
              f"recovered={result['recovered']}", file=sys.stderr)
    return result


#: the goodput drill's pseudo-site (a real injected kill supervised by
#: the REAL elastic agent; the gate is the goodput ledger's arithmetic)
GOODPUT_SITE = "train_goodput"


def drill_train_goodput(workdir: str, verbose: bool = True) -> dict:
    """Goodput-ledger drill (ISSUE 15): run the training worker under
    the REAL elastic agent with a hard ``os._exit`` injected inside a
    checkpoint save mid-run, let the agent restart it, then integrate
    the two ledgers (the agent's supervisor ledger + the engine
    observer's train ledger) through ``telemetry.goodput`` and gate:

      * buckets sum to the run's total wall EXACTLY;
      * the kill actually cost something (``restart_lost`` > 0) and the
        redo shows up (``replay_catchup`` > 0 — the crash lands between
        a durable checkpoint and the next, so work IS discarded);
      * ``train_goodput_frac`` matches an INDEPENDENT computation over
        the worker's own per-step wall-stamp log within 5% — two
        measurement paths, one number.
    """
    import time as _time

    from ..elasticity.elastic_agent import run_elastic
    from ..telemetry.goodput import goodput_report, load_ledger_events

    site_dir = os.path.join(workdir, GOODPUT_SITE)
    os.makedirs(site_dir, exist_ok=True)
    save_dir = os.path.join(site_dir, "ckpt")
    steplog = os.path.join(site_dir, "steps.jsonl")
    agent_ledger = os.path.join(site_dir, "agent_ledger.json")
    train_ledger = os.path.join(site_dir, "train_ledger.json")
    marker = os.path.join(site_dir, "fired.marker")

    env = dict(os.environ)
    # run_elastic MERGES this dict over os.environ (child_env.update),
    # so inherited keys must be OVERRIDDEN, not popped: an exported
    # XLA_FLAGS (the test harness's 8-device mesh) or an operator's
    # DSTPU_RESTART_LEDGER would otherwise leak into the worker
    env.update({
        "XLA_FLAGS": "",
        "DSTPU_RESTART_LEDGER": "",
        "JAX_PLATFORMS": "cpu",
        "DRILL_SAVE_DIR": save_dir,
        "DRILL_PROGRESS_FILE": os.path.join(site_dir, "progress.json"),
        "DRILL_STEPLOG": steplog,
        # crash INSIDE the 3rd checkpoint save: steps 1-2 are durable,
        # step 3's compute is discarded (restart_lost) and redone
        # (replay_catchup) after the agent restarts the worker
        "DSTPU_FAULT_SITE": "pre_save",
        "DSTPU_FAULT_MODE": "exit",
        "DSTPU_FAULT_ONCE_FILE": marker,
        "DSTPU_FAULT_SKIP": "2",
        "DSTPU_TELEMETRY": "1",
        "DSTPU_TRAIN_OBS": "1",
        "DSTPU_TRAIN_LEDGER": train_ledger,
        # per-step progress events: the catch-up high-water mark is
        # exact instead of export_every-granular
        "DSTPU_TRAIN_OBS_PROGRESS_EVERY": "1",
    })
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-c",
           "import sys; from deepspeed_tpu.resilience.faultdrill import "
           "_worker; sys.exit(_worker())"]
    t0 = _time.time()
    rc = run_elastic(
        cmd,
        {"max_train_batch_size": 2000, "micro_batch_sizes": [2, 4, 6],
         "min_gpus": 1, "max_gpus": 10000, "version": 0.1},
        max_restarts=3, min_restart_interval_s=0.0,
        backoff_base_s=0.0, crash_loop_budget=5,
        ledger_path=agent_ledger, env=env)
    t_end = _time.time()

    result = {"site": GOODPUT_SITE, "mode": "train", "agent_rc": rc,
              "fault_fired": os.path.exists(marker)}
    events = load_ledger_events([agent_ledger, train_ledger])
    rep = goodput_report(events, t0=t0, t_end=t_end)
    result["goodput"] = {
        "total_wall_s": round(rep["total_wall_s"], 3),
        "buckets": {k: round(v, 3) for k, v in rep["buckets"].items()},
        "train_goodput_frac": rep["train_goodput_frac"],
        "worker_runs": rep["worker_runs"],
    }
    buckets_exact = abs(sum(rep["buckets"].values())
                        - rep["total_wall_s"]) < 1e-6
    result["buckets_sum_exact"] = buckets_exact

    # ---- the independent arithmetic over the worker's step log ------ #
    entries = []
    if os.path.exists(steplog):
        with open(steplog) as f:
            entries = [json.loads(ln) for ln in f if ln.strip()]
    runs = [(e.get("t_start"), e.get("t_end"))
            for e in load_ledger_events([agent_ledger])
            if e.get("event") in ("restart", "success", "drained",
                                  "giveup")]
    expected = None
    if rc == 0 and len(runs) == 2 and entries and rep["total_wall_s"] > 0:
        (s1, e1), (s2, e2) = runs
        total = t_end - t0
        lead = s1 - t0            # agent setup before the first launch
        tail = t_end - e2
        downtime = s2 - e1
        r1 = [e for e in entries if e["t1"] <= e1]
        r2 = [e for e in entries if e["t0"] >= s2]
        ck_total = sum(e["t1"] - e["t0"] for e in entries
                       if e["kind"] == "ckpt")
        durable = [e["t1"] for e in r1 if e["kind"] == "ckpt"]
        lost = e1 - (max(durable) if durable else s1)
        hwm = max((e["step"] for e in r1 if e["kind"] == "step"),
                  default=0)
        caught = [e["t1"] for e in r2
                  if e["kind"] == "step" and e["step"] >= hwm]
        catch_end = min(caught) if caught else e2
        catchup = max(0.0, catch_end - s2) - sum(
            min(e["t1"], catch_end) - e["t0"] for e in r2
            if e["kind"] == "ckpt" and e["t0"] < catch_end)
        productive = (total - lead - tail - downtime - lost - catchup
                      - ck_total)
        expected = productive / total
        result["expected"] = {
            "frac": round(expected, 4), "lost_s": round(lost, 3),
            "downtime_s": round(downtime, 3),
            "catchup_s": round(catchup, 3),
            "checkpoint_s": round(ck_total, 3),
        }
    frac = rep["train_goodput_frac"]
    match = (expected is not None and frac is not None
             and abs(frac - expected) <= 0.05)
    result["frac_matches_drill"] = match
    result["recovered"] = (
        rc == 0 and result["fault_fired"] and buckets_exact and match
        and rep["buckets"]["restart_lost"] > 0
        and rep["buckets"]["replay_catchup"] > 0
        and rep["buckets"]["checkpoint_save"] > 0)
    if verbose:
        print(f"[faultdrill:{GOODPUT_SITE}] rc={rc} "
              f"frac={frac if frac is None else round(frac, 4)} "
              f"expected={None if expected is None else round(expected, 4)} "
              f"buckets={result['goodput']['buckets']} "
              f"recovered={result['recovered']}", file=sys.stderr)
    return result


def _run_worker(env: dict, fn: str = "_worker") -> int:
    env = dict(env)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-c",
           "import sys; from deepspeed_tpu.resilience.faultdrill import "
           f"{fn}; sys.exit({fn}())"]
    return subprocess.run(cmd, env=env).returncode


def drill_site(site: str, workdir: str, verbose: bool = True) -> dict:
    """Crash-then-recover drill for one site. Returns a result dict with
    ``recovered`` True/False plus diagnostics."""
    site_dir = os.path.join(workdir, site)
    os.makedirs(site_dir, exist_ok=True)
    save_dir = os.path.join(site_dir, "ckpt")
    progress_file = os.path.join(site_dir, "progress.json")
    marker = os.path.join(site_dir, "fired.marker")

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)            # single CPU device: fastest drill
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DRILL_SAVE_DIR": save_dir,
        "DRILL_PROGRESS_FILE": progress_file,
        "DSTPU_FAULT_SITE": site,
        "DSTPU_FAULT_MODE": "exit",
        "DSTPU_FAULT_STEP": str(DRILL_FAULT_STEP),
        "DSTPU_FAULT_ONCE_FILE": marker,
        # save sites: let a couple of clean saves land first so recovery
        # has a previous tag to fall back to
        "DSTPU_FAULT_SKIP": "2" if site in (
            "pre_save", "mid_save", "post_save_pre_latest") else "0",
    })

    result = {"site": site}
    rc_crash = _run_worker(env)
    result["crash_rc"] = rc_crash
    result["fault_fired"] = os.path.exists(marker)
    if rc_crash == 0 or not result["fault_fired"]:
        result["recovered"] = False
        result["error"] = ("worker did not crash — injection site never "
                           "reached")
        return result

    rc_rec = _run_worker(env)             # marker disarms the injector
    result["recover_rc"] = rc_rec
    progress = {}
    if os.path.exists(progress_file):
        with open(progress_file) as f:
            progress = json.load(f)
    result["final_steps"] = progress.get("global_steps")

    from ..checkpoint.engine_checkpoint import (
        LATEST_FILE, validate_checkpoint_dir)
    latest_ok = False
    latest_path = os.path.join(save_dir, LATEST_FILE)
    if os.path.exists(latest_path):
        with open(latest_path) as f:
            tag = f.read().strip()
        latest_ok, reason = validate_checkpoint_dir(
            os.path.join(save_dir, tag))
        result["latest_tag"] = tag
        if not latest_ok:
            result["latest_invalid"] = reason
    result["recovered"] = (rc_rec == 0
                           and progress.get("global_steps") == DRILL_STEPS
                           and latest_ok)
    if verbose:
        print(f"[faultdrill:{site}] crash_rc={rc_crash} "
              f"recover_rc={rc_rec} final_steps={result['final_steps']} "
              f"recovered={result['recovered']}", file=sys.stderr)
    return result


def _serve_env(workdir: str, phase: str, **extra) -> dict:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)            # single CPU device: fastest drill
    for k in ("DSTPU_FAULT_SITE", "DSTPU_SERVE_JOURNAL",
              "DSTPU_SERVE_DRAIN_MANIFEST", "DSTPU_FLIGHT_DIR",
              "DSTPU_TELEMETRY"):
        env.pop(k, None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DRILL_SERVE_PHASE": phase,
        "DRILL_ORACLE_FILE": os.path.join(workdir, "oracle.json"),
    })
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _serve_oracle(workdir: str) -> Optional[dict]:
    """The uninterrupted greedy streams, computed once per drill workdir
    and shared by every serve site (greedy decode is deterministic, so
    one oracle serves them all)."""
    path = os.path.join(workdir, "oracle.json")
    if not os.path.exists(path):
        rc = _run_worker(_serve_env(workdir, "oracle"), fn="_serve_worker")
        if rc != 0 or not os.path.exists(path):
            return None
    with open(path) as f:
        return json.load(f)


def drill_serve_site(site: str, workdir: str, verbose: bool = True) -> dict:
    """Crash-then-replay drill for one serve site (or ``sigterm``):
    kill a serving replica mid-stream, recover on a fresh engine from
    the manifest/journal, assert token parity with the uninterrupted
    run and full block-pool recovery."""
    site_dir = os.path.join(workdir, f"serve_{site}")
    os.makedirs(site_dir, exist_ok=True)
    journal = os.path.join(site_dir, "replay.jsonl")
    manifest = os.path.join(site_dir, "manifest.json")
    result_file = os.path.join(site_dir, "result.json")
    marker = os.path.join(site_dir, "fired.marker")

    result = {"site": site, "mode": "serve"}
    oracle = _serve_oracle(workdir)
    if oracle is None:
        result.update(recovered=False, error="oracle run failed")
        return result

    env = _serve_env(workdir, "serve",
                     DRILL_JOURNAL=journal, DRILL_MANIFEST=manifest,
                     DSTPU_SERVE_JOURNAL=journal,
                     # crash-path observability: the injector (or the
                     # sigterm drain) must leave a Chrome-trace flight
                     # dump next to the replay state — asserted below
                     DSTPU_FLIGHT_DIR=site_dir)
    if site == SIGTERM_SITE:
        # a REAL preemption signal mid-decode: PreemptionHandler ->
        # pipeline unwind -> drain() -> atomic manifest publish
        env["DRILL_SIGTERM_AFTER_ROUND"] = "1"
    else:
        # a hard os._exit at the armed site: no drain ran, the
        # write-ahead journal alone carries the committed chains. The
        # skips land the crash mid-stream with state worth replaying.
        env.update({
            "DSTPU_FAULT_SITE": site,
            "DSTPU_FAULT_MODE": "exit",
            "DSTPU_FAULT_ONCE_FILE": marker,
            "DSTPU_FAULT_SKIP": {"pre_dispatch": "4", "mid_commit": "3",
                                 "during_prefill_chunk": "2",
                                 "during_cow_copy": "1"}.get(site, "0"),
        })
    rc_crash = _run_worker(env, fn="_serve_worker")
    result["crash_rc"] = rc_crash
    # 99 = MEMBERSHIP_CHANGE_EXIT: the cooperative drain's exit code
    fired = os.path.exists(marker) if site != SIGTERM_SITE \
        else rc_crash == 99
    result["fault_fired"] = fired
    if rc_crash == 0 or not fired:
        result.update(recovered=False,
                      error="worker did not crash — injection site never "
                            "reached")
        return result
    if site == SIGTERM_SITE and not os.path.exists(manifest):
        result.update(recovered=False,
                      error="drain published no manifest")
        return result
    # the crash (injector fire) or drain must have auto-dumped the phase
    # flight recorder — the trace artifact a postmortem starts from
    # (docs/observability.md). Validated as loadable Chrome-trace JSON.
    dumps = [f for f in os.listdir(site_dir)
             if f.startswith("flight_") and f.endswith(".json")]
    flight_ok = False
    for f in dumps:
        try:
            with open(os.path.join(site_dir, f)) as fh:
                trace = json.load(fh)
            flight_ok |= isinstance(trace.get("traceEvents"), list)
        except ValueError:
            pass
    result["flight_dump"] = flight_ok

    rc_rec = _run_worker(
        _serve_env(workdir, "recover", DRILL_JOURNAL=journal,
                   DRILL_MANIFEST=manifest, DRILL_RESULT_FILE=result_file),
        fn="_serve_worker")
    result["recover_rc"] = rc_rec
    replayed = {}
    if os.path.exists(result_file):
        with open(result_file) as f:
            replayed = json.load(f)
    toks = replayed.get("tokens", {})
    result["replayed_sequences"] = replayed.get("replayed")
    result["pool_recovered"] = replayed.get("pool_recovered")
    # every sequence the dead replica owed tokens to must finish with a
    # stream identical to the uninterrupted run (a request admitted
    # AFTER the kill point never entered the journal — the client
    # retries it; everything admitted must replay exactly)
    parity = bool(toks) and all(toks[u] == oracle[u] for u in toks)
    result["token_parity"] = parity
    result["recovered"] = (rc_rec == 0 and parity
                           and replayed.get("pool_recovered") is True
                           and flight_ok)
    if verbose:
        print(f"[faultdrill:serve:{site}] crash_rc={rc_crash} "
              f"recover_rc={rc_rec} replayed={result['replayed_sequences']} "
              f"parity={parity} recovered={result['recovered']}",
              file=sys.stderr)
    return result


def _overload_worker() -> int:
    """The overload drill's worker (subprocess; configured by env): the
    same engine serves a 2.5x-capacity traffic spike twice — admission
    controller OFF, then ON — and the gates reproduce ISSUE 16's
    acceptance criteria:

      1. CAPACITY: a saturating deadline-free burst; the completed rate
         IS the service capacity C.
      2. KNEE: ``sweep_capacity`` over 0.5/0.7/0.9 x C on the deadline
         workload locates the knee (highest offered rate whose goodput
         fraction still meets the SLO) and its goodput RATE.
      3. SPIKE x2: the SAME seeded :class:`SpikeArrivals` schedule —
         knee-rate steady state with a 2.5 x C window — offered once
         uncontrolled and once through an armed
         :class:`AdmissionController` with client retries.
      4. GATES (written to DRILL_RESULT_FILE): controller-on goodput
         rate >= 0.95 x the knee goodput rate; controller-off collapses
         below 0.85 x; completed-request queue-wait p99 stays within
         the deadline on the controlled run; the controller visibly
         engaged (ladder transitions or door rejections); both reports'
         outcome breakdowns balance exactly.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ..serving.admission import AdmissionController
    from ..telemetry.loadgen import (PoissonArrivals, SpikeArrivals,
                                     WorkloadMix, _tiny_engine,
                                     build_requests, run_open_loop,
                                     sweep_capacity)

    eng, mcfg = _tiny_engine(max_seqs=8, num_blocks=96)

    def mk_mix(deadline_s: float = 0.0) -> WorkloadMix:
        return WorkloadMix(
            prompt_lens=(16,), prompt_probs=(1.0,),
            gen_lens=(8,), gen_probs=(1.0,),
            deadline_frac=1.0 if deadline_s else 0.0,
            deadline_s=deadline_s, vocab_size=mcfg.vocab_size)

    # 0) warmup: pay the XLA compiles OUTSIDE every timed phase — a
    # cold capacity pass would measure compile time, not service rate
    run_open_loop(eng, build_requests(PoissonArrivals(500.0, seed=0),
                                      mk_mix(), 10, seed=0,
                                      uid_base=6_000_000))

    # 1) capacity: a saturating 1-second burst, no deadlines — the
    # completed rate is what the engine can actually serve. Two
    # passes: the first sizes the second (everything downstream is
    # rate-RELATIVE, so the drill means the same thing on any host)
    slots = eng.config.max_seqs
    est = run_open_loop(eng, build_requests(
        PoissonArrivals(500.0, seed=1), mk_mix(), 32, seed=1,
        uid_base=7_000_000), max_live=slots
    ).report["rates_rps"]["completed"] or 1.0
    n_cap = max(32, int(2.0 * est))
    # max_live pins the engine at exactly its slot count: saturated
    # WITHOUT oversubscription churn, i.e. the peak service rate
    cap_rps = run_open_loop(eng, build_requests(
        PoissonArrivals(4.0 * est, seed=11), mk_mix(), n_cap, seed=11,
        uid_base=7_500_000), max_live=slots
    ).report["rates_rps"]["completed"] or est
    # deadline ~8 requests' worth of service time (floored above OS
    # scheduling noise): generous at the knee, unmeetable once an
    # uncontrolled queue builds
    deadline_s = max(0.25, 8.0 / cap_rps)
    mix = mk_mix(deadline_s)

    # 2) locate the knee on the deadline workload — ~2 s of steady
    # state at the highest probed rate
    n_sweep = max(48, int(1.8 * cap_rps))
    sweep = sweep_capacity(
        eng, [0.5 * cap_rps, 0.7 * cap_rps, 0.9 * cap_rps], n_sweep,
        mix, seed=2, goodput_slo_frac=0.9)
    knee_rps = sweep["knee_rps"]
    knee_goodput_rps = sweep["knee_goodput_rps"]
    if knee_rps is None:
        # no sweep row met the SLO (a very noisy host) — steer by the
        # best goodput rate observed so the spike still compares on/off
        best = max(sweep["curve"], key=lambda r: r["goodput_rps"] or 0.0)
        knee_rps = best["offered_rps"]
        knee_goodput_rps = best["goodput_rps"] or 1.0

    # 3) the spike: steady state AT the knee, then a 2.5 x capacity
    # window long enough that the uncontrolled backlog (~1.5 x C x dur
    # requests, several deadlines deep) cannot hide inside the deadline
    spike_rps = 2.5 * cap_rps
    dur_s = max(1.0, 3.0 * deadline_s)
    start_s = 1.0
    mult = spike_rps / knee_rps
    n = int(knee_rps * (start_s + 1.0) + spike_rps * dur_s)
    proc = SpikeArrivals(knee_rps, mult, start_s, dur_s, seed=3)

    off = run_open_loop(
        eng, build_requests(proc, mix, n, seed=3, uid_base=8_000_000)
    ).report

    ctrl = AdmissionController(eng, window_s=0.5,
                               qw_slo_s=deadline_s / 4, tick_s=0.05,
                               hysteresis_s=0.5,
                               retry_cap_s=deadline_s)
    # pre-warm the browned-out program shapes (halved prefill chunk,
    # spec off): without this the ladder's first engagement pays a
    # fresh XLA compile mid-spike, and the compile stall feeds back
    # into the controller's own queue-wait evidence as phantom overload
    for lvl in (3, 0):
        ctrl.apply_level(lvl)
        run_open_loop(
            eng,
            build_requests(PoissonArrivals(est), mk_mix(), 12,
                           seed=40 + lvl, uid_base=9_900_000 + lvl),
            max_live=slots)
    # snapshot past the OFF run's cumulative history: the controller
    # must steer on ITS run's evidence, not the preceding collapse
    ctrl.prime()
    on = run_open_loop(
        eng, build_requests(proc, mix, n, seed=3, uid_base=9_000_000),
        admission=ctrl, retry_budget=2, retry_base_s=0.05).report

    on_g = on["rates_rps"]["goodput"] or 0.0
    off_g = off["rates_rps"]["goodput"] or 0.0
    qw_p99 = on["latency"]["queue_wait_s"].get("p99")
    gates = {
        "on_holds_knee": on_g >= 0.95 * knee_goodput_rps,
        "off_collapses": off_g < 0.85 * knee_goodput_rps,
        "qw_p99_within_slo": qw_p99 is not None
        and qw_p99 <= deadline_s,
        "controller_engaged": on["admission"]["transitions"] >= 1
        or on["requests"]["rejected_admission"] > 0,
        "balance_ok_off": off["requests"]["balance_ok"],
        "balance_ok_on": on["requests"]["balance_ok"],
    }
    result = {
        "capacity_rps": round(cap_rps, 3),
        "deadline_s": round(deadline_s, 4),
        "knee_rps": round(knee_rps, 3),
        "knee_goodput_rps": round(knee_goodput_rps, 3),
        "spike": {"base_rps": round(knee_rps, 3),
                  "spike_rps": round(spike_rps, 3),
                  "start_s": start_s, "dur_s": round(dur_s, 3),
                  "requests": n},
        "off": {"goodput_rps": round(off_g, 3),
                "requests": off["requests"],
                "queue_wait_p99_s":
                off["latency"]["queue_wait_s"].get("p99")},
        "on": {"goodput_rps": round(on_g, 3),
               "requests": on["requests"],
               "queue_wait_p99_s": qw_p99,
               "retries": on.get("retries"),
               "admission": on["admission"]},
        "gates": gates,
    }
    with open(os.environ["DRILL_RESULT_FILE"], "w") as f:
        json.dump(result, f)
    return 0 if all(gates.values()) else 1


def drill_overload(workdir: str, verbose: bool = True) -> dict:
    """Overload drill: a 2.5x-capacity traffic spike served by the same
    engine with the admission controller off (must collapse below
    0.85 x the knee goodput rate) and on (must hold >= 0.95 x with
    queue-wait p99 inside the deadline) — the ISSUE 16 robustness
    gate."""
    site_dir = os.path.join(workdir, "overload")
    os.makedirs(site_dir, exist_ok=True)
    result_file = os.path.join(site_dir, "result.json")
    env = _serve_env(site_dir, "overload", DRILL_RESULT_FILE=result_file)
    # the worker arms/disarms the controller itself — a caller's kill
    # switch or tuning knobs must not skew the on-vs-off comparison
    for k in list(env):
        if k.startswith("DSTPU_ADMISSION") \
                and k != "DSTPU_ADMISSION_DEBUG":
            env.pop(k)
    rc = _run_worker(env, fn="_overload_worker")
    result = {"site": OVERLOAD_SITE, "mode": "overload", "rc": rc}
    if os.path.exists(result_file):
        with open(result_file) as f:
            result.update(json.load(f))
    gates = result.get("gates") or {}
    result["recovered"] = rc == 0 and bool(gates) \
        and all(gates.values())
    if verbose:
        print(f"[faultdrill:{OVERLOAD_SITE}] rc={rc} "
              f"knee={result.get('knee_goodput_rps')}rps "
              f"on={result.get('on', {}).get('goodput_rps')}rps "
              f"off={result.get('off', {}).get('goodput_rps')}rps "
              f"gates={gates} recovered={result['recovered']}",
              file=sys.stderr)
    return result


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="crash a short CPU train or serve loop at each "
                    "fault-injection site and verify recovery (exit "
                    "non-zero on any unrecovered failure)")
    ap.add_argument("--mode", default="train",
                    choices=("train", "serve", "fleet", "train_goodput",
                             "overload", "disagg", "all"),
                    help="train: checkpoint-recovery drill (PR 1 sites); "
                         "serve: drain/replay drill (serve sites + "
                         "sigterm); fleet: kill-one-of-N replica-pool "
                         "drill (SIGTERM under offered load, survivor "
                         "replay + rollup exactness); train_goodput: "
                         "elastic-agent-supervised kill whose goodput "
                         "ledger must match the drill's wall-clock "
                         "arithmetic (ISSUE 15); overload: "
                         "2.5x-capacity spike, admission controller on "
                         "vs off (ISSUE 16); disagg: aborted-handoff + "
                         "prefill-specialist-kill drill (ISSUE 17); "
                         "all: every mode")
    ap.add_argument("--sites", default=None,
                    help="comma-separated site subset (default: every "
                         "site of the selected mode)")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    args = ap.parse_args(argv)

    serve_sites = list(SERVE_FAULT_SITES) + [SIGTERM_SITE]
    if args.sites:
        sites = [s for s in args.sites.split(",") if s]
        valid = set(FAULT_SITES) | {SIGTERM_SITE, FLEET_SITE,
                                    GOODPUT_SITE, OVERLOAD_SITE}
        unknown = set(sites) - valid
        if unknown:
            ap.error(f"unknown sites {sorted(unknown)}; valid: "
                     f"{sorted(valid)}")
    elif args.mode == "train":
        sites = list(TRAIN_FAULT_SITES)
    elif args.mode == "serve":
        sites = serve_sites
    elif args.mode == "fleet":
        sites = [FLEET_SITE]
    elif args.mode == "train_goodput":
        sites = [GOODPUT_SITE]
    elif args.mode == "overload":
        sites = [OVERLOAD_SITE]
    elif args.mode == "disagg":
        sites = [DISAGG_SITE]
    else:
        sites = (list(TRAIN_FAULT_SITES) + serve_sites
                 + [FLEET_SITE, GOODPUT_SITE, OVERLOAD_SITE,
                    DISAGG_SITE])
    workdir = args.workdir or tempfile.mkdtemp(prefix="dstpu_faultdrill_")

    results = [drill_fleet(workdir) if site == FLEET_SITE
               else drill_train_goodput(workdir)
               if site == GOODPUT_SITE
               else drill_overload(workdir)
               if site == OVERLOAD_SITE
               else drill_disagg(workdir)
               if site == DISAGG_SITE
               else drill_serve_site(site, workdir)
               if site in serve_sites else drill_site(site, workdir)
               for site in sites]
    ok = all(r["recovered"] for r in results)
    print(json.dumps({"ok": ok, "results": results}, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
