"""Fault drill — crash a short train loop at every injection site, then
prove it recovers.

For each site in :data:`~.fault_injection.FAULT_SITES`:

  1. run a tiny CPU train-loop worker with ``DSTPU_FAULT_SITE=<site>``
     armed (hard ``os._exit`` crash) and a once-marker file;
  2. re-run the SAME command (the marker disarms the injector — exactly
     what a supervisor restart looks like);
  3. assert the second run completes all its steps, resuming from the
     newest valid checkpoint, and that ``latest`` points at a
     validating tag.

Exit 0 only when every site both crashed and recovered. This is the CI
guard (``bin/dstpu_faultdrill``) that keeps the recovery paths in
``checkpoint/`` and ``runtime/engine.py`` honest; tier-1 runs it over a
subset via ``tests/unit/test_resilience.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
from typing import List, Optional

from .fault_injection import FAULT_SITES

#: steps the drill worker trains for; the fault fires at DRILL_FAULT_STEP
DRILL_STEPS = 5
DRILL_FAULT_STEP = 3


def _worker() -> int:
    """The drill's training worker (run in a subprocess; configured by
    env). Trains DRILL_STEPS steps on a tiny model, checkpointing every
    step; resumes from the save dir when a checkpoint exists."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, make_model

    save_dir = os.environ["DRILL_SAVE_DIR"]
    progress_file = os.environ["DRILL_PROGRESS_FILE"]

    cfg_model = GPT2Config.tiny(dtype=jnp.float32)
    model, init_fn, loss_fn = make_model(cfg_model)
    params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=17)
    engine, _, _, _ = dstpu.initialize(
        loss_fn=loss_fn, params=params, config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "steps_per_print": 1000,
        })
    engine.load_checkpoint(save_dir)

    # a comm-facade collective each step: the 'collective' site lives in
    # comm._record, which plain data-parallel GSPMD training never crosses
    # (XLA inserts its own collectives) — this is the instrumented path
    # ZeRO++/Ulysses/MoE seams use
    from jax.sharding import PartitionSpec as P

    import deepspeed_tpu.comm.comm as dcomm
    from deepspeed_tpu.utils.jax_compat import shard_map
    dp = engine.topology.axis_size("data")
    comm_probe = shard_map(
        lambda v: dcomm.all_reduce(v, "sum", axis_name="data"),
        mesh=engine.topology.mesh, in_specs=P("data"),
        out_specs=P("data"), check_vma=False)

    while engine.global_steps < DRILL_STEPS:
        rng = np.random.RandomState(engine.global_steps)
        batch = {"tokens": jnp.asarray(
            rng.randint(0, 512, size=(engine.config.train_batch_size, 18)),
            jnp.int32)}
        engine.train_batch(batch)
        engine.save_checkpoint(save_dir)
        comm_probe(jnp.ones((dp,), jnp.float32))
        with open(progress_file, "w") as f:
            json.dump({"global_steps": engine.global_steps}, f)
    return 0


def _run_worker(env: dict) -> int:
    env = dict(env)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-c",
           "import sys; from deepspeed_tpu.resilience.faultdrill import "
           "_worker; sys.exit(_worker())"]
    return subprocess.run(cmd, env=env).returncode


def drill_site(site: str, workdir: str, verbose: bool = True) -> dict:
    """Crash-then-recover drill for one site. Returns a result dict with
    ``recovered`` True/False plus diagnostics."""
    site_dir = os.path.join(workdir, site)
    os.makedirs(site_dir, exist_ok=True)
    save_dir = os.path.join(site_dir, "ckpt")
    progress_file = os.path.join(site_dir, "progress.json")
    marker = os.path.join(site_dir, "fired.marker")

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)            # single CPU device: fastest drill
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DRILL_SAVE_DIR": save_dir,
        "DRILL_PROGRESS_FILE": progress_file,
        "DSTPU_FAULT_SITE": site,
        "DSTPU_FAULT_MODE": "exit",
        "DSTPU_FAULT_STEP": str(DRILL_FAULT_STEP),
        "DSTPU_FAULT_ONCE_FILE": marker,
        # save sites: let a couple of clean saves land first so recovery
        # has a previous tag to fall back to
        "DSTPU_FAULT_SKIP": "2" if site in (
            "pre_save", "mid_save", "post_save_pre_latest") else "0",
    })

    result = {"site": site}
    rc_crash = _run_worker(env)
    result["crash_rc"] = rc_crash
    result["fault_fired"] = os.path.exists(marker)
    if rc_crash == 0 or not result["fault_fired"]:
        result["recovered"] = False
        result["error"] = ("worker did not crash — injection site never "
                           "reached")
        return result

    rc_rec = _run_worker(env)             # marker disarms the injector
    result["recover_rc"] = rc_rec
    progress = {}
    if os.path.exists(progress_file):
        with open(progress_file) as f:
            progress = json.load(f)
    result["final_steps"] = progress.get("global_steps")

    from ..checkpoint.engine_checkpoint import (
        LATEST_FILE, validate_checkpoint_dir)
    latest_ok = False
    latest_path = os.path.join(save_dir, LATEST_FILE)
    if os.path.exists(latest_path):
        with open(latest_path) as f:
            tag = f.read().strip()
        latest_ok, reason = validate_checkpoint_dir(
            os.path.join(save_dir, tag))
        result["latest_tag"] = tag
        if not latest_ok:
            result["latest_invalid"] = reason
    result["recovered"] = (rc_rec == 0
                           and progress.get("global_steps") == DRILL_STEPS
                           and latest_ok)
    if verbose:
        print(f"[faultdrill:{site}] crash_rc={rc_crash} "
              f"recover_rc={rc_rec} final_steps={result['final_steps']} "
              f"recovered={result['recovered']}", file=sys.stderr)
    return result


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="crash a short CPU train loop at each fault-injection "
                    "site and verify recovery (exit non-zero on any "
                    "unrecovered failure)")
    ap.add_argument("--sites", default=",".join(FAULT_SITES),
                    help="comma-separated site subset (default: all)")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    args = ap.parse_args(argv)

    sites = [s for s in args.sites.split(",") if s]
    unknown = set(sites) - set(FAULT_SITES)
    if unknown:
        ap.error(f"unknown sites {sorted(unknown)}; valid: {FAULT_SITES}")
    workdir = args.workdir or tempfile.mkdtemp(prefix="dstpu_faultdrill_")

    results = [drill_site(site, workdir) for site in sites]
    ok = all(r["recovered"] for r in results)
    print(json.dumps({"ok": ok, "results": results}, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
