"""Step watchdog — stall detection for hung steps / wedged collectives.

Communication-heavy schedules (ZeRO++ quantized collectives, EP all-to-all)
add collective phases per step; a wedged collective presents as a step that
simply never finishes, with no error anywhere. The reference stack leans on
torch-elastic's worker heartbeats; under single-controller SPMD the
idiomatic equivalent is an in-process heartbeat thread:

  - the engine calls :meth:`step_start` / :meth:`step_end` around each
    compiled step (and :meth:`phase` at named sub-phases);
  - the thread compares the in-flight step's age against
    ``stall_factor x`` the trailing-median step time;
  - on a stall it logs a diagnosis naming the last phase and the last
    collective recorded through ``comm._record`` (so a hung collective is
    *named*, not just implied), and — when ``action='abort'`` — hard-exits
    with ``MEMBERSHIP_CHANGE_EXIT`` so the elastic agent restarts the
    worker from the newest checkpoint.

An optional ``heartbeat_file`` receives a small JSON blob every check
interval; external supervisors (k8s liveness probes, the elastic agent)
can watch its mtime without attaching to the process.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
from collections import deque
from typing import Callable, Optional

from ..utils.logging import logger


class StepWatchdog:
    def __init__(self, stall_factor: float = 5.0,
                 check_interval_s: float = 2.0,
                 min_median_samples: int = 3,
                 min_stall_s: float = 10.0,
                 action: str = "log",
                 heartbeat_file: Optional[str] = None,
                 history: int = 64,
                 on_stall: Optional[Callable[[dict], None]] = None,
                 abort_exit_code: Optional[int] = None):
        if action not in ("log", "abort"):
            raise ValueError(f"watchdog action must be log|abort, got {action!r}")
        self.stall_factor = float(stall_factor)
        self.check_interval_s = float(check_interval_s)
        self.min_median_samples = int(min_median_samples)
        self.min_stall_s = float(min_stall_s)
        self.action = action
        self.heartbeat_file = heartbeat_file
        self.on_stall = on_stall
        if abort_exit_code is None:
            from ..elasticity.elastic_agent import MEMBERSHIP_CHANGE_EXIT
            abort_exit_code = MEMBERSHIP_CHANGE_EXIT
        self.abort_exit_code = int(abort_exit_code)

        self._durations: deque = deque(maxlen=int(history))
        self._lock = threading.Lock()
        self._step: Optional[int] = None        # in-flight step, None = idle
        self._step_t0 = 0.0
        self._last_phase = "idle"
        self._stall_reported_for: Optional[int] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="step-watchdog")
        self._thread.start()

    # ------------------------- engine-facing ------------------------- #

    def step_start(self, step: int) -> None:
        with self._lock:
            self._step = int(step)
            self._step_t0 = time.monotonic()
            self._last_phase = "step"

    def phase(self, name: str) -> None:
        with self._lock:
            self._last_phase = str(name)

    def step_end(self, step: int) -> None:
        with self._lock:
            if self._step is not None:
                self._durations.append(time.monotonic() - self._step_t0)
            self._step = None
            self._last_phase = "idle"

    def step_abort(self) -> None:
        """The step died (exception mid-step): go idle WITHOUT recording a
        duration — a stale in-flight marker would otherwise read as a
        stall forever (and action='abort' would kill a recovered
        process)."""
        with self._lock:
            self._step = None
            self._last_phase = "idle"

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    # --------------------------- the thread --------------------------- #

    def _median(self) -> Optional[float]:
        if len(self._durations) < self.min_median_samples:
            return None
        return statistics.median(self._durations)

    def check_once(self, now: Optional[float] = None) -> Optional[dict]:
        """One stall evaluation (also called directly by tests). Returns the
        diagnosis dict when a stall is detected, else None."""
        now = time.monotonic() if now is None else now
        with self._lock:
            step, t0 = self._step, self._step_t0
            phase = self._last_phase
            median = self._median()
        if step is None or median is None:
            return None
        elapsed = now - t0
        budget = max(self.stall_factor * median, self.min_stall_s)
        if elapsed <= budget or self._stall_reported_for == step:
            return None
        self._stall_reported_for = step
        from ..comm.comms_logging import last_collective
        diag = {
            "step": step,
            "elapsed_s": round(elapsed, 3),
            "median_step_s": round(median, 3),
            "stall_factor": self.stall_factor,
            "last_phase": phase,
            "last_collective": last_collective(),
            "action": self.action,
        }
        logger.error(
            f"WATCHDOG: step {step} stalled — {elapsed:.1f}s elapsed vs "
            f"median {median:.3f}s (budget {budget:.1f}s); last phase "
            f"'{phase}', last collective {diag['last_collective']}")
        try:
            # dump every live phase flight recorder (telemetry/
            # flight_recorder.py): the postmortem shows the spans leading
            # into the stall. Best-effort — a dump failure must never
            # mask the stall being reported.
            from ..telemetry.flight_recorder import auto_dump
            diag["flight_dumps"] = auto_dump("watchdog_stall")
        except Exception as e:
            logger.warning(f"watchdog flight dump failed: {e}")
        if self.on_stall is not None:
            try:
                self.on_stall(diag)
            except Exception as e:   # a broken callback must not kill the dog
                logger.warning(f"watchdog on_stall callback failed: {e}")
        if self.action == "abort":
            logger.error(f"WATCHDOG: aborting for restart "
                         f"(exit {self.abort_exit_code})")
            os._exit(self.abort_exit_code)
        return diag

    def _heartbeat(self) -> None:
        if not self.heartbeat_file:
            return
        with self._lock:
            blob = {
                "time": time.time(),
                "in_step": self._step,
                "last_phase": self._last_phase,
                "steps_recorded": len(self._durations),
                "median_step_s": self._median(),
            }
        try:
            tmp = self.heartbeat_file + ".tmp"
            with open(tmp, "w") as f:
                json.dump(blob, f)
            os.replace(tmp, self.heartbeat_file)
        except OSError as e:
            logger.warning(f"watchdog heartbeat write failed: {e}")

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            try:
                self._heartbeat()
                self.check_once()
            except Exception as e:    # never let the watchdog thread die
                logger.warning(f"watchdog check failed: {e}")
