"""Preemption grace handling — SIGTERM → final checkpoint → elastic exit.

TPU preemption (spot/maintenance) delivers SIGTERM with a short grace
window. The flow here mirrors the reference's elastic story (torn-down
workers resume from the newest checkpoint via ``DSElasticAgent``):

  1. :class:`PreemptionHandler` installs signal handlers that only set a
     flag (signal-safe; the previous handler is chained);
  2. the engine polls the flag at the step boundary — the only point where
     ``TrainState`` is consistent — performs an *urgent save*, and exits
     with ``MEMBERSHIP_CHANGE_EXIT``;
  3. the elastic agent (``elasticity/elastic_agent.py``) treats that exit
     as a cooperative membership change and re-launches against the
     surviving device set; ``load_checkpoint`` restores the exact
     ``global_steps`` / optimizer state.
"""

from __future__ import annotations

import signal
import threading
from typing import Iterable, Optional

from ..utils.logging import logger

_DEFAULT_SIGNALS = ("SIGTERM",)


def _resolve(name) -> signal.Signals:
    if isinstance(name, str):
        return getattr(signal.Signals, name)
    return signal.Signals(name)


class PreemptionHandler:
    """Flag-setting signal handler with chaining and manual triggering.

    Handlers can only be installed from the main thread (CPython rule);
    installation from another thread degrades to manual-only mode
    (:meth:`request` still works) with a warning.
    """

    def __init__(self, signals: Iterable = _DEFAULT_SIGNALS):
        self._event = threading.Event()
        self._signal: Optional[int] = None
        self._previous = {}
        self._installed = False
        sigs = [_resolve(s) for s in signals]
        if threading.current_thread() is threading.main_thread():
            for sig in sigs:
                self._previous[sig] = signal.signal(sig, self._on_signal)
            self._installed = True
        else:
            logger.warning(
                "PreemptionHandler built off the main thread: signal "
                "handlers not installed; only request() will trigger it")

    def _on_signal(self, signum, frame):
        self._signal = signum
        self._event.set()
        logger.warning(f"preemption signal {signal.Signals(signum).name} "
                       f"received — will checkpoint at the step boundary")
        prev = self._previous.get(signal.Signals(signum))
        if callable(prev):
            prev(signum, frame)

    # ------------------------------------------------------------------ #

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    @property
    def signal_received(self) -> Optional[int]:
        return self._signal

    def request(self) -> None:
        """Trigger preemption without a real signal (tests, external
        schedulers that know the deadline out-of-band)."""
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def uninstall(self) -> None:
        """Restore the previous handlers (tests must not leak handlers)."""
        if not self._installed:
            return
        for sig, prev in self._previous.items():
            try:
                signal.signal(sig, prev if prev is not None else signal.SIG_DFL)
            except (ValueError, TypeError):
                pass
        self._previous.clear()
        self._installed = False
