"""Resilience layer: fault injection, step watchdog, preemption grace,
restart ledger. See docs/resilience.md for the failure model and the
recovery guarantees each piece provides."""

from .fault_injection import (
    DISAGG_FAULT_SITE,
    FAULT_SITES,
    SERVE_FAULT_SITES,
    TRAIN_FAULT_SITES,
    FaultInjector,
    InjectedFault,
    get_fault_injector,
    set_fault_injector,
)
from .ledger import RestartLedger
from .preemption import PreemptionHandler
from .watchdog import StepWatchdog

__all__ = [
    "DISAGG_FAULT_SITE",
    "FAULT_SITES",
    "SERVE_FAULT_SITES",
    "TRAIN_FAULT_SITES",
    "FaultInjector",
    "InjectedFault",
    "get_fault_injector",
    "set_fault_injector",
    "RestartLedger",
    "PreemptionHandler",
    "StepWatchdog",
]
