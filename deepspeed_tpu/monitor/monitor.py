"""Experiment monitors.

Analogue of the reference's ``deepspeed/monitor/`` (`MonitorMaster`
``monitor/monitor.py:30`` fanning out to TensorBoard/W&B/CSV/Comet writers).
Same event shape: ``write_events([(tag, value, step), ...])``, rank-0 only.
"""

from __future__ import annotations

import csv
import os
from typing import List, Optional, Tuple

from ..config.config import Config
from ..utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    enabled = False

    def write_events(self, events: List[Event]) -> None:
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    def __init__(self, cfg):
        self.enabled = False
        try:
            from torch.utils.tensorboard import SummaryWriter  # torch cpu is baked in
            path = os.path.join(cfg.output_path or "runs", cfg.job_name)
            self.summary_writer = SummaryWriter(log_dir=path)
            self.enabled = True
        except Exception as e:
            logger.warning(f"TensorBoard monitor unavailable: {e}")

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for tag, value, step in events:
            self.summary_writer.add_scalar(tag, value, step)
        self.summary_writer.flush()


class CSVMonitor(Monitor):
    def __init__(self, cfg):
        self.output_path = os.path.join(cfg.output_path or "csv_logs", cfg.job_name)
        os.makedirs(self.output_path, exist_ok=True)
        # per-tag open handles, kept for the monitor's lifetime: a
        # telemetry bridge emits dozens of tags per interval, and
        # reopening each file per event turned every snapshot into
        # O(tags) open/close syscalls
        self._files = {}
        self.enabled = True

    def _file(self, tag: str):
        f = self._files.get(tag)
        if f is None:
            fname = os.path.join(self.output_path,
                                 tag.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            f = open(fname, "a", newline="")
            if new:
                csv.writer(f).writerow(["step", tag])
            self._files[tag] = f
        return f

    def write_events(self, events: List[Event]) -> None:
        for tag, value, step in events:
            f = self._file(tag)
            csv.writer(f).writerow([step, value])
            f.flush()

    def close(self) -> None:
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        self._files.clear()


class WandbMonitor(Monitor):
    def __init__(self, cfg):
        self.enabled = False
        try:
            import wandb
            wandb.init(project=cfg.project, group=cfg.group, entity=cfg.team)
            self._wandb = wandb
            self.enabled = True
        except Exception as e:
            logger.warning(f"wandb monitor unavailable: {e}")

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for tag, value, step in events:
            self._wandb.log({tag: value}, step=step)


class CometMonitor(Monitor):
    def __init__(self, cfg):
        self.enabled = False
        try:
            import comet_ml
            self.experiment = comet_ml.Experiment(
                api_key=cfg.api_key, project_name=cfg.project, workspace=cfg.workspace)
            if cfg.experiment_name:
                self.experiment.set_name(cfg.experiment_name)
            self.enabled = True
        except Exception as e:
            logger.warning(f"comet monitor unavailable: {e}")

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for tag, value, step in events:
            self.experiment.log_metric(tag, value, step=step)


class MonitorMaster(Monitor):
    """Multiplexes events to every enabled writer (reference monitor.py:30)."""

    def __init__(self, config: Config):
        self.writers: List[Monitor] = []
        import jax
        if jax.process_index() != 0:
            self.enabled = False
            return
        if config.tensorboard.enabled:
            self.writers.append(TensorBoardMonitor(config.tensorboard))
        if config.csv_monitor.enabled:
            self.writers.append(CSVMonitor(config.csv_monitor))
        if config.wandb.enabled:
            self.writers.append(WandbMonitor(config.wandb))
        if config.comet.enabled:
            self.writers.append(CometMonitor(config.comet))
        self.enabled = any(w.enabled for w in self.writers)

    def write_events(self, events: List[Event]) -> None:
        for w in self.writers:
            if w.enabled:
                w.write_events(events)
