from .monitor import MonitorMaster, TensorBoardMonitor, CSVMonitor, WandbMonitor, CometMonitor
