"""Multi-experiment resource scheduler for the autotuner.

Parity with the reference's ``autotuning/scheduler.py`` ``ResourceManager``
(the 2.7k-LoC subsystem VERDICT r4 flagged as the remaining autotuning
gap): the reference forks the USER TRAINING SCRIPT once per candidate
config across a pool of nodes, polls for completion, and reads back each
experiment's metrics file. The TPU translation keeps exactly that
launch-and-collect contract — an experiment is one subprocess (local, or
``ssh host`` for hostfile entries) running the user's command with

  DSTPU_AT_CONFIG  = path to the candidate ds_config JSON
  DSTPU_AT_METRICS = path the script must write its metrics JSON to

and at most one experiment per host at a time (a TPU host's chips are
exclusive — slots-per-host is meaningless here, unlike the reference's
GPU-count slots). ``report_metrics`` is the helper scripts call to emit
the metrics file the scheduler collects.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import time
from typing import Any, Dict, List, Optional, Sequence

from ..utils.logging import log_dist, logger


def report_metrics(metrics: Dict[str, Any],
                   path: Optional[str] = None) -> None:
    """Write the experiment's metrics JSON where the scheduler (or the
    caller) asked for it. Training scripts run under the ResourceManager
    call this once after their measured steps; ``score`` is the field the
    tuner maximizes (fall back: ``throughput``)."""
    path = path or os.environ.get("DSTPU_AT_METRICS")
    if not path:
        logger.warning("report_metrics: no DSTPU_AT_METRICS path; skipped")
        return
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(metrics, f)
    os.replace(tmp, path)                      # atomic publish


@dataclasses.dataclass
class _Run:
    exp: Any                                    # autotuner.Experiment
    proc: subprocess.Popen
    host: Optional[str]
    exp_dir: str
    started: float
    log: Any


class ResourceManager:
    """Schedules experiment launches over a host pool.

    ``cmd``: the user training command (list of argv strings) — it reads
    ``DSTPU_AT_CONFIG`` and writes ``DSTPU_AT_METRICS``.
    ``hosts``: hostnames to ``ssh`` into; None/[] = run locally. With N
    hosts, N experiments run concurrently (one per host).
    ``exp_timeout``: per-experiment wall budget in seconds; expired
    experiments are killed and marked failed (a stuck candidate must not
    stall the sweep — reference scheduler.py experiment timeout).
    """

    def __init__(self, cmd: Sequence[str],
                 hosts: Optional[Sequence[str]] = None,
                 exp_dir: str = "autotuning_exps",
                 exp_timeout: float = 1800.0,
                 max_parallel: Optional[int] = None):
        self.cmd = list(cmd)
        if hosts:
            self.hosts: List[Optional[str]] = list(hosts)
            if max_parallel:
                self.hosts = self.hosts[:max_parallel]
        else:
            # local mode: max_parallel slots on this host (CPU-mesh sweeps
            # parallelize; a real TPU host is exclusive — leave it at 1)
            self.hosts = [None] * (max_parallel or 1)
        self.exp_dir = exp_dir
        self.exp_timeout = float(exp_timeout)

    # ------------------------------------------------------------------ #

    def _launch(self, exp, idx: int, host: Optional[str],
                base_config: Dict[str, Any]) -> _Run:
        from .autotuner import _apply_overrides
        d = os.path.join(self.exp_dir, f"exp_{idx:04d}")
        os.makedirs(d, exist_ok=True)
        cfg_path = os.path.join(d, "ds_config.json")
        cfg = _apply_overrides(base_config, exp.overrides)
        # same strip as the in-process runner (autotuner._run_experiment):
        # the candidate micro batch re-derives the batch math; stale
        # train_batch_size/gas from the base config would fail the
        # engine's batch-size invariant for every candidate
        cfg.pop("autotuning", None)
        if "train_micro_batch_size_per_gpu" in exp.overrides:
            cfg.pop("train_batch_size", None)
            cfg.pop("gradient_accumulation_steps", None)
        with open(cfg_path, "w") as f:
            json.dump(cfg, f, indent=2)
        with open(os.path.join(d, "overrides.json"), "w") as f:
            json.dump(exp.overrides, f)
        metrics_path = os.path.join(d, "metrics.json")
        env = {**os.environ,
               "DSTPU_AT_CONFIG": os.path.abspath(cfg_path),
               "DSTPU_AT_METRICS": os.path.abspath(metrics_path)}
        if host is None:
            argv = self.cmd
        else:
            # hostfile entry: env rides the ssh command line (the remote
            # shell does not inherit ours) — reference runner ssh pattern.
            # shlex-quoted against spaces/metachars, and wrapped in a
            # remote-side `timeout` so killing the local ssh client can
            # never strand a compute-bound process on the TPU host
            import shlex
            exports = " ".join(
                f"{k}={shlex.quote(env[k])}" for k in ("DSTPU_AT_CONFIG",
                                                       "DSTPU_AT_METRICS"))
            remote = (f"{exports} timeout {int(self.exp_timeout) + 30} "
                      + " ".join(shlex.quote(c) for c in self.cmd))
            argv = ["ssh", host, remote]
        log = open(os.path.join(d, "stderr.log"), "w")
        proc = subprocess.Popen(argv, env=env, stdout=log, stderr=log)
        log_dist(f"autotuning exp {idx} -> "
                 f"{host or 'local'}: {exp.overrides}")
        return _Run(exp=exp, proc=proc, host=host, exp_dir=d,
                    started=time.time(), log=log)

    def _collect(self, run: _Run, metric: str) -> None:
        from .autotuner import FAILED, OK
        run.log.close()
        metrics_path = os.path.join(run.exp_dir, "metrics.json")
        if run.proc.returncode != 0:
            run.exp.status = FAILED
            run.exp.error = f"rc={run.proc.returncode}"
            return
        if not os.path.exists(metrics_path):
            run.exp.status = FAILED
            run.exp.error = "no metrics.json written"
            return
        with open(metrics_path) as f:
            metrics = json.load(f)
        run.exp.metrics = metrics
        # honor the configured metric: named key first ('latency' scores
        # negated — lower is better), then the generic fallbacks. A file
        # with NONE of the keys is a failed experiment, not an OK with
        # -inf (which would silently poison best())
        if metric in metrics:
            v = float(metrics[metric])
            run.exp.score = -v if metric == "latency" else v
        elif "score" in metrics:
            run.exp.score = float(metrics["score"])
        elif "throughput" in metrics:
            run.exp.score = float(metrics["throughput"])
        else:
            run.exp.status = FAILED
            run.exp.error = (f"metrics.json has none of "
                             f"['{metric}', 'score', 'throughput']")
            return
        run.exp.status = OK

    def run(self, experiments: List[Any],
            base_config: Dict[str, Any],
            metric: str = "throughput") -> List[Any]:
        """Run every experiment to completion (one per host at a time);
        mutates and returns the Experiment records. ``metric`` names the
        metrics-file key the tuner maximizes (``latency`` is negated)."""
        from .autotuner import FAILED
        os.makedirs(self.exp_dir, exist_ok=True)
        pending = list(enumerate(experiments))
        running: Dict[int, _Run] = {}            # keyed by host SLOT
        while pending or running:
            # fill free slots
            for slot, host in enumerate(self.hosts):
                if slot in running or not pending:
                    continue
                idx, exp = pending.pop(0)
                running[slot] = self._launch(exp, idx, host, base_config)
            # poll
            time.sleep(0.05)
            for slot, run in list(running.items()):
                if run.proc.poll() is not None:
                    self._collect(run, metric)
                    del running[slot]
                elif time.time() - run.started > self.exp_timeout:
                    run.proc.kill()
                    run.proc.wait()
                    run.log.close()
                    run.exp.status = FAILED
                    run.exp.error = f"timeout_{self.exp_timeout:.0f}s"
                    del running[slot]
        return experiments
