from .autotuner import Autotuner, Experiment
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner, build_tuner
