from .autotuner import Autotuner, Experiment
from .scheduler import ResourceManager, report_metrics
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner, build_tuner
