"""Autotuner — search ZeRO stage × micro-batch (× user dimensions) for the
fastest configuration that fits memory.

Parity with the reference's ``Autotuner`` (``autotuning/autotuner.py:42``,
``tune:404``) and its experiment scheduler (``autotuning/scheduler.py``
``ResourceManager``): the reference forks launcher jobs per experiment and
reads back metrics files; on TPU a single-controller process can build the
engine in-process per candidate, so the "scheduler" is a sequential (or
user-parallelized) experiment loop with the same record/prune/early-stop
semantics. The reference's model-info profile run (peak activation memory at
micro-batch 1) maps to XLA's compile-time memory analysis: candidates whose
``compiled.memory_analysis()`` exceeds the device budget are pruned without
running a step.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..utils.logging import log_dist, logger
from .tuner import build_tuner

FAILED = "failed"
PRUNED = "pruned_oom"
OK = "ok"


@dataclasses.dataclass
class Experiment:
    overrides: Dict[str, Any]
    status: str = "pending"
    score: float = float("-inf")
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)
    error: str = ""


def _apply_overrides(config: Dict[str, Any], overrides: Dict[str, Any]) -> Dict[str, Any]:
    out = json.loads(json.dumps(config))  # deep copy, JSON-typed
    for key, value in overrides.items():
        node = out
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return out


def _device_memory_budget() -> Optional[int]:
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001 — backend without memory stats
        pass
    return None


class Autotuner:
    """Tunes engine configuration for a given model.

    Args:
      loss_fn/params: as for ``deepspeed_tpu.initialize``.
      base_config: ds_config dict; its ``autotuning`` block steers the search.
      batch_fn: ``(batch_size) -> batch pytree`` producing training batches.
    """

    def __init__(self, loss_fn: Callable, params: Any,
                 base_config: Dict[str, Any], batch_fn: Callable[[int], Any],
                 resource_manager: Any = None):
        self.loss_fn = loss_fn
        self.params = params
        self.base_config = dict(base_config)
        self.batch_fn = batch_fn
        # multi-experiment launch mode (reference scheduler.py
        # ResourceManager): experiments run as user-script subprocesses
        # over a host pool instead of in-process engine builds
        self.resource_manager = resource_manager
        # single source of defaults: the AutotuningConfig dataclass
        from ..config.config import AutotuningConfig
        at = self.base_config.get("autotuning", {})
        cfg = at if isinstance(at, AutotuningConfig) else \
            AutotuningConfig.from_dict(dict(at))
        self.metric = cfg.metric
        self.tuner_type = cfg.tuner_type
        self.early_stopping = int(cfg.tuner_early_stopping)
        self.num_trials = int(cfg.tuner_num_trials)
        self.fast = bool(cfg.fast)
        self.mbs_min = int(cfg.min_train_micro_batch_size_per_gpu)
        self.mbs_max = int(cfg.max_train_micro_batch_size_per_gpu)
        self.num_mbs = int(cfg.num_tuning_micro_batch_sizes)
        self.profile_steps = (int(cfg.start_profile_step),
                              int(cfg.end_profile_step))
        self.results_dir = cfg.results_dir
        self.user_space = dict(cfg.tuning_space or {})
        self.experiments: List[Experiment] = []

    # ------------------------------ space ------------------------------ #

    def search_space(self) -> List[Dict[str, Any]]:
        """ZeRO stages × micro-batch powers of two × user dimensions."""
        stages = self.user_space.get("zero_optimization.stage", [0, 1, 2, 3])
        if self.fast:
            stages = [s for s in stages if s in (0, 1, 2)] or stages
        mbs = []
        m = self.mbs_min
        while m <= self.mbs_max and len(mbs) < self.num_mbs:
            mbs.append(m)
            m *= 2
        extra_keys = [k for k in self.user_space
                      if k != "zero_optimization.stage"]
        cands = []
        for stage in stages:
            for mb in mbs:
                base = {"zero_optimization.stage": stage,
                        "train_micro_batch_size_per_gpu": mb}
                stack = [base]
                for key in extra_keys:
                    stack = [dict(c, **{key: v}) for c in stack
                             for v in self.user_space[key]]
                cands.extend(stack)
        return cands

    # --------------------------- experiments --------------------------- #

    def _run_experiment(self, overrides: Dict[str, Any]) -> Experiment:
        import deepspeed_tpu as dstpu
        exp = Experiment(overrides=overrides)
        cfg = _apply_overrides(self.base_config, overrides)
        cfg.pop("autotuning", None)
        cfg.pop("train_batch_size", None)   # re-derive from micro batch
        cfg.pop("gradient_accumulation_steps", None)
        try:
            engine, _, _, _ = dstpu.initialize(
                loss_fn=self.loss_fn, params=self.params, config=cfg)
        except Exception as e:  # noqa: BLE001 — invalid candidate
            exp.status, exp.error = FAILED, repr(e)
            return exp
        try:
            budget = _device_memory_budget()
            batch = self.batch_fn(engine.config.train_batch_size)
            warmup, measure = self.profile_steps
            # memory prune before stepping (reference model-info profile run)
            if budget is not None:
                try:
                    analysis = engine._train_step.lower(
                        engine.state, batch).compile().memory_analysis()
                    need = getattr(analysis, "temp_size_in_bytes", 0) + \
                        getattr(analysis, "argument_size_in_bytes", 0)
                    if need > budget:
                        exp.status = PRUNED
                        exp.metrics["estimated_bytes"] = float(need)
                        return exp
                except Exception:  # noqa: BLE001 — lowering w/o analysis
                    pass
            for _ in range(warmup):
                engine.train_batch(batch)
            jax.block_until_ready(engine.state.params)
            t0 = time.perf_counter()
            for _ in range(measure):
                engine.train_batch(batch)
            jax.block_until_ready(engine.state.params)
            dt = (time.perf_counter() - t0) / measure
            tput = engine.config.train_batch_size / dt
            exp.metrics = {"samples_per_sec": tput, "step_latency_s": dt}
            exp.score = tput if self.metric == "throughput" else -dt
            exp.status = OK
        except Exception as e:  # noqa: BLE001 — OOM / compile failure
            exp.status, exp.error = FAILED, repr(e)
        return exp

    # ------------------------------ tune ------------------------------- #

    def tune(self) -> Dict[str, Any]:
        """Run the search; returns the best overrides (written to
        ``results_dir/best_config.json`` with the full experiment log)."""
        space = self.search_space()
        tuner = build_tuner(self.tuner_type, space)
        log_dist(f"autotuning: {len(space)} candidates, tuner="
                 f"{self.tuner_type}, metric={self.metric}")
        if self.resource_manager is not None:
            return self._tune_scheduled(space, tuner)
        since_best = 0
        best_score = float("-inf")
        for trial in range(min(self.num_trials, len(space))):
            cand = tuner.next()
            if cand is None:
                break
            exp = self._run_experiment(cand)
            self.experiments.append(exp)
            tuner.update(cand, exp.score)
            log_dist(f"autotuning trial {trial}: {cand} -> {exp.status} "
                     f"score={exp.score:.2f}")
            if exp.score > best_score:
                best_score, since_best = exp.score, 0
            else:
                since_best += 1
                if since_best >= self.early_stopping:
                    log_dist(f"autotuning early stop after {trial + 1} trials")
                    break
        best, score = tuner.best()
        self._write_results(best, score)
        return best or {}

    def _tune_scheduled(self, space, tuner) -> Dict[str, Any]:
        """Scheduler mode: propose wave-sized batches of candidates from
        the tuner and launch them over the ResourceManager's host pool
        (reference autotuner.run_tuning + scheduler.run_job — experiments
        run in parallel up to the pool size; the tuner sees every wave's
        scores before proposing the next)."""
        wave = max(1, len(self.resource_manager.hosts))
        remaining = min(self.num_trials, len(space))
        since_best = 0
        best_score = float("-inf")
        while remaining > 0:
            cands = []
            for _ in range(min(wave, remaining)):
                c = tuner.next()
                if c is None:
                    break
                # tentative mark so the tuner proposes DISTINCT candidates
                # within one wave (update() appends; the real score lands
                # after the wave, and -inf placeholders are ignored by
                # best() / the model fit)
                tuner.update(c, float("-inf"))
                cands.append(c)
            if not cands:
                break
            exps = [Experiment(overrides=c) for c in cands]
            self.resource_manager.run(exps, self.base_config,
                                      metric=self.metric)
            for cand, exp in zip(cands, exps):
                self.experiments.append(exp)
                tuner.update(cand, exp.score)
                log_dist(f"autotuning exp: {cand} -> {exp.status} "
                         f"score={exp.score:.2f}")
                if exp.score > best_score:
                    best_score, since_best = exp.score, 0
                else:
                    since_best += 1
            remaining -= len(cands)
            if since_best >= self.early_stopping:
                log_dist("autotuning early stop (scheduled mode)")
                break
        best, score = tuner.best()
        self._write_results(best, score)
        return best or {}

    def _write_results(self, best, score) -> None:
        os.makedirs(self.results_dir, exist_ok=True)
        with open(os.path.join(self.results_dir, "best_config.json"), "w") as f:
            json.dump({
                "best_overrides": best,
                "score": score,
                "metric": self.metric,
                "experiments": [dataclasses.asdict(e) for e in self.experiments],
            }, f, indent=2, default=str)
        log_dist(f"autotuning: best {best} (score {score:.2f}) -> "
                 f"{self.results_dir}/best_config.json")
