"""Tuner strategies — grid / random / model-based.

Parity with the reference's ``autotuning/tuner/`` (``GridSearchTuner``,
``RandomTuner``, ``ModelBasedTuner`` — the last an xgboost cost model): a
tuner proposes the next candidate from the search space given the scores
observed so far. The model-based tuner here fits a least-squares cost model
over the numeric features of the measured points (no xgboost dependency) and
ranks untried candidates by predicted score — same explore-then-exploit
shape, dependency-free.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class BaseTuner:
    def __init__(self, space: Sequence[Dict[str, Any]], seed: int = 0):
        self.space = list(space)
        self.observed: List[Tuple[Dict[str, Any], float]] = []
        self._rng = random.Random(seed)

    def next(self) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def update(self, candidate: Dict[str, Any], score: float) -> None:
        self.observed.append((candidate, score))

    def _untried(self) -> List[Dict[str, Any]]:
        seen = [c for c, _ in self.observed]
        return [c for c in self.space if c not in seen]

    def best(self) -> Tuple[Optional[Dict[str, Any]], float]:
        if not self.observed:
            return None, float("-inf")
        return max(self.observed, key=lambda cs: cs[1])


class GridSearchTuner(BaseTuner):
    def next(self):
        rest = self._untried()
        return rest[0] if rest else None


class RandomTuner(BaseTuner):
    def next(self):
        rest = self._untried()
        return self._rng.choice(rest) if rest else None


class ModelBasedTuner(BaseTuner):
    """Explore ``n_warmup`` random points, then exploit a least-squares cost
    model over numeric candidate features."""

    def __init__(self, space, seed: int = 0, n_warmup: int = 3):
        super().__init__(space, seed)
        self.n_warmup = n_warmup

    def _features(self, cand: Dict[str, Any]) -> List[float]:
        out = []
        for key in sorted({k for c in self.space for k in c}):
            v = cand.get(key, 0)
            if isinstance(v, bool):
                out.append(float(v))
            elif isinstance(v, (int, float)):
                out.append(float(v))
                out.append(float(np.log2(max(abs(v), 1))))
            else:
                out.append(float(abs(hash(str(v))) % 7))
        return out + [1.0]

    def next(self):
        rest = self._untried()
        if not rest:
            return None
        finite = [(c, s) for c, s in self.observed if np.isfinite(s)]
        if len(finite) < self.n_warmup:
            return self._rng.choice(rest)
        X = np.asarray([self._features(c) for c, _ in finite])
        y = np.asarray([s for _, s in finite])
        coef, *_ = np.linalg.lstsq(X, y, rcond=None)
        preds = [(float(np.asarray(self._features(c)) @ coef), c)
                 for c in rest]
        return max(preds, key=lambda pc: pc[0])[1]


def build_tuner(name: str, space, seed: int = 0) -> BaseTuner:
    name = (name or "gridsearch").lower()
    if name in ("gridsearch", "grid"):
        return GridSearchTuner(space, seed)
    if name == "random":
        return RandomTuner(space, seed)
    if name in ("model_based", "modelbased", "xgboost"):
        return ModelBasedTuner(space, seed)
    raise ValueError(f"unknown tuner_type '{name}'")
