from .elasticity import (
    ElasticityError,
    ElasticityConfigError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    ensure_immutable_elastic_config,
)
from .elastic_agent import run_elastic
