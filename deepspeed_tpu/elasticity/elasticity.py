"""Elastic batch-size computation.

Capability parity with the reference's ``elasticity/elasticity.py``
(``compute_elastic_config:233``, v0.1 ``:83`` / v0.2 ``:126`` algorithms,
``ensure_immutable_elastic_config:208`` — SURVEY.md §5 "Failure detection /
elastic recovery"): given allowed micro-batch sizes and a max acceptable
global batch, pick the global batch size compatible with the *largest set of
device counts*, so the scheduler can scale the job up/down without touching
convergence (global batch stays fixed; micro×GAS×dp re-factorizes).

v0.1 searches batch sizes built by scaling each micro-batch (and their LCM)
to the nearest highly-composite multiple. v0.2 works at node granularity
with a fixed ``model_parallel_size`` and ``num_gpus_per_node`` (here:
chips per host), and also returns the chosen micro-batch.

The TPU difference is terminological only — "gpus" are chips — so the knob
names keep ds_config spelling.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.logging import logger

#: smallest highly composite numbers — enough for ~720K batch sizes
#: (the reference uses the same table; it is a mathematical constant list)
_HCN = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260,
    1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360,
    50400, 55440, 83160, 110880, 166320, 221760, 277200, 332640, 498960,
    554400, 665280, 720720,
]

#: env var carrying the scheduler's view of the elastic config
ELASTICITY_ENV = "DSTPU_ELASTICITY_CONFIG"


class ElasticityError(RuntimeError):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


def _lcm(values: Sequence[int]) -> int:
    out = 1
    for v in values:
        out = out * v // math.gcd(out, v)
    return out


def _candidate_batch_sizes(bases: Sequence[int], max_batch: int) -> List[int]:
    """Scale each base to the largest HCN multiple <= max_batch."""
    out = set()
    for base in bases:
        if base >= max_batch:
            out.add(base)
            continue
        limit = max_batch // base
        hcn = max(h for h in _HCN if h <= limit)
        out.add(hcn * base)
    return sorted(out)


def _valid_device_counts(batch: int, micro_batches: Sequence[int],
                         lo: int, hi: int) -> List[int]:
    """All device counts in [lo, hi] for which batch = micro*gas*n works."""
    valid = set()
    for mb in micro_batches:
        if batch % mb:
            continue
        slots = batch // mb          # micro-batches per global batch
        for n in range(1, int(math.isqrt(slots)) + 1):
            if slots % n == 0:
                for d in (n, slots // n):
                    if lo <= d <= hi:
                        valid.add(d)
    return sorted(valid)


def _best_batch(micro_batches: Sequence[int], max_batch: int, lo: int,
                hi: int, prefer_larger: bool) -> Tuple[int, List[int]]:
    if any(mb > max_batch for mb in micro_batches):
        raise ElasticityConfigError(
            f"every micro batch must be <= max_acceptable_batch_size "
            f"({max_batch}); got {list(micro_batches)}")
    bases = list(micro_batches) + [_lcm(micro_batches)]
    best = (min(micro_batches), [])
    for cand in _candidate_batch_sizes(bases, max_batch):
        counts = _valid_device_counts(cand, micro_batches, lo, hi)
        better = len(counts) > len(best[1]) or (
            len(counts) == len(best[1]) and
            (cand > best[0] if prefer_larger else cand < best[0]))
        if better:
            best = (cand, counts)
    return best


def _v01(micro_batches, max_batch, min_dev=None, max_dev=None,
         prefer_larger=True):
    min_dev = min_dev or 1
    max_dev = max_dev or max_batch // min(micro_batches)
    return _best_batch(micro_batches, max_batch, min_dev, max_dev,
                       prefer_larger)


def _v02(micro_batches, max_batch, current_devices, min_dev, max_dev,
         prefer_larger=True, devices_per_node=1, model_parallel_size=1):
    if devices_per_node % model_parallel_size:
        raise ElasticityError(
            f"num_gpus_per_node ({devices_per_node}) must be divisible by "
            f"model_parallel_size ({model_parallel_size}) in elasticity v0.2")
    dp_per_node = devices_per_node // model_parallel_size

    current_dp_ranks = max(1, current_devices // model_parallel_size)

    def pick_micro(batch: int) -> Optional[int]:
        # the micro batch must divide the per-DP-RANK batch (model-parallel
        # ranks share samples, they don't add batch slots)
        chosen = None
        for mb in micro_batches:
            if (batch // current_dp_ranks) % mb == 0:
                if chosen is None or (prefer_larger and mb > chosen):
                    chosen = mb
        return chosen

    batch, node_counts = _v01(
        micro_batches, max_batch // dp_per_node,
        max(1, min_dev // devices_per_node),
        max(1, max_dev // devices_per_node), prefer_larger)
    batch *= dp_per_node
    dp_counts = [n * dp_per_node for n in node_counts]
    if current_devices // model_parallel_size in dp_counts:
        return batch, dp_counts, pick_micro(batch)

    # current allocation not in the preferred set: fit a batch to it
    current_dp = (current_devices // devices_per_node) * dp_per_node
    fitted = [mb * current_dp * (max_batch // (mb * current_dp))
              for mb in micro_batches if mb * current_dp <= max_batch]
    if not fitted:
        raise ElasticityIncompatibleWorldSize(
            f"no micro batch in {list(micro_batches)} fits "
            f"{current_devices} devices under max batch {max_batch}")
    batch = max(fitted) if prefer_larger else min(fitted)
    return batch, [current_dp], pick_micro(batch)


def compute_elastic_config(config, world_size: int = 0,
                           return_microbatch: bool = False):
    """Compute (final_batch_size, valid_device_counts[, micro_batch]).

    ``config`` is a Config, an ElasticityConfig, or a ds_config-style dict
    with an ``elasticity`` block. When ``world_size`` > 0 the current world
    must be in the valid set (raises ElasticityIncompatibleWorldSize
    otherwise) and the per-world micro-batch is resolved.
    """
    ecfg = _as_elastic_cfg(config)
    if not ecfg["enabled"]:
        raise ElasticityConfigError("elasticity block is not enabled")
    micro = list(ecfg["micro_batch_sizes"])
    if not micro or any(m <= 0 for m in micro):
        raise ElasticityConfigError(
            f"micro_batch_sizes must be positive: {micro}")
    version = float(ecfg["version"])
    if version >= 0.2:
        ws = world_size or ecfg["num_gpus_per_node"]
        batch, counts, mb = _v02(
            micro, ecfg["max_train_batch_size"], ws,
            ecfg["min_gpus"], ecfg["max_gpus"],
            devices_per_node=ecfg["num_gpus_per_node"],
            model_parallel_size=ecfg["model_parallel_size"])
    else:
        batch, counts = _v01(micro, ecfg["max_train_batch_size"],
                             ecfg["min_gpus"], ecfg["max_gpus"])
        mb = None

    if world_size > 0:
        dp = world_size // ecfg["model_parallel_size"]
        if dp not in counts:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} (dp {dp}) not in the elastic set "
                f"{counts} for batch {batch}")
        if mb is None:
            per = batch // dp
            fits = [m for m in micro if per % m == 0]
            if not fits:
                raise ElasticityIncompatibleWorldSize(
                    f"no configured micro batch divides {per} "
                    f"(batch {batch} over dp {dp})")
            mb = max(fits)
    if return_microbatch:
        return batch, counts, mb
    return batch, counts


def ensure_immutable_elastic_config(runtime_cfg) -> None:
    """Fail if the scheduler launched this job under a different elastic
    config than the runtime sees (env ``DSTPU_ELASTICITY_CONFIG``)."""
    if ELASTICITY_ENV not in os.environ:
        logger.warning(
            f"{ELASTICITY_ENV} not set; cannot guarantee the resource "
            "scheduler scales this job with compatible device counts")
        return
    sched = json.loads(os.environ[ELASTICITY_ENV])
    run = _as_elastic_cfg(runtime_cfg)
    for key in ("max_train_batch_size", "micro_batch_sizes", "version"):
        sv = sched.get(key)
        if sv is not None and sv != run[key]:
            raise ElasticityConfigError(
                f"elastic config mismatch: scheduler saw {key}={sv}, "
                f"runtime has {key}={run[key]}")


def _as_elastic_cfg(config) -> Dict:
    if isinstance(config, dict):
        block = config.get("elasticity", config)
        get = block.get
    else:
        block = getattr(config, "elasticity", config)
        get = lambda k, d=None: getattr(block, k, d)  # noqa: E731
    return {
        "enabled": bool(get("enabled", False)),
        "max_train_batch_size": int(get("max_train_batch_size", 2000)),
        "micro_batch_sizes": list(get("micro_batch_sizes", [2, 4, 6])),
        "min_gpus": int(get("min_gpus", 1)),
        "max_gpus": int(get("max_gpus", 10000)),
        "version": float(get("version", 0.2)),
        "num_gpus_per_node": int(get("num_gpus_per_node", 1)),
        "model_parallel_size": int(get("model_parallel_size", 1)),
    }
