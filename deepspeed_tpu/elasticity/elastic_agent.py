"""Elastic restart agent.

Parity with the reference's ``DSElasticAgent`` (``elasticity/elastic_agent.py:32``,
a torch-elastic ``LocalElasticAgent`` subclass that re-spawns workers on
membership change). TPU SPMD has one process per host and no in-band rank
rendezvous to re-form, so the idiomatic equivalent is a **supervisor loop**:
run the training command; on failure (or an explicit membership-change exit
code) re-launch it against the currently-available device/host set, with the
elastic config pinned in the environment (``ensure_immutable_elastic_config``
checks it runtime-side) — recovery is checkpoint-based, exactly like the
reference (restart → ``load_checkpoint`` with the mesh-agnostic format).
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..utils.logging import logger
from .elasticity import ELASTICITY_ENV, compute_elastic_config

#: a worker exits with this code to request a re-launch (membership change)
MEMBERSHIP_CHANGE_EXIT = 99


def run_elastic(
    cmd: Sequence[str],
    elastic_config: Dict,
    max_restarts: int = 100,
    discover_world: Optional[Callable[[], int]] = None,
    min_restart_interval_s: float = 5.0,
    env: Optional[Dict[str, str]] = None,
) -> int:
    """Supervise ``cmd`` with elastic restarts.

    ``discover_world`` returns the currently-available device count (default:
    keep the last value); each (re)launch validates it against the elastic
    device-count set and exports the pinned elastic config plus
    ``DSTPU_ELASTIC_WORLD_SIZE`` for the worker. Returns the final exit code
    (0 on success)."""
    batch, valid_dp = compute_elastic_config(
        {"elasticity": dict(elastic_config, enabled=True)})
    # compute_elastic_config returns DATA-PARALLEL rank counts; the agent
    # compares device counts, so scale by the model-parallel degree
    mp = int(elastic_config.get("model_parallel_size", 1) or 1)
    valid_counts = [c * mp for c in valid_dp]
    logger.info(f"elastic agent: batch={batch}, valid device counts="
                f"{valid_counts} (dp counts {valid_dp} x mp {mp})")

    restarts = 0
    world = discover_world() if discover_world else 0
    while True:
        child_env = dict(os.environ)
        child_env[ELASTICITY_ENV] = json.dumps(dict(elastic_config,
                                                    enabled=True))
        if world:
            if world not in valid_counts:
                usable = [c for c in valid_counts if c <= world]
                if not usable:
                    raise RuntimeError(
                        f"no elastic device count <= available {world} "
                        f"(valid: {valid_counts})")
                world = max(usable)
            child_env["DSTPU_ELASTIC_WORLD_SIZE"] = str(world)
        child_env.update(env or {})

        start = time.time()
        proc = subprocess.run(list(cmd), env=child_env)
        if proc.returncode == 0:
            return 0
        restarts += 1
        if restarts > max_restarts:
            logger.error(f"elastic agent: giving up after {restarts - 1} "
                         f"restarts (last exit {proc.returncode})")
            return proc.returncode
        if time.time() - start < min_restart_interval_s:
            time.sleep(min_restart_interval_s)
        if discover_world:
            world = discover_world()
        logger.warning(
            f"elastic agent: worker exited {proc.returncode} "
            f"({'membership change' if proc.returncode == MEMBERSHIP_CHANGE_EXIT else 'failure'}), "
            f"restart {restarts}/{max_restarts} with world={world or 'unchanged'}")
