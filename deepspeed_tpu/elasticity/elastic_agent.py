"""Elastic restart agent.

Parity with the reference's ``DSElasticAgent`` (``elasticity/elastic_agent.py:32``,
a torch-elastic ``LocalElasticAgent`` subclass that re-spawns workers on
membership change). TPU SPMD has one process per host and no in-band rank
rendezvous to re-form, so the idiomatic equivalent is a **supervisor loop**:
run the training command; on failure (or an explicit membership-change exit
code) re-launch it against the currently-available device/host set, with the
elastic config pinned in the environment (``ensure_immutable_elastic_config``
checks it runtime-side) — recovery is checkpoint-based, exactly like the
reference (restart → ``load_checkpoint`` with the mesh-agnostic format).

Preemption-aware hardening (docs/resilience.md):

  - supervisor SIGTERM/SIGINT are FORWARDED to the worker, which (with
    ``resilience.preemption`` enabled) writes a final checkpoint and exits
    ``MEMBERSHIP_CHANGE_EXIT``; the agent then exits instead of restarting
    — a preempted host drains gracefully end to end;
  - crash restarts back off exponentially, and a **crash-loop budget**
    (consecutive fast failures) stops a wedged fleet from restarting
    forever; cooperative membership-change exits never count against it;
  - every lifecycle event lands in a JSON **restart ledger** for
    postmortems (``resilience/ledger.py``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import time
from typing import Callable, Dict, Optional, Sequence

from ..resilience.ledger import RestartLedger
from ..utils.logging import logger
from .elasticity import ELASTICITY_ENV, compute_elastic_config

#: a worker exits with this code to request a re-launch (membership change)
MEMBERSHIP_CHANGE_EXIT = 99


def run_elastic(
    cmd: Sequence[str],
    elastic_config: Dict,
    max_restarts: int = 100,
    discover_world: Optional[Callable[[], int]] = None,
    min_restart_interval_s: float = 5.0,
    env: Optional[Dict[str, str]] = None,
    grace_period_s: float = 30.0,
    backoff_base_s: float = 1.0,
    backoff_max_s: float = 60.0,
    crash_loop_budget: int = 5,
    crash_loop_window_s: float = 60.0,
    ledger_path: Optional[str] = None,
) -> int:
    """Supervise ``cmd`` with elastic restarts.

    ``discover_world`` returns the currently-available device count (default:
    keep the last value); each (re)launch validates it against the elastic
    device-count set and exports the pinned elastic config plus
    ``DSTPU_ELASTIC_WORLD_SIZE`` for the worker.

    On supervisor SIGTERM/SIGINT the signal is forwarded to the worker,
    which gets ``grace_period_s`` to write a final checkpoint; the agent
    then returns without restarting. Crash restarts (exit != 0 and !=
    ``MEMBERSHIP_CHANGE_EXIT``) back off exponentially from
    ``backoff_base_s``; ``crash_loop_budget`` consecutive failures that die
    within ``crash_loop_window_s`` abort the supervision entirely.
    ``ledger_path`` (or env ``DSTPU_RESTART_LEDGER``) records a JSON audit
    trail. Returns the final exit code (0 on success)."""
    batch, valid_dp = compute_elastic_config(
        {"elasticity": dict(elastic_config, enabled=True)})
    # compute_elastic_config returns DATA-PARALLEL rank counts; the agent
    # compares device counts, so scale by the model-parallel degree
    mp = int(elastic_config.get("model_parallel_size", 1) or 1)
    valid_counts = [c * mp for c in valid_dp]
    logger.info(f"elastic agent: batch={batch}, valid device counts="
                f"{valid_counts} (dp counts {valid_dp} x mp {mp})")

    ledger = RestartLedger(ledger_path
                           or os.environ.get("DSTPU_RESTART_LEDGER"))

    stop_signal = {"num": None, "time": None}
    proc_box = {"proc": None}

    def _on_signal(signum, frame):
        # NO ledger write here: the handler runs reentrantly on the main
        # thread and could truncate a record() already in progress — the
        # supervise loop records the event once the wait returns
        stop_signal["num"] = signum
        stop_signal["time"] = time.time()
        p = proc_box["proc"]
        logger.warning(
            f"elastic agent: received {signal.Signals(signum).name}; "
            f"forwarding to worker and draining (grace {grace_period_s}s)")
        if p is not None and p.poll() is None:
            try:
                p.send_signal(signum)
            except OSError:
                pass

    previous_handlers = {}
    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            previous_handlers[sig] = signal.signal(sig, _on_signal)
    except ValueError:
        # not the main thread (tests) — signals degrade to kill-by-caller
        previous_handlers = {}

    restarts = 0
    consecutive_fast_failures = 0
    world = discover_world() if discover_world else 0
    try:
        while True:
            child_env = dict(os.environ)
            child_env[ELASTICITY_ENV] = json.dumps(dict(elastic_config,
                                                        enabled=True))
            if world:
                if world not in valid_counts:
                    usable = [c for c in valid_counts if c <= world]
                    if not usable:
                        raise RuntimeError(
                            f"no elastic device count <= available {world} "
                            f"(valid: {valid_counts})")
                    world = max(usable)
                child_env["DSTPU_ELASTIC_WORLD_SIZE"] = str(world)
            child_env.update(env or {})

            start = time.time()
            proc = subprocess.Popen(list(cmd), env=child_env)
            proc_box["proc"] = proc
            ledger.record("launch", restarts=restarts, world=world or None,
                          pid=proc.pid, t_start=start)
            if stop_signal["num"] is not None:
                # signal raced the launch: forward it now
                try:
                    proc.send_signal(stop_signal["num"])
                except OSError:
                    pass
            rc = None
            while rc is None:
                try:
                    rc = proc.wait(timeout=0.5)
                except subprocess.TimeoutExpired:
                    t0 = stop_signal["time"]
                    if t0 is not None and time.time() - t0 > grace_period_s:
                        logger.error(
                            f"elastic agent: worker ignored the signal for "
                            f"{grace_period_s}s; killing")
                        ledger.record("grace_expired",
                                      grace_period_s=grace_period_s)
                        proc.kill()
                        rc = proc.wait()
                except KeyboardInterrupt:
                    # SIGINT outside our handler (non-main-thread installs)
                    stop_signal["num"] = signal.SIGINT
                    stop_signal["time"] = stop_signal["time"] or time.time()
                    try:
                        proc.send_signal(signal.SIGINT)
                    except OSError:
                        pass
            runtime = time.time() - start
            proc_box["proc"] = None

            if stop_signal["num"] is not None:
                # drain: the worker already got the signal; give it the
                # grace period to finish its final checkpoint (train) or
                # publish its replay manifest (serve — the v2 engine's
                # drain() writes DSTPU_SERVE_DRAIN_MANIFEST and the
                # restarted/survivor replica re-admits from it), then
                # stop supervising — a preempted host must NOT restart
                ledger.record("signal", signum=int(stop_signal["num"]),
                              name=signal.Signals(stop_signal["num"]).name)
                manifest = child_env.get("DSTPU_SERVE_DRAIN_MANIFEST")
                if manifest and not os.path.exists(manifest):
                    manifest = None        # drain never published it
                ledger.record("drained", rc=rc, runtime_s=round(runtime, 3),
                              serve_manifest=manifest,
                              t_start=start, t_end=start + runtime)
                logger.warning(f"elastic agent: draining after signal; "
                               f"worker exit {rc}"
                               + (f", replay manifest {manifest}"
                                  if manifest else ""))
                return 0 if rc in (0, MEMBERSHIP_CHANGE_EXIT) else rc

            if rc == 0:
                ledger.record("success", runtime_s=round(runtime, 3),
                              t_start=start, t_end=start + runtime)
                return 0

            restarts += 1
            membership = rc == MEMBERSHIP_CHANGE_EXIT
            if membership:
                consecutive_fast_failures = 0
            elif runtime < crash_loop_window_s:
                consecutive_fast_failures += 1
            else:
                consecutive_fast_failures = 0

            if restarts > max_restarts:
                logger.error(f"elastic agent: giving up after {restarts - 1} "
                             f"restarts (last exit {rc})")
                ledger.record("giveup", reason="max_restarts", rc=rc,
                              restarts=restarts - 1,
                              t_start=start, t_end=start + runtime)
                return rc
            if consecutive_fast_failures >= crash_loop_budget:
                logger.error(
                    f"elastic agent: crash loop — {consecutive_fast_failures} "
                    f"consecutive failures inside {crash_loop_window_s}s; "
                    f"giving up (last exit {rc})")
                ledger.record("giveup", reason="crash_loop", rc=rc,
                              consecutive_fast_failures=consecutive_fast_failures,
                              t_start=start, t_end=start + runtime)
                return rc

            backoff = 0.0
            if not membership and consecutive_fast_failures > 0:
                backoff = min(
                    backoff_base_s * (2 ** (consecutive_fast_failures - 1)),
                    backoff_max_s)
            wait_s = max(backoff,
                         min_restart_interval_s - runtime
                         if runtime < min_restart_interval_s else 0.0)
            if discover_world:
                world = discover_world()
            logger.warning(
                f"elastic agent: worker exited {rc} "
                f"({'membership change' if membership else 'failure'}), "
                f"restart {restarts}/{max_restarts} with "
                f"world={world or 'unchanged'}"
                + (f" after {wait_s:.1f}s backoff" if wait_s else ""))
            ledger.record("restart", rc=rc, restarts=restarts,
                          membership_change=membership,
                          backoff_s=round(wait_s, 3), world=world or None,
                          runtime_s=round(runtime, 3),
                          t_start=start, t_end=start + runtime)
            if wait_s:
                time.sleep(wait_s)
    finally:
        for sig, prev in previous_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
