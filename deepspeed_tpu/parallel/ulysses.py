"""Ulysses sequence parallelism.

Analogue of the reference's DeepSpeed-Ulysses
(``deepspeed/sequence/layer.py``: ``DistributedAttention:271`` wrapping any
local attention with ``_SeqAllToAll:216`` head-scatter/seq-gather, and the
SP vocab cross-entropy ``sequence/cross_entropy.py``). On TPU the all-to-all
rides the ICI ``seq`` mesh axis inside ``shard_map``:

    inputs  [B, T/sp, H, D]  (sequence sharded)
    a2a  →  [B, T, H/sp, D]  (heads sharded, full sequence)   — attention here
    a2a  →  [B, T/sp, H, D]  back

GQA/uneven heads (reference ``uneven_heads_all2all:43``): kv heads broadcast
to the q head count, then heads pad to a multiple of sp with zero heads that
are sliced off after the inverse a2a — so kv_heads < sp (llama-70B kv=8 on
sp=16) and non-divisible layouts both work.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.jax_compat import shard_map

from .. import comm

SEQ_AXIS = "seq"
DATA_AXIS = "data"


def _a2a_scatter_heads(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """[B, T_local, H, D] -> [B, T_full, H/sp, D] (inside shard_map)."""
    return comm.all_to_all_single(x, axis_name=axis_name, split_axis=2,
                                  concat_axis=1, log_name="ulysses_qkv")


def _a2a_gather_heads(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """[B, T_full, H/sp, D] -> [B, T_local, H, D] (inside shard_map)."""
    return comm.all_to_all_single(x, axis_name=axis_name, split_axis=1,
                                  concat_axis=2, log_name="ulysses_out")


class DistributedAttention:
    """Wraps a local attention fn ``(q, k, v) -> out`` (all ``[B, T, H, D]``)
    so it runs with the sequence dimension sharded over the ``seq`` mesh axis.

    Reference parity: ``deepspeed/sequence/layer.py:271`` (scatter_idx=2 /
    gather_idx=1 default layout).
    """

    def __init__(self, local_attention: Callable, mesh: Mesh,
                 seq_axis: str = SEQ_AXIS):
        self.local_attn = local_attention
        self.mesh = mesh
        self.seq_axis = seq_axis

    def __call__(self, query: jnp.ndarray, key: jnp.ndarray,
                 value: jnp.ndarray) -> jnp.ndarray:
        sp = self.mesh.shape[self.seq_axis]
        if sp == 1:
            return self.local_attn(query, key, value)
        H = query.shape[2]
        Hk = key.shape[2]
        if Hk != H and H % Hk:
            raise ValueError(
                f"GQA requires q_heads % kv_heads == 0 ({H}/{Hk})")
        # GQA / uneven heads (reference uneven_heads_all2all,
        # sequence/layer.py:43). Three ladder rungs, cheapest first:
        #
        # 1. native — both head counts divide sp: rank r's q heads
        #    [rH/sp,(r+1)H/sp) map exactly into its kv range, kv rides the
        #    a2a at native width (1/group of the broadcast cost).
        # 2. grouped-gather — Hk does not divide sp (llama-70B kv=8 on
        #    sp=16, the case that motivates uneven heads). SPMD forbids the
        #    reference's genuinely uneven per-rank head counts (static
        #    shapes), so instead kv is GATHERED into an [sp]-head send
        #    layout where slot r holds exactly the one kv head rank r's q
        #    group attends to. Comm volume is sp heads — the minimal
        #    multiple of sp a static a2a can move — vs H for the broadcast
        #    (llama-70B sp=16: 16 heads instead of 64). Applies when each
        #    rank's q shard attends one kv head: G % (H/sp) == 0, G = H/Hk.
        #    (The other uniform case, (H/sp) % G == 0, implies Hk % sp == 0
        #    and is already rung 1.)
        # 3. broadcast+pad — anything irregular: kv repeats to H, all three
        #    pad to a multiple of sp with zero heads sliced off after the
        #    inverse a2a.
        pad_h = 0
        G = H // Hk if Hk else 1
        hq = H // sp if H % sp == 0 else 0
        if H % sp == 0 and Hk % sp == 0:
            pass                                    # native GQA through a2a
        elif hq and Hk != H and G % hq == 0:
            idx = jnp.asarray([(r * hq) // G for r in range(sp)], jnp.int32)
            key = jnp.take(key, idx, axis=2)
            value = jnp.take(value, idx, axis=2)
        else:
            if Hk != H:
                key = jnp.repeat(key, G, axis=2)
                value = jnp.repeat(value, G, axis=2)
            pad_h = (-H) % sp
            if pad_h:
                pad = ((0, 0), (0, 0), (0, pad_h), (0, 0))
                query = jnp.pad(query, pad)
                key = jnp.pad(key, pad)
                value = jnp.pad(value, pad)

        axis = self.seq_axis
        attn = self.local_attn

        def inner(q, k, v):
            q = _a2a_scatter_heads(q, axis)
            k = _a2a_scatter_heads(k, axis)
            v = _a2a_scatter_heads(v, axis)
            o = attn(q, k, v)
            return _a2a_gather_heads(o, axis)

        dp = self.mesh.shape.get(DATA_AXIS, 1)
        batch_axis = DATA_AXIS if dp > 1 and query.shape[0] % dp == 0 else None
        spec = P(batch_axis, axis, None, None)
        out = shard_map(inner, mesh=self.mesh, in_specs=(spec, spec, spec),
                        out_specs=spec, check_vma=False)(query, key, value)
        return out[:, :, :H] if pad_h else out


def sp_cross_entropy(logits: jnp.ndarray, targets: jnp.ndarray, mesh: Mesh,
                     seq_axis: str = SEQ_AXIS) -> jnp.ndarray:
    """Mean next-token NLL with the sequence dim sharded over ``seq`` —
    analogue of reference ``sequence/cross_entropy.py:vocab_sequence_parallel_cross_entropy``.
    logits [B, T, V], targets [B, T]; returns scalar mean over the FULL sequence."""
    sp = mesh.shape[seq_axis]

    def local_loss(lg, tg):
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, tg[..., None], axis=-1)[..., 0]
        # mean over the full (global) sequence = psum of local sums / global count
        total = jax.lax.psum(nll.sum(), seq_axis)
        count = jax.lax.psum(jnp.asarray(nll.size, jnp.float32), seq_axis)
        return total / count

    if sp == 1:
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0].mean()

    return shard_map(local_loss, mesh=mesh,
                     in_specs=(P(None, seq_axis, None), P(None, seq_axis)),
                     out_specs=P(), check_vma=False)(logits, targets)


def ulysses_attention(query, key, value, mesh: Mesh,
                      local_attention: Optional[Callable] = None,
                      seq_axis: str = SEQ_AXIS, causal: bool = True,
                      use_kernel: Optional[bool] = None,
                      interpret: Optional[bool] = None):
    """Functional one-shot form of DistributedAttention.

    The post-a2a local attention (heads sharded, full sequence) is exactly
    the Pallas flash kernel's shape, so ``use_kernel`` (default on TPU) runs
    it per device; False keeps the XLA fused attention."""
    attn = local_attention
    if attn is None:
        if use_kernel is None:
            use_kernel = jax.default_backend() == "tpu"
        if use_kernel:
            from ..ops.kernels import flash_attention
            attn = functools.partial(flash_attention, causal=causal,
                                     layout="BTHD", interpret=interpret)
        else:
            attn = functools.partial(
                jax.nn.dot_product_attention, is_causal=causal)
    return DistributedAttention(attn, mesh, seq_axis)(query, key, value)
