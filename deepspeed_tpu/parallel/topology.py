"""Mesh construction and axis registry.

TPU-native replacement for the reference's process-group factory
(``deepspeed/utils/groups.py``, ~40 ``_get_*`` accessors over NCCL groups) and
pipeline grid (``runtime/pipe/topology.py``): here every form of parallelism is
a *named axis of one* ``jax.sharding.Mesh``:

    data    — data parallel (and the ZeRO sharding axis)
    model   — tensor parallel
    pipe    — pipeline stages
    seq     — Ulysses / ring sequence parallel
    expert  — expert parallel (MoE)

Collectives ride ICI when the communicating axis is innermost on the physical
topology; ``MeshConfig.axis_order`` controls that layout (model/seq innermost
by default — they carry per-layer collectives; pipe outermost — it only does
neighbor ppermute).

Multi-host: JAX SPMD means one process per host and a global mesh over all
devices; ``build_mesh`` uses ``jax.devices()`` (global), matching how the
reference's launcher-assigned ranks compose into the world group.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config.config import ConfigError, MeshConfig
from ..config.config_utils import is_auto
from ..utils.logging import log_dist

AXIS_NAMES = ("pipe", "data", "expert", "seq", "model")

#: role of each mesh axis — the program auditor
#: (``analysis/program_audit.py``) labels collectives with these so a
#: budget-violation diff names what the unexpected comm was for
AXIS_ROLES = {
    "pipe": "pipeline-stage neighbor comm",
    "data": "data-parallel / ZeRO grad+param comm",
    "data_inner": "ZeRO++ hpZ / MiCS shard-group comm",
    "expert": "MoE expert-parallel dispatch",
    # serving reuses the same axis name for sequence-parallel inference
    # (inference/v2/seq_parallel.py): ring prefill ppermutes + the
    # per-layer decode stat-combine all-gather audit under this role
    "seq": "Ulysses/ring sequence-parallel comm",
    "model": "tensor-parallel partial-sum comm",
}

#: canonical name of the batch-sharded mesh axes (ZeRO shards over these)
DATA_AXES = ("data",)

#: optional factorization of the data axis into (outer, inner) used by
#: ZeRO++ hpZ (secondary param partition within a "node" group) and MiCS
#: (sub-world shard groups): the inner axis is the shard group, the outer
#: axis the replica group. reference: zero_hpz_partition_size
#: (stage3.py/partition_parameters.py _partition_param_sec) and
#: runtime/zero/mics.py shard groups.
DATA_INNER_AXIS = "data_inner"


@dataclasses.dataclass
class Topology:
    """A built mesh plus axis metadata. The single source of truth for
    "who is parallel over what" — the analogue of the reference's
    ``PipelineParallelGrid`` + ``groups.py`` accessors combined."""

    mesh: Mesh
    axis_sizes: Dict[str, int]

    # ------------------------- size accessors -------------------------- #
    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.axis_sizes.values())))

    def axis_size(self, name: str) -> int:
        return self.axis_sizes.get(name, 1)

    @property
    def dp_world_size(self) -> int:
        return self.axis_size("data") * self.axis_size(DATA_INNER_AXIS)

    @property
    def tp_world_size(self) -> int:
        return self.axis_size("model")

    @property
    def pp_world_size(self) -> int:
        return self.axis_size("pipe")

    @property
    def sp_world_size(self) -> int:
        return self.axis_size("seq")

    @property
    def ep_world_size(self) -> int:
        return self.axis_size("expert")

    # ZeRO partitions over the fused seq×data group, mirroring the reference
    # passing seq_data_parallel_group as dp_process_group (engine.py:1572)
    @property
    def zero_axes(self) -> Sequence[str]:
        return tuple(a for a in ("seq", "data", DATA_INNER_AXIS)
                     if self.axis_size(a) > 1) or ("data",)

    @property
    def zero_world_size(self) -> int:
        return self.dp_world_size * self.axis_size("seq")

    # ------------------------- sharding helpers ------------------------ #
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self, extra_batch_axes: Sequence[str] = ()) -> NamedSharding:
        """Sharding for [batch, ...] arrays: batch over data (+seq if fused)."""
        axes = tuple(a for a in ("data", DATA_INNER_AXIS, *extra_batch_axes)
                     if self.axis_size(a) > 1)
        if not axes:
            return self.replicated()
        return NamedSharding(self.mesh, P(axes))

    def __repr__(self):
        sizes = ", ".join(f"{k}={v}" for k, v in self.axis_sizes.items())
        return f"Topology({sizes})"


def build_mesh(
    cfg: Optional[MeshConfig] = None,
    devices: Optional[Sequence] = None,
    inner_shard_size: int = 1,
) -> Topology:
    """Construct the device mesh from config.

    ``data: "auto"`` absorbs all devices not claimed by the other axes.
    ``inner_shard_size`` factors the data axis into
    (data, :data:`DATA_INNER_AXIS`) for hpZ/MiCS sub-group sharding.
    Raises if the product of axis sizes doesn't divide the device count.
    """
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)

    sizes = {
        "pipe": int(cfg.pipe),
        "expert": int(cfg.expert),
        "seq": int(cfg.seq),
        "model": int(cfg.model),
    }
    fixed = int(np.prod(list(sizes.values())))
    if is_auto(cfg.data) or cfg.data in (None, -1):
        if n % fixed != 0:
            raise ConfigError(
                f"device count {n} not divisible by model*pipe*seq*expert={fixed}")
        sizes["data"] = n // fixed
    else:
        sizes["data"] = int(cfg.data)
        if fixed * sizes["data"] != n:
            raise ConfigError(
                f"mesh axis product {fixed * sizes['data']} != device count {n} "
                f"(axes: data={sizes['data']}, {sizes})")

    order = list(cfg.axis_order)
    if sorted(order) != sorted(AXIS_NAMES):
        raise ConfigError(f"mesh.axis_order must be a permutation of {AXIS_NAMES}, got {order}")
    placement = getattr(cfg, "expert_placement", None)
    if placement is not None:                 # None = respect axis_order
        if placement not in ("inside_data", "outside_data"):
            raise ConfigError(
                f"expert_placement must be 'inside_data' or 'outside_data', "
                f"got {placement!r}")
        di, ei = order.index("data"), order.index("expert")
        if placement == "inside_data" and ei < di:
            order.remove("expert")
            order.insert(order.index("data") + 1, "expert")
        elif placement == "outside_data" and ei > di:
            order.remove("expert")
            order.insert(order.index("data"), "expert")

    inner = int(inner_shard_size)
    if inner > 1:
        if sizes["data"] % inner != 0:
            raise ConfigError(
                f"inner shard size {inner} (hpZ/MiCS) must divide the data "
                f"axis size {sizes['data']}")
        sizes["data"] //= inner
        sizes[DATA_INNER_AXIS] = inner
        order.insert(order.index("data") + 1, DATA_INNER_AXIS)

    shape = [sizes[a] for a in order]
    dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, axis_names=tuple(order))
    topo = Topology(mesh=mesh, axis_sizes={a: sizes[a] for a in order})
    log_dist(f"Built mesh: {topo} over {n} devices", ranks=[0])
    return topo


# --------------------------------------------------------------------------- #
# groups.py-compatible module-level registry
# --------------------------------------------------------------------------- #

_TOPOLOGY: Optional[Topology] = None


def set_topology(topo: Topology) -> None:
    global _TOPOLOGY
    _TOPOLOGY = topo


def get_topology() -> Topology:
    if _TOPOLOGY is None:
        raise RuntimeError("Topology not initialized — call initialize() or build_mesh() first")
    return _TOPOLOGY


def has_topology() -> bool:
    return _TOPOLOGY is not None


def get_data_parallel_world_size() -> int:
    return get_topology().dp_world_size


def get_model_parallel_world_size() -> int:
    return get_topology().tp_world_size


def get_sequence_parallel_world_size() -> int:
    return get_topology().sp_world_size


def get_expert_parallel_world_size() -> int:
    return get_topology().ep_world_size


def get_pipe_parallel_world_size() -> int:
    return get_topology().pp_world_size
