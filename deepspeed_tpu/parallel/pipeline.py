"""Pipeline parallelism.

Analogue of the reference's ``runtime/pipe/`` (4,379 LoC: ``PipelineModule``
with ``LayerSpec``/``TiedLayerSpec`` partitioning, ``PipelineEngine`` running
a 1F1B instruction stream through ``_INSTRUCTION_MAP`` with torch p2p
send/recv between stage ranks). The TPU-native inversion (SURVEY.md §7):
instead of an interpreter dispatching host-side instructions per microbatch,
the ENTIRE pipeline schedule is one compiled program — ``shard_map`` over the
``pipe`` mesh axis, stage params sharded on their leading dim, and a
``lax.scan`` GPipe loop whose inter-stage sends are ``ppermute`` (neighbor
ICI hops). Backward flows through the same loop via autodiff — the reverse
schedule the reference hand-codes (``_exec_backward_pass``/SendGrad/RecvGrad)
falls out of ``jax.grad``.

Activation memory is managed with ``jax.checkpoint`` on the stage function
(``remat``), which is what 1F1B's early-backward buys on GPUs.

Host-side ``LayerSpec`` / ``partition_layers`` mirror the reference's model
description and ``parameters``/``uniform``/``type:regex`` partition methods
(``runtime/pipe/module.py:391``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .. import comm

PIPE_AXIS = "pipe"
DATA_AXIS = "data"


# --------------------------------------------------------------------- #
# model description (host-side parity surface)
# --------------------------------------------------------------------- #

@dataclasses.dataclass
class LayerSpec:
    """Deferred layer description (reference pipe/module.py:30)."""
    module_class: Any
    args: tuple = ()
    kwargs: dict = dataclasses.field(default_factory=dict)
    param_count: int = 0     # used by partition_method="parameters"

    def build(self):
        return self.module_class(*self.args, **self.kwargs)

    @property
    def typename(self) -> str:
        return getattr(self.module_class, "__name__", str(self.module_class))


@dataclasses.dataclass
class TiedLayerSpec(LayerSpec):
    """Layer sharing params with another by key (reference pipe/module.py:77).
    In JAX, tying = reusing the same param subtree; the spec records intent."""
    key: str = ""


def partition_layers(layers: Sequence[LayerSpec], num_stages: int,
                     method: str = "uniform") -> List[int]:
    """Return stage boundary indices (len num_stages+1), reference
    _partition_layers (pipe/module.py:391) semantics:
      "uniform"     — equal layer counts
      "parameters"  — balance summed param_count
      "type:regex"  — equal counts of layers whose typename matches regex
    """
    n = len(layers)
    if num_stages > n:
        raise ValueError(
            f"cannot partition {n} layers into {num_stages} stages "
            f"(every stage needs at least one layer)")
    if method == "uniform":
        weights = [1.0] * n
    elif method == "parameters":
        weights = [max(float(s.param_count), 0.0) for s in layers]
        if sum(weights) == 0:
            weights = [1.0] * n
    elif method.startswith("type:"):
        pat = re.compile(method[len("type:"):], re.IGNORECASE)
        weights = [1.0 if pat.search(s.typename) else 0.0 for s in layers]
        if sum(weights) == 0:
            raise ValueError(f"no layer matches partition regex {method!r}")
    else:
        raise ValueError(f"unknown partition method {method!r}")

    # greedy prefix-sum balance
    total = sum(weights)
    cum = np.cumsum([0.0] + list(weights))
    bounds = [0]
    for s in range(1, num_stages):
        target = total * s / num_stages
        idx = int(np.searchsorted(cum, target))
        idx = max(bounds[-1] + 1, min(idx, n - (num_stages - s)))
        bounds.append(idx)
    bounds.append(n)
    return bounds


# --------------------------------------------------------------------- #
# the compiled pipeline
# --------------------------------------------------------------------- #

def stack_stage_params(block_params: Any, num_stages: int) -> Any:
    """Reshape stacked block params [L, ...] → [P, L/P, ...] so the leading
    dim shards over the ``pipe`` axis (one group of L/P blocks per stage)."""

    def reshape(leaf):
        L = leaf.shape[0]
        if L % num_stages != 0:
            raise ValueError(
                f"layer count {L} must divide pipeline stages {num_stages}")
        return leaf.reshape(num_stages, L // num_stages, *leaf.shape[1:])

    return jax.tree_util.tree_map(reshape, block_params)


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: jnp.ndarray,
                   mesh: Mesh, num_microbatches: int,
                   pipe_axis: str = PIPE_AXIS,
                   shard_batch_over_data: bool = True,
                   remat: bool = True) -> jnp.ndarray:
    """Run ``x`` through a ``pipe``-sharded stack of stages with a GPipe
    fill/drain schedule compiled into one program.

    stage_fn(params_local, h) -> h' where params_local has the [L/P, ...]
    per-stage leaves and h is one microbatch of activations [mb, ...].
    x: [B, ...] with B divisible by num_microbatches.
    Differentiable end-to-end.
    """
    n_stages = mesh.shape[pipe_axis]
    if n_stages == 1:
        squeezed = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        return stage_fn(squeezed, x)

    B = x.shape[0]
    m = num_microbatches
    if B % m != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {m}")
    micro = x.reshape(m, B // m, *x.shape[1:])
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(params_local, micro_local):
        params_local = jax.tree_util.tree_map(lambda p: p[0], params_local)
        idx = jax.lax.axis_index(pipe_axis)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        total_steps = m + n_stages - 1

        buf = jnp.zeros_like(micro_local[0])
        outputs = jnp.zeros_like(micro_local)

        def step(carry, t):
            buf_in, outputs = carry
            # stage 0 feeds microbatch t (clamped in drain phase; the result
            # is masked out by the last stage's write gate)
            x_t = jax.lax.dynamic_index_in_dim(
                micro_local, jnp.clip(t, 0, m - 1), keepdims=False)
            inp = jnp.where(idx == 0, x_t, buf_in)
            out = fn(params_local, inp)
            # last stage owns microbatch t-(P-1) at step t
            out_t = t - (n_stages - 1)
            write = jnp.logical_and(idx == n_stages - 1, out_t >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, out, jnp.clip(out_t, 0, m - 1), axis=0)
            outputs = jnp.where(write, updated, outputs)
            buf_next = comm.ppermute(out, perm, axis_name=pipe_axis,
                                     log_name="pipe_send_activations")
            return (buf_next, outputs), None

        (_, outputs), _ = jax.lax.scan(step, (buf, outputs),
                                       jnp.arange(total_steps))
        # results live on the last stage; psum broadcasts them everywhere
        outputs = jax.lax.psum(
            jnp.where(idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            pipe_axis)
        return outputs

    dp = mesh.shape.get(DATA_AXIS, 1)
    batch_spec = P(None, DATA_AXIS) if (
        shard_batch_over_data and dp > 1 and (B // m) % dp == 0) else P()
    param_spec = jax.tree_util.tree_map(lambda _: P(pipe_axis), stage_params)

    y = shard_map(body, mesh=mesh,
                  in_specs=(param_spec, batch_spec),
                  out_specs=batch_spec, check_vma=False)(stage_params, micro)
    return y.reshape(B, *y.shape[2:])
