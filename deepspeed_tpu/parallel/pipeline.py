"""Pipeline parallelism.

Analogue of the reference's ``runtime/pipe/`` (4,379 LoC: ``PipelineModule``
with ``LayerSpec``/``TiedLayerSpec`` partitioning, ``PipelineEngine`` running
a 1F1B instruction stream through ``_INSTRUCTION_MAP`` with torch p2p
send/recv between stage ranks). The TPU-native inversion (SURVEY.md §7):
instead of an interpreter dispatching host-side instructions per microbatch,
the ENTIRE pipeline schedule is one compiled program — ``shard_map`` over the
``pipe`` mesh axis, stage params sharded on their leading dim, and a
``lax.scan`` GPipe loop whose inter-stage sends are ``ppermute`` (neighbor
ICI hops). Backward flows through the same loop via autodiff — the reverse
schedule the reference hand-codes (``_exec_backward_pass``/SendGrad/RecvGrad)
falls out of ``jax.grad``.

Activation memory is managed with ``jax.checkpoint`` on the stage function
(``remat``), which is what 1F1B's early-backward buys on GPUs.

Host-side ``LayerSpec`` / ``partition_layers`` mirror the reference's model
description and ``parameters``/``uniform``/``type:regex`` partition methods
(``runtime/pipe/module.py:391``).
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from ..utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .. import comm

PIPE_AXIS = "pipe"
DATA_AXIS = "data"


# --------------------------------------------------------------------- #
# model description (host-side parity surface)
# --------------------------------------------------------------------- #

@dataclasses.dataclass
class LayerSpec:
    """Deferred layer description (reference pipe/module.py:30)."""
    module_class: Any
    args: tuple = ()
    kwargs: dict = dataclasses.field(default_factory=dict)
    param_count: int = 0     # used by partition_method="parameters"

    def build(self):
        return self.module_class(*self.args, **self.kwargs)

    @property
    def typename(self) -> str:
        return getattr(self.module_class, "__name__", str(self.module_class))


@dataclasses.dataclass
class TiedLayerSpec(LayerSpec):
    """Layer sharing params with another by key (reference pipe/module.py:77).

    In JAX, tying is a real mechanism, not intent: all specs with the same
    ``key`` read ONE param subtree (stored once under ``params["tied"][key]``
    by ``PipelineModule``), and because that subtree enters the pipeline's
    ``shard_map`` replicated, ``jax.grad`` psums its per-stage gradient
    contributions across the pipe axis — the automatic form of the
    reference's ``_exec_reduce_tied_grads`` allreduce (pipe/engine.py:275).
    ``forward_fn(params, x)`` overrides the module's apply for the non-owning
    use (e.g. embedding-transpose unembed)."""
    key: str = ""
    forward_fn: Optional[Callable] = None


def partition_layers(layers: Sequence[LayerSpec], num_stages: int,
                     method: str = "uniform") -> List[int]:
    """Return stage boundary indices (len num_stages+1), reference
    _partition_layers (pipe/module.py:391) semantics:
      "uniform"     — equal layer counts
      "parameters"  — balance summed param_count
      "type:regex"  — equal counts of layers whose typename matches regex
    """
    n = len(layers)
    if num_stages > n:
        raise ValueError(
            f"cannot partition {n} layers into {num_stages} stages "
            f"(every stage needs at least one layer)")
    if method == "uniform":
        weights = [1.0] * n
    elif method == "parameters":
        weights = [max(float(s.param_count), 0.0) for s in layers]
        if sum(weights) == 0:
            weights = [1.0] * n
    elif method.startswith("type:"):
        pat = re.compile(method[len("type:"):], re.IGNORECASE)
        weights = [1.0 if pat.search(s.typename) else 0.0 for s in layers]
        if sum(weights) == 0:
            raise ValueError(f"no layer matches partition regex {method!r}")
    else:
        raise ValueError(f"unknown partition method {method!r}")

    # greedy prefix-sum balance
    total = sum(weights)
    cum = np.cumsum([0.0] + list(weights))
    bounds = [0]
    for s in range(1, num_stages):
        target = total * s / num_stages
        idx = int(np.searchsorted(cum, target))
        idx = max(bounds[-1] + 1, min(idx, n - (num_stages - s)))
        bounds.append(idx)
    bounds.append(n)
    return bounds


# --------------------------------------------------------------------- #
# the compiled pipeline
# --------------------------------------------------------------------- #

def stack_stage_params(block_params: Any, num_stages: int) -> Any:
    """Reshape stacked block params [L, ...] → [P, L/P, ...] so the leading
    dim shards over the ``pipe`` axis (one group of L/P blocks per stage)."""

    def reshape(leaf):
        L = leaf.shape[0]
        if L % num_stages != 0:
            raise ValueError(
                f"layer count {L} must divide pipeline stages {num_stages}")
        return leaf.reshape(num_stages, L // num_stages, *leaf.shape[1:])

    return jax.tree_util.tree_map(reshape, block_params)


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: jnp.ndarray,
                   mesh: Mesh, num_microbatches: int,
                   pipe_axis: str = PIPE_AXIS,
                   shard_batch_over_data: bool = True,
                   remat: bool = True) -> jnp.ndarray:
    """Run ``x`` through a ``pipe``-sharded stack of stages with a GPipe
    fill/drain schedule compiled into one program.

    stage_fn(params_local, h) -> h' where params_local has the [L/P, ...]
    per-stage leaves and h is one microbatch of activations [mb, ...].
    x: [B, ...] with B divisible by num_microbatches.
    Differentiable end-to-end.
    """
    n_stages = mesh.shape[pipe_axis]
    if n_stages == 1:
        squeezed = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        return stage_fn(squeezed, x)

    B = x.shape[0]
    m = num_microbatches
    if B % m != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {m}")
    micro = x.reshape(m, B // m, *x.shape[1:])
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(params_local, micro_local):
        params_local = jax.tree_util.tree_map(lambda p: p[0], params_local)
        idx = jax.lax.axis_index(pipe_axis)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        total_steps = m + n_stages - 1

        buf = jnp.zeros_like(micro_local[0])
        outputs = jnp.zeros_like(micro_local)

        def step(carry, t):
            buf_in, outputs = carry
            # stage 0 feeds microbatch t (clamped in drain phase; the result
            # is masked out by the last stage's write gate)
            x_t = jax.lax.dynamic_index_in_dim(
                micro_local, jnp.clip(t, 0, m - 1), keepdims=False)
            inp = jnp.where(idx == 0, x_t, buf_in)
            out = fn(params_local, inp)
            # last stage owns microbatch t-(P-1) at step t
            out_t = t - (n_stages - 1)
            write = jnp.logical_and(idx == n_stages - 1, out_t >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, out, jnp.clip(out_t, 0, m - 1), axis=0)
            outputs = jnp.where(write, updated, outputs)
            buf_next = comm.ppermute(out, perm, axis_name=pipe_axis,
                                     log_name="pipe_send_activations")
            return (buf_next, outputs), None

        (_, outputs), _ = jax.lax.scan(step, (buf, outputs),
                                       jnp.arange(total_steps))
        # results live on the last stage; psum broadcasts them everywhere
        outputs = jax.lax.psum(
            jnp.where(idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            pipe_axis)
        return outputs

    dp = mesh.shape.get(DATA_AXIS, 1)
    batch_spec = P(None, DATA_AXIS) if (
        shard_batch_over_data and dp > 1 and (B // m) % dp == 0) else P()
    param_spec = jax.tree_util.tree_map(lambda _: P(pipe_axis), stage_params)

    y = shard_map(body, mesh=mesh,
                  in_specs=(param_spec, batch_spec),
                  out_specs=batch_spec, check_vma=False)(stage_params, micro)
    return y.reshape(B, *y.shape[2:])


# --------------------------------------------------------------------- #
# engine-integrated pipeline module
# --------------------------------------------------------------------- #

class PipelineModule:
    """Trainable pipeline model the Engine can drive — the analogue of the
    reference's ``PipelineModule`` + ``PipelineEngine.train_batch``
    (runtime/pipe/module.py:86, engine.py:338), re-designed for one compiled
    SPMD program instead of an instruction interpreter.

    ``layers`` is a flat LayerSpec list (embed ... blocks ... head), each
    spec building an object with ``.init(rng, x) -> params`` and
    ``.apply(params, x) -> y`` (flax modules qualify). Layers are partitioned
    into ``num_stages = mesh.shape["pipe"]`` groups by ``partition_method``
    (reference ``_partition_layers`` semantics). Stage s runs its sublist as
    one ``lax.switch`` branch inside a fill/drain ring over the pipe axis:

        step t: stage 0 feeds microbatch t; stage s computes its branch on
        the ppermute'd boundary activation; the LAST stage also computes the
        per-microbatch loss (so only boundary-shaped tensors ever ride the
        ring — tokens in, loss out, no logits traffic).

    Schedule/bubble math: with m microbatches and P stages the compiled
    fill/drain loop runs m + P - 1 steps, so the bubble fraction is
    (P-1)/(m+P-1) — GPipe's. The reference's 1F1B has the SAME bubble; what
    1F1B buys on GPUs is peak activation memory (P microbatches in flight
    instead of m).

    Memory: ``remat=True`` (default) recomputes each stage's INTERIOR in
    backward, so per step only the boundary activation is saved — but the
    scan still saves one boundary carry per step: O(m) boundaries resident.
    ``boundary_windows`` bounds that: the schedule runs as windows of W
    steps with ``jax.checkpoint`` around each window, so backward keeps
    O(m/W + W) boundary carries (W ~= sqrt(m+P-1) when "auto") and replays
    a window's forward during its backward — the classic sqrt-remat trade
    (~+33% pipeline-forward FLOPs for 1F1B-class boundary memory). For long
    sequences the boundary IS the activation, so this is the knob that
    matches 1F1B's O(P) in-flight profile. Use m >> P to amortize the
    bubble.

    The engine consumes this via ``loss_fn`` / ``init`` — train_batch, GAS,
    loss scaling, ZeRO (over data axes), checkpointing all compose unchanged.
    """

    def __init__(self, layers: Sequence[LayerSpec], mesh: Mesh,
                 num_microbatches: int,
                 loss_fn: Optional[Callable] = None,
                 input_fn: Optional[Callable] = None,
                 partition_method: str = "uniform",
                 pipe_axis: str = PIPE_AXIS,
                 remat: bool = True,
                 boundary_windows: Optional[Any] = None,
                 param_specs: Optional[Any] = None):
        self.specs = list(layers)
        self.mesh = mesh
        self.pipe_axis = pipe_axis
        self.num_stages = mesh.shape.get(pipe_axis, 1)
        self.num_microbatches = num_microbatches
        self.remat = remat
        # None = plain scan (O(m) boundary carries in backward); "auto" =
        # sqrt(m+P-1)-sized checkpointed windows; int = explicit window size
        self.boundary_windows = boundary_windows
        # optional params-shaped PartitionSpec tree for tensor parallelism
        # INSIDE the pipeline: layers see their model-axis shards and own
        # the psums (Megatron-style), composing pipe x model x data in one
        # step (reference PipeModelDataParallelTopology,
        # runtime/pipe/topology.py:244). None = params replicated over the
        # non-batch axes inside the step.
        self.param_specs = param_specs
        # batch -> stage-0 input; default: next-token LM on batch["tokens"]
        self.input_fn = input_fn or (lambda b: b["tokens"][:, :-1])
        # (last_layer_out, batch_slice) -> scalar mean loss; default: NLL
        self.loss_head = loss_fn or _default_lm_loss
        self.bounds = partition_layers(self.specs, self.num_stages,
                                       partition_method)
        self._built = [s.build() for s in self.specs]

    # ------------------------------ init ------------------------------ #

    def init(self, rng, sample_batch) -> Any:
        """Build the params pytree {"stages": (tree...,), "tied": {...}} by
        running the layers once on a host-side sample; validates that every
        stage boundary carries the same activation signature."""
        x = self.input_fn(sample_batch)
        stage_params: List[Any] = []
        tied: dict = {}
        boundary_sig = None
        for s in range(self.num_stages):
            group: List[Any] = []
            for i in range(self.bounds[s], self.bounds[s + 1]):
                spec, mod = self.specs[i], self._built[i]
                rng, sub = jax.random.split(rng)
                if isinstance(spec, TiedLayerSpec) and spec.key in tied:
                    p = tied[spec.key]       # share the existing subtree
                    group.append(None)       # marker: read from tied
                else:
                    p = mod.init(sub, x)
                    if isinstance(spec, TiedLayerSpec):
                        tied[spec.key] = p
                        group.append(None)
                    else:
                        group.append(p)
                x = self._apply_layer(i, p, x)
            if s < self.num_stages - 1:
                sig = (jnp.shape(x), jnp.result_type(x))
                if boundary_sig is None:
                    boundary_sig = sig
                elif sig != boundary_sig:
                    raise ValueError(
                        f"stage {s} boundary signature {sig} != stage 0's "
                        f"{boundary_sig}; pipeline stages must exchange "
                        f"identically-shaped activations (choose partition "
                        f"bounds so embed/head sit inside the first/last "
                        f"stage)")
            stage_params.append(tuple(group))
        self._boundary_sig = boundary_sig
        return {"stages": tuple(stage_params), "tied": tied}

    def _apply_layer(self, i: int, p: Any, x):
        spec, mod = self.specs[i], self._built[i]
        if isinstance(spec, TiedLayerSpec) and spec.forward_fn is not None:
            return spec.forward_fn(p, x)
        return mod.apply(p, x)

    # ----------------------------- loss ------------------------------- #

    def loss_fn(self, params, batch, rng):
        del rng
        m = self.num_microbatches
        P_ = self.num_stages
        if P_ == 1:
            x = self.input_fn(batch)
            x = self._run_stage(0, params, x)
            return self.loss_head(x, batch)

        if not hasattr(self, "_boundary_sig"):
            # params came from disk without an in-process init(): derive the
            # boundary signature abstractly from stage 0
            mb = jax.tree_util.tree_leaves(batch)[0].shape[0] // m
            sample = jax.tree_util.tree_map(lambda a: a[:mb], batch)
            sd = jax.eval_shape(
                lambda p, b: self._stage_fn(0, p)(self.input_fn(b)),
                params, sample)
            self._boundary_sig = (sd.shape, sd.dtype)

        dp_axes = tuple(a for a in ("data", "data_inner")
                        if self.mesh.shape.get(a, 1) > 1)
        bspec = P(None, dp_axes) if dp_axes else P(None)
        # constrain AT the reshape seam: the [B, ...] -> [m, B/m, ...]
        # reshape moves the data-sharded batch dim from 0 to 1, and
        # without the annotation GSPMD resolves the transition by
        # involuntary full rematerialization on composed meshes
        # (spmd_partitioner.cc:652 — VERDICT r4 weak #3); constraining
        # dim 0 first keeps each transition a single move
        from jax.sharding import NamedSharding as _NS
        micro = jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(
                jax.lax.with_sharding_constraint(
                    a, _NS(self.mesh, P(dp_axes) if dp_axes else P())
                ).reshape((m, a.shape[0] // m) + a.shape[1:]),
                _NS(self.mesh, bspec)), batch)
        # Params enter replicated across the pipe axis DURING the step:
        # with heterogeneous per-stage subtrees there is no stackable
        # leading dim to shard over ``pipe`` (each device COMPUTES only its
        # switch branch). At-REST residency is a different story: the
        # engine's sharding plan stores params/grads/opt-state sharded over
        # pipe x data (ZeroShardingPlan pipe residency), so per-rank live
        # param bytes scale as total/(P x dp) between the gathers XLA
        # schedules at this boundary. ``param_specs`` additionally shards
        # TP'd layers over the model axis inside the step.
        if self.param_specs is not None:
            pspec = self.param_specs
        else:
            pspec = jax.tree_util.tree_map(lambda _: P(), params)

        return shard_map(self._ring_schedule, mesh=self.mesh,
                         in_specs=(pspec, jax.tree_util.tree_map(
                             lambda _: bspec, micro)),
                         out_specs=P(), check_vma=False)(params, micro)

    def _run_stage(self, s: int, params, x):
        fn = self._stage_fn(s, params)
        return fn(x)

    def _stage_fn(self, s: int, params):
        def run(x):
            for i in range(self.bounds[s], self.bounds[s + 1]):
                spec = self.specs[i]
                if isinstance(spec, TiedLayerSpec):
                    p = params["tied"][spec.key]
                else:
                    p = params["stages"][s][i - self.bounds[s]]
                x = self._apply_layer(i, p, x)
            return x
        return jax.checkpoint(run) if self.remat else run

    def _ring_schedule(self, params, micro):
        """Inside shard_map over the pipe axis (and data axes for batch)."""
        m, n_stages = self.num_microbatches, self.num_stages
        idx = jax.lax.axis_index(self.pipe_axis)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        shape, dtype = self._boundary_sig
        mb = jax.tree_util.tree_leaves(micro)[0].shape[1]
        bshape = (mb,) + tuple(shape[1:])

        def branch(s):
            def run(tok_batch, buf):
                fn = self._stage_fn(s, params)
                if s == 0:
                    out = fn(self.input_fn(tok_batch))
                    loss = jnp.zeros((), jnp.float32)
                elif s == n_stages - 1:
                    y = fn(buf)
                    loss = self.loss_head(y, tok_batch).astype(jnp.float32)
                    out = jnp.zeros(bshape, dtype)
                else:
                    out = fn(buf)
                    loss = jnp.zeros((), jnp.float32)
                if out.shape != bshape or out.dtype != dtype:
                    raise ValueError(
                        f"stage {s} emitted {out.shape}/{out.dtype}, "
                        f"boundary is {bshape}/{dtype}")
                return out, loss
            return run

        branches = [branch(s) for s in range(n_stages)]
        total_steps = m + n_stages - 1

        def step(carry, t):
            buf_in, loss_acc = carry
            # stage 0 consumes microbatch t; the last stage consumes t-(P-1)
            my_t = jnp.where(idx == n_stages - 1, t - (n_stages - 1), t)
            my_t_c = jnp.clip(my_t, 0, m - 1)
            mb_slice = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, my_t_c,
                                                       keepdims=False), micro)
            out, loss = jax.lax.switch(idx, branches, mb_slice, buf_in)
            valid = jnp.logical_and(my_t >= 0, my_t <= m - 1)
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(idx == n_stages - 1, valid), loss, 0.0)
            buf_next = comm.ppermute(out, perm, axis_name=self.pipe_axis,
                                     log_name="pipe_send_activations")
            return (buf_next, loss_acc), None

        buf0 = jnp.zeros(bshape, dtype)
        # (1,)-shaped accumulator — scalar scan carries break the legacy
        # shard_map transpose (see _ring's carry0 note)
        carry0 = (buf0, jnp.zeros((1,), jnp.float32))
        if self.boundary_windows is None:
            (_, loss_sum), _ = jax.lax.scan(step, carry0,
                                            jnp.arange(total_steps))
        else:
            (_, loss_sum) = _windowed_schedule(step, carry0, total_steps,
                                               self.boundary_windows)
        loss_sum = loss_sum[0]
        # only the last stage accumulated loss; psum broadcasts it, and the
        # same psum over the data axes averages the data-parallel shards
        loss = jax.lax.psum(
            jnp.where(idx == n_stages - 1, loss_sum, 0.0), self.pipe_axis) / m
        for a in ("data", "data_inner"):
            if self.mesh.shape.get(a, 1) > 1:
                loss = jax.lax.pmean(loss, a)
        return loss


def _windowed_schedule(step, carry0, total_steps: int, W):
    """Run ``total_steps`` ring steps as jax.checkpoint'd windows of W
    (sqrt-remat over the schedule: backward keeps O(steps/W + W) boundary
    carries and replays one window's forward during its backward). The
    remainder runs as ONE tail window of exact size — no padded no-op
    steps."""
    if W == "auto":
        W = max(1, int(np.ceil(np.sqrt(total_steps))))
    W = min(int(W), total_steps)
    n_full, rem = divmod(total_steps, W)

    @jax.checkpoint
    def window(carry, t_vec):
        carry, _ = jax.lax.scan(step, carry, t_vec)
        return carry

    carry = carry0
    if n_full:
        ts = jnp.arange(n_full * W).reshape(n_full, W)
        carry, _ = jax.lax.scan(lambda c, tv: (window(c, tv), None),
                                carry, ts)
    if rem:
        carry = window(carry, jnp.arange(n_full * W, total_steps))
    return carry


class StackedPipelineModule:
    """Uniform-block pipeline with TRUE in-step stage residency.

    The reference's pipeline ranks materialize ONLY their stage's layers,
    ever (``runtime/pipe/module.py:391`` — each rank builds just its
    partition). ``PipelineModule`` above reproduces that at REST (the
    engine's plan shards params over pipe) but its heterogeneous per-stage
    subtrees force replicated entry into the compiled step. This class is
    the TPU-native answer for the models pipelines actually train — uniform
    stacks of identical transformer blocks (every registry LM qualifies):

      * interior block params stack on a leading ``[L]`` dim whose shard_map
        in_spec is ``P(pipe)`` — each rank's program only ever reads its own
        ``[L/P]`` slice. There is no gather and no ``lax.switch``: every
        rank runs the same block loop on its local stack.
      * the tied embedding/LM-head table shards over pipe on the VOCAB dim.
        Embedding lookup and the final fused cross-entropy are cooperative:
        each rank contributes its vocab slice (masked lookup / partial
        logsumexp + target-logit), combined with psums over the pipe axis —
        Megatron's vocab-parallel embedding + cross entropy, ridden on the
        pipe axis so no rank ever holds the full table. Work splits exactly
        (each rank computes 1/P of the unembed FLOPs): nothing is
        duplicated, and full logits never exist anywhere.

    Peak in-step live parameter bytes per rank ≈ total/P + the replicated
    leftovers (positional table slice, final norm) + boundary buffers — the
    bound ``test_pipeline_stacked_residency`` asserts from the compiled
    step's ``memory_analysis()`` (argument + temp bytes), replacing the
    at-rest-only sharding-metadata assertion.

    Schedule: the same GPipe fill/drain ring as ``PipelineModule`` (m+P-1
    steps, ``ppermute`` boundary sends, optional sqrt-remat boundary
    windows). The cooperative embed/loss run every ring step on all ranks
    (masked during fill/drain), which costs (m+P-1)/m of their FLOPs — the
    same bubble factor the whole pipe pays.

    Tensor parallelism composes WITHOUT user-code psums: the shard_map is
    manual only over ``pipe``/data axes; the ``model`` axis stays automatic,
    so block params carrying model-axis shardings (from ``tp_rules``) are
    partitioned by GSPMD, which inserts the Megatron psums itself
    (VERDICT r3 #9).

    Params tree: ``{"embed": {"wte": [V, C], "wpe": [Tmax, C]?},
    "blocks": <block tree, leading dim L>, "final": <final_fn params>}``.
    """

    def __init__(self, mesh: Mesh, num_microbatches: int, *,
                 num_layers: int, hidden_size: int, vocab_size: int,
                 block_init: Callable, block_fn: Callable,
                 max_seq_len: Optional[int] = None,
                 final_init: Optional[Callable] = None,
                 final_fn: Optional[Callable] = None,
                 compute_dtype: Any = jnp.bfloat16,
                 param_dtype: Any = jnp.float32,
                 pipe_axis: str = PIPE_AXIS,
                 remat: bool = True,
                 boundary_windows: Optional[Any] = None,
                 tp_block_specs: Optional[Any] = None,
                 aux_weight: float = 0.0):
        # block_fn may return (h, aux_scalar) — e.g. MoE blocks with a
        # load-balance loss; the schedule accumulates aux over layers and
        # valid microbatches and adds aux_weight * mean_aux to the loss
        self.aux_weight = aux_weight
        self.mesh = mesh
        self.pipe_axis = pipe_axis
        self.num_stages = mesh.shape.get(pipe_axis, 1)
        self.num_microbatches = num_microbatches
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.vocab_size = vocab_size
        self.max_seq_len = max_seq_len
        self.block_init = block_init     # (rng, h_sample) -> block params
        self.block_fn = block_fn         # (block_params, h) -> h
        self.final_init = final_init     # (rng, h_sample) -> final params
        self.final_fn = final_fn         # (final_params, h) -> h
        self.compute_dtype = compute_dtype
        self.param_dtype = param_dtype
        self.remat = remat
        self.boundary_windows = boundary_windows
        # optional per-BLOCK PartitionSpec tree (without the leading [L]
        # dim) for Megatron-style tensor parallelism over the ``model``
        # axis. The step's shard_map is manual only over pipe/data — the
        # model axis stays AUTOMATIC, so GSPMD partitions the block matmuls
        # from these at-rest shardings and inserts the row-parallel psums
        # itself: no psum ever appears in block_fn (VERDICT r3 #9).
        self.tp_block_specs = tp_block_specs
        if num_layers % max(self.num_stages, 1):
            raise ValueError(
                f"pipeline stages ({self.num_stages}) must divide "
                f"num_layers ({num_layers})")
        if vocab_size % max(self.num_stages, 1):
            raise ValueError(
                f"pipeline stages ({self.num_stages}) must divide "
                f"vocab_size ({vocab_size}) — the vocab-parallel embed/head "
                f"shards the table over pipe")

    # ------------------------------ init ------------------------------ #

    def init(self, rng, sample_batch) -> Any:
        tokens = sample_batch["tokens"]
        mb = tokens.shape[0] // self.num_microbatches or 1
        T = tokens.shape[1] - 1
        h_sample = jnp.zeros((mb, T, self.hidden_size), self.compute_dtype)
        r_wte, r_wpe, r_fin, r_blk = jax.random.split(rng, 4)
        embed = {"wte": (0.02 * jax.random.normal(
            r_wte, (self.vocab_size, self.hidden_size))).astype(self.param_dtype)}
        if self.max_seq_len is not None:
            embed["wpe"] = (0.01 * jax.random.normal(
                r_wpe, (self.max_seq_len, self.hidden_size))).astype(self.param_dtype)
        blocks = [self.block_init(r, h_sample)
                  for r in jax.random.split(r_blk, self.num_layers)]
        stacked = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *blocks)
        final = self.final_init(r_fin, h_sample) if self.final_init else {}
        return {"embed": embed, "blocks": stacked, "final": final}

    def param_specs(self, params: Any) -> Any:
        """At-rest PartitionSpecs: blocks on the leading [L] dim over pipe
        (+ ``tp_block_specs`` model dims), wte on vocab over pipe;
        wpe/final replicated. Pass as ``tp_specs`` to ``initialize`` so the
        at-rest plan coincides with the step's in_specs (no resharding at
        the jit boundary); ZeRO merges its data axes on other dims."""
        pipe = self.pipe_axis

        return self._spec_tree(params,
                               lambda tp: P(pipe, *tuple(tp)))

    def _manual_in_specs(self, params: Any) -> Any:
        """in_specs for the step's shard_map: ONLY the manual axes (pipe,
        and expert entries from tp_block_specs — MoE weights stay sharded
        per expert rank inside the ring); auto-axis (model) shardings ride
        the arguments' actual placements."""
        pipe = self.pipe_axis
        manual = set(self._manual_axes())

        def strip(tp_spec):
            kept = []
            for s in tuple(tp_spec):
                names = s if isinstance(s, tuple) else (s,)
                kept.append(s if all(n in manual for n in names if n)
                            and s is not None else None)
            while kept and kept[-1] is None:
                kept.pop()
            return P(pipe, *kept)

        return self._spec_tree(params, strip)

    def _spec_tree(self, params: Any, block_leaf_spec: Callable) -> Any:
        """One builder for at-rest specs AND shard_map in_specs — they must
        stay structurally identical (a divergence is a silent reshard at
        the jit boundary). ``block_leaf_spec(tp_spec) -> P`` maps a
        tp_block_specs leaf to the block leaf's spec."""
        pipe = self.pipe_axis
        if self.tp_block_specs is not None:
            blocks = jax.tree_util.tree_map(
                lambda tp, _l: block_leaf_spec(tp), self.tp_block_specs,
                params["blocks"], is_leaf=lambda x: isinstance(x, P))
        else:
            blocks = jax.tree_util.tree_map(lambda _: P(pipe),
                                            params["blocks"])
        specs = {
            "embed": {"wte": P(pipe)},
            "blocks": blocks,
            "final": jax.tree_util.tree_map(lambda _: P(), params["final"]),
        }
        if "wpe" in params["embed"]:
            specs["embed"]["wpe"] = P()
        return specs

    # ----------------------------- loss ------------------------------- #

    def _manual_axes(self):
        """pipe + the batch-carrying axes. ``expert`` is MANUAL (the
        reference's expert-data-parallel: EP ranks are carved out of the
        DP world, so expert ranks hold distinct batch shards and MoE
        blocks run their a2a over the expert axis directly inside the
        ring). ``model`` stays automatic (GSPMD TP).

        Batch convention (same as the standalone MoE layer's
        ``P(("data", "expert"))`` dispatch): expert ranks SUBDIVIDE a data
        rank's shard, and the engine's batch math counts data axes only —
        ``train_micro_batch_size_per_gpu`` is per DATA rank, so each
        (data, expert) device runs micro/ep rows through the dense parts
        too (no duplicated dense compute). micro/m must divide
        data x expert."""
        axes = [self.pipe_axis]
        for a in (DATA_AXIS, "data_inner", "expert"):
            if self.mesh.shape.get(a, 1) > 1:
                axes.append(a)
        return tuple(axes)

    def loss_fn(self, params, batch, rng):
        del rng
        m = self.num_microbatches
        tokens = batch["tokens"]
        if self.num_stages == 1 and self.mesh.shape.get("expert", 1) == 1:
            # pure-EP meshes (pipe=1, expert>1) still need the shard_map
            # ring: block_fns bind expert-axis collectives
            return self._sequential_loss(params, tokens)
        manual = self._manual_axes()
        dp_axes = tuple(a for a in manual if a != self.pipe_axis)
        bspec = P(None, dp_axes) if dp_axes else P(None)
        pspec = self._manual_in_specs(params)
        # constrain AT the reshape seam: the engine hands tokens data-
        # sharded only; the ring wants them (data x expert)-sharded on the
        # microbatch dim. Do the subdivision FIRST (dim 0, a plain
        # dynamic-slice reshard) and only then reshape — asking GSPMD to
        # subdivide and move dims in one transition is what triggered
        # involuntary full rematerialization (spmd_partitioner.cc:652,
        # VERDICT r4 weak #3)
        from jax.sharding import NamedSharding as _NS
        if dp_axes:
            tokens = jax.lax.with_sharding_constraint(
                tokens, _NS(self.mesh, P(dp_axes)))
        micro = jax.lax.with_sharding_constraint(
            tokens.reshape((m, tokens.shape[0] // m) + tokens.shape[1:]),
            _NS(self.mesh, bspec))

        return shard_map(
            self._ring, mesh=self.mesh,
            in_specs=(pspec, bspec), out_specs=P(),
            axis_names=frozenset(manual), check_vma=False)(params, micro)

    # cooperative (vocab-parallel over pipe) embed / loss ---------------- #

    def _coop_embed(self, wte_local, wpe, tok):
        """[mb, T] tokens -> [mb, T, C]; each rank looks up its vocab range,
        psum over pipe combines (Megatron VocabParallelEmbedding)."""
        Vp = wte_local.shape[0]
        lo = jax.lax.axis_index(self.pipe_axis) * Vp
        rel = tok - lo
        ok = (rel >= 0) & (rel < Vp)
        x = jnp.take(wte_local, jnp.clip(rel, 0, Vp - 1), axis=0)
        x = jnp.where(ok[..., None], x, jnp.zeros_like(x))
        x = jax.lax.psum(x, self.pipe_axis)
        if wpe is not None:
            x = x + wpe[: tok.shape[1]]
        return x.astype(self.compute_dtype)

    def _coop_loss(self, final_params, wte_local, h, targets):
        """Fused vocab-parallel next-token xent: h [mb, T, C] (the LAST
        stage's output, broadcast), targets [mb, T]. Each rank computes its
        [mb, T, V/P] logit slice; logsumexp and the target logit combine
        with psums. Full logits never materialize on any rank."""
        if self.final_fn is not None:
            h = self.final_fn(final_params, h)
        Vp = wte_local.shape[0]
        lo = jax.lax.axis_index(self.pipe_axis) * Vp
        logits = jax.lax.dot_general(
            h, wte_local.astype(h.dtype), (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [mb, T, Vp] f32
        # global max via all_gather (differentiable, unlike pmax); the
        # gathered [P, mb, T] maxes are tiny next to the logit slices
        mx = jnp.max(jax.lax.all_gather(
            jnp.max(logits, axis=-1), self.pipe_axis), axis=0)
        s = jax.lax.psum(
            jnp.sum(jnp.exp(logits - mx[..., None]), axis=-1), self.pipe_axis)
        lse = mx + jnp.log(s)
        rel = targets - lo
        ok = (rel >= 0) & (rel < Vp)
        tgt_l = jnp.take_along_axis(
            logits, jnp.clip(rel, 0, Vp - 1)[..., None], axis=-1)[..., 0]
        tgt = jax.lax.psum(jnp.where(ok, tgt_l, 0.0), self.pipe_axis)
        return (lse - tgt).mean()

    def _run_blocks(self, blocks_local, h):
        """Returns (h, aux_sum) — aux is 0 unless block_fn returns
        (h, aux) pairs (MoE load-balance losses)."""
        bfn = jax.checkpoint(self.block_fn) if self.remat else self.block_fn

        def body(h, bp):
            out = bfn(bp, h)
            if isinstance(out, tuple):
                return out[0], out[1].astype(jnp.float32)
            return out, jnp.zeros((), jnp.float32)

        h, auxs = jax.lax.scan(body, h, blocks_local)
        return h, auxs.sum()

    def _sequential_loss(self, params, tokens):
        wte = params["embed"]["wte"]
        wpe = params["embed"].get("wpe")
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
        x = jnp.take(wte, inp, axis=0)
        if wpe is not None:
            x = x + wpe[: inp.shape[1]]
        h, aux = self._run_blocks(params["blocks"],
                                  x.astype(self.compute_dtype))
        if self.final_fn is not None:
            h = self.final_fn(params["final"], h)
        logits = jax.lax.dot_general(
            h, wte.astype(h.dtype), (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        t = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
        return (lse - t).mean() + self.aux_weight * aux

    def _ring(self, params, micro):
        """shard_map body. Every leaf is LOCAL: blocks [L/P, ...], wte
        [V/P, C]; micro [m, mb_local, T+1]."""
        m, P_ = self.num_microbatches, self.num_stages
        idx = jax.lax.axis_index(self.pipe_axis)
        perm = [(i, (i + 1) % P_) for i in range(P_)]
        blocks = params["blocks"]
        wte = params["embed"]["wte"]
        wpe = params["embed"].get("wpe")
        final = params["final"]
        mb, T1 = micro.shape[1], micro.shape[2]
        bshape = (mb, T1 - 1, self.hidden_size)
        total_steps = m + P_ - 1

        def step(carry, t):
            buf_in, loss_acc, aux_acc = carry
            tok_in = jax.lax.dynamic_index_in_dim(
                micro, jnp.clip(t, 0, m - 1), keepdims=False)   # [mb, T+1]
            x_emb = self._coop_embed(wte, wpe, tok_in[:, :-1])
            x_in = jnp.where(idx == 0, x_emb, buf_in)
            h, aux_t = self._run_blocks(blocks, x_in)
            # stage idx processes microbatch t-idx at step t: gate its aux
            my_t = t - idx
            aux_valid = jnp.logical_and(my_t >= 0, my_t <= m - 1)
            aux_acc = aux_acc + jnp.where(aux_valid, aux_t, 0.0)
            # the LAST stage just finished microbatch t-(P-1): broadcast its
            # output and run the cooperative loss on every rank
            t_out = t - (P_ - 1)
            h_last = jax.lax.psum(
                jnp.where(idx == P_ - 1, h, jnp.zeros_like(h)),
                self.pipe_axis)
            tok_out = jax.lax.dynamic_index_in_dim(
                micro, jnp.clip(t_out, 0, m - 1), keepdims=False)
            loss_t = self._coop_loss(final, wte, h_last, tok_out[:, 1:])
            valid = jnp.logical_and(t_out >= 0, t_out <= m - 1)
            loss_acc = loss_acc + jnp.where(valid, loss_t, 0.0)
            buf_next = comm.ppermute(h, perm, axis_name=self.pipe_axis,
                                     log_name="pipe_send_activations")
            return (buf_next, loss_acc, aux_acc), None

        # (1,)-shaped accumulators, NOT scalars: a scalar scan carry inside
        # a shard_map body trips the legacy (pre-0.5) shard_map transpose's
        # residual naming ({0: axes} names on a rank-0 residual ->
        # _SpecError); the singleton axis costs nothing and is squeezed
        # right after the scan
        carry0 = (jnp.zeros(bshape, self.compute_dtype),
                  jnp.zeros((1,), jnp.float32), jnp.zeros((1,), jnp.float32))
        if self.boundary_windows is None:
            (_, loss_sum, aux_sum), _ = jax.lax.scan(step, carry0,
                                                     jnp.arange(total_steps))
        else:
            (_, loss_sum, aux_sum) = _windowed_schedule(
                step, carry0, total_steps, self.boundary_windows)
        loss_sum, aux_sum = loss_sum[0], aux_sum[0]

        loss = loss_sum / m     # already identical on every pipe rank
        if self.aux_weight:
            # each stage accumulated its own layers' aux: sum over pipe
            loss = loss + self.aux_weight * jax.lax.psum(
                aux_sum, self.pipe_axis) / m
        for a in (DATA_AXIS, "data_inner", "expert"):
            if self.mesh.shape.get(a, 1) > 1:
                loss = jax.lax.pmean(loss, a)
        return loss


def _default_lm_loss(out, batch):
    """Mean next-token NLL: ``out`` [mb, T, V] logits, batch["tokens"]
    [mb, T+1]. Computed as logsumexp - target logit (no [mb, T, V] log_softmax
    materialization). For a real vocab, prefer a last stage that emits HIDDEN
    states and a ``loss_fn`` built on ``models/_lm_utils.chunked_lm_xent``
    (hidden @ embedding fused per chunk) — then full logits never exist."""
    targets = batch["tokens"][:, 1:]
    logits = out.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return (lse - tgt).mean()
