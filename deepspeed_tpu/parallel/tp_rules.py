"""Tensor-parallel sharding rules.

TPU-native replacement for the reference's AutoTP
(``module_inject/auto_tp.py:189``: parse an HF module tree, classify each
Linear as column- or row-parallel, slice weights with
``ReplaceWithTensorSlicing``) and for Megatron-style mpu pass-through. Here a
*rule* is a regex over the parameter path mapped to a ``PartitionSpec`` using
the ``model`` mesh axis — no weight copying: ``pjit`` shards the original
arrays and XLA inserts the (all-reduce at row-parallel outputs) collectives.

``infer_tp_specs`` is the AutoTP analogue: given only a params pytree it
classifies projection matrices by shape/name heuristics — fused qkv and MLP
up-projections are column-parallel (shard output dim), attention/MLP output
projections are row-parallel (shard input dim), embeddings shard the vocab
dim, everything else replicates.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from ..utils.logging import log_dist

MODEL_AXIS = "model"

#: default name patterns, mirroring the reference's policy vocabulary
#: (module_inject/containers/*: qkv/dense/h_to_4h/4h_to_h, HF: c_attn/c_proj/c_fc)
#: the T5-style wi/wo names are WORD-BOUNDED: a bare r"wo" also matched
#: "word_embeddings" and silently vocab-sharded every embedding table the
#: generic rules saw (first-match-wins put row before embed)
COLUMN_PATTERNS = [r"c_attn", r"qkv", r"query", r"key", r"value", r"q_proj",
                   r"k_proj", r"v_proj", r"c_fc", r"up_proj", r"gate_proj",
                   r"h_to_4h", r"fc1", r"\bwi(_\w+)?\b"]
ROW_PATTERNS = [r"c_proj", r"o_proj", r"out_proj", r"dense(?!_h)", r"4h_to_h",
                r"fc2", r"\bwo\b", r"down_proj"]
EMBED_PATTERNS = [r"wte", r"embed_tokens", r"word_embeddings", r"embedding\b",
                  r"lm_head"]


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def _spec_col(shape: Tuple[int, ...]) -> P:
    """Column parallel: shard the LAST dim (kernel [in, out] → out)."""
    spec = [None] * len(shape)
    if len(shape) >= 1:
        spec[-1] = MODEL_AXIS
    return P(*spec)


def _spec_row(shape: Tuple[int, ...]) -> P:
    """Row parallel: shard the second-to-last dim (kernel [in, out] → in).
    1-D leaves (bias of a row-parallel matmul) replicate — the matmul output
    is all-reduced first, then bias added once."""
    if len(shape) < 2:
        return P()
    spec = [None] * len(shape)
    spec[-2] = MODEL_AXIS
    return P(*spec)


def _spec_embed(shape: Tuple[int, ...]) -> P:
    """Embedding [vocab, hidden]: shard vocab (dim 0)."""
    spec = [None] * len(shape)
    if len(shape) >= 2:
        spec[0] = MODEL_AXIS
    return P(*spec)


class TPRules:
    """Ordered (regex, kind) rules; first match wins.

    kind: "column" | "row" | "embed" | "replicate" | an explicit PartitionSpec.
    """

    def __init__(self, rules: Optional[Sequence[Tuple[str, Any]]] = None):
        self.rules: List[Tuple[re.Pattern, Any]] = [
            (re.compile(pat), kind) for pat, kind in (rules or [])]

    def add(self, pattern: str, kind: Any) -> "TPRules":
        self.rules.append((re.compile(pattern), kind))
        return self

    def spec_for(self, path: str, shape: Tuple[int, ...], tp_size: int) -> P:
        for pat, kind in self.rules:
            if pat.search(path):
                return _kind_to_spec(kind, shape, tp_size)
        return P()

    def specs_for_tree(self, params: Any, tp_size: int) -> Any:
        """Params-shaped pytree of PartitionSpecs."""
        if tp_size <= 1:
            return jax.tree_util.tree_map(lambda _: P(), params)

        def mk(path, leaf):
            return self.spec_for(_path_str(path), tuple(np.shape(leaf)), tp_size)

        return jax.tree_util.tree_map_with_path(mk, params)


def _kind_to_spec(kind: Any, shape: Tuple[int, ...], tp_size: int) -> P:
    if isinstance(kind, P):
        return kind
    if kind == "replicate":
        return P()
    dim_for = {"column": len(shape) - 1, "row": len(shape) - 2, "embed": 0}
    builder = {"column": _spec_col, "row": _spec_row, "embed": _spec_embed}[kind]
    d = dim_for[kind]
    # only shard when the dim exists and divides evenly
    if d < 0 or d >= len(shape) or shape[d] % tp_size != 0:
        return P()
    return builder(shape)


#: ready-made rules for the in-repo GPT-2 (models/gpt2.py param names)
GPT2_TP_RULES = TPRules([
    (r"attn/c_attn", "column"),
    (r"attn/c_proj", "row"),
    (r"mlp/c_fc", "column"),
    (r"mlp/c_proj", "row"),
    (r"wte/embedding", "embed"),
])


def default_rules() -> TPRules:
    """AutoTP-style generic rules from the shared pattern vocabulary."""
    rules = TPRules()
    for pat in COLUMN_PATTERNS:
        rules.add(pat, "column")
    for pat in ROW_PATTERNS:
        rules.add(pat, "row")
    for pat in EMBED_PATTERNS:
        rules.add(pat, "embed")
    return rules


def infer_tp_specs(params: Any, tp_size: int,
                   rules: Optional[TPRules] = None) -> Any:
    """The AutoTP entry point: produce TP PartitionSpecs for any params tree
    using name-pattern classification (reference auto_tp.py tp_parser
    analogue — instead of module introspection, path-pattern matching)."""
    rules = rules or default_rules()
    specs = rules.specs_for_tree(params, tp_size)
    n_sharded = sum(1 for s in jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
        if any(e is not None for e in tuple(s)))
    log_dist(f"AutoTP: sharded {n_sharded} param tensors over '{MODEL_AXIS}' "
             f"axis (tp={tp_size})")
    return specs
