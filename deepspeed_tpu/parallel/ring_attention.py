"""Ring attention (context parallelism).

The reference has NO ring attention (SURVEY.md §2.4: long context is Ulysses
only). This is the TPU-native extension the survey prescribes: KV blocks
rotate around the ``seq`` mesh axis via ``ppermute`` (nearest-neighbor ICI
traffic) while each device keeps its Q shard and accumulates attention with
an online-softmax, so sequence length scales linearly with the ring size and
full T×T scores never materialize.

Algorithm (blockwise attention / Liu et al. RingAttention):
  each of the sp steps: partial = softmax-accumulate(Q_local, K_rot, V_rot)
  with running (max, denominator, numerator); then ppermute K/V to the next
  ring neighbor. Causal masking uses global block indices.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.jax_compat import shard_map

from .. import comm

SEQ_AXIS = "seq"
DATA_AXIS = "data"
NEG_INF = -1e30


def _ring_attention_local(q, k, v, axis_name: str, causal: bool, sm_scale: float):
    """Runs inside shard_map. q/k/v: [B, T_loc, H, D] local shards."""
    from ..utils.jax_compat import axis_size
    sp = axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, T_loc, H, D = q.shape

    qf = q.astype(jnp.float32) * sm_scale
    # accumulators for online softmax
    numer = jnp.zeros((B, T_loc, H, D), jnp.float32)
    denom = jnp.zeros((B, T_loc, H), jnp.float32)
    row_max = jnp.full((B, T_loc, H), NEG_INF, jnp.float32)

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(carry, r):
        numer, denom, row_max, k_blk, v_blk = carry
        # the block we hold at round r originated on device (my_idx - r) mod sp
        src = (my_idx - r) % sp
        # scores [B, T_loc, H, T_loc]
        scores = jnp.einsum("bqhd,bkhd->bqhk", qf, k_blk.astype(jnp.float32))
        if causal:
            q_pos = my_idx * T_loc + jnp.arange(T_loc)[:, None]       # [Tq,1]
            k_pos = src * T_loc + jnp.arange(T_loc)[None, :]          # [1,Tk]
            mask = (k_pos <= q_pos)[None, :, None, :]                 # [1,Tq,1,Tk]
            scores = jnp.where(mask, scores, NEG_INF)
        blk_max = scores.max(axis=-1)                                  # [B,Tq,H]
        new_max = jnp.maximum(row_max, blk_max)
        # guard fully-masked rows (new_max == NEG_INF)
        safe_max = jnp.where(new_max <= NEG_INF / 2, 0.0, new_max)
        correction = jnp.exp(row_max - safe_max)
        correction = jnp.where(row_max <= NEG_INF / 2, 0.0, correction)
        p = jnp.exp(scores - safe_max[..., None])
        p = jnp.where(scores <= NEG_INF / 2, 0.0, p)
        numer = numer * correction[..., None] + jnp.einsum(
            "bqhk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        denom = denom * correction + p.sum(axis=-1)
        # rotate KV to the next ring neighbor
        k_blk = comm.ppermute(k_blk, perm, axis_name=axis_name)
        v_blk = comm.ppermute(v_blk, perm, axis_name=axis_name)
        return (numer, denom, new_max, k_blk, v_blk), None

    (numer, denom, _, _, _), _ = jax.lax.scan(
        step, (numer, denom, row_max, k, v), jnp.arange(sp))
    out = numer / jnp.maximum(denom, 1e-20)[..., None]
    return out.astype(q.dtype)


def _merge_partials(o1, lse1, o2, lse2):
    """Combine two normalized attention partials by their logsumexps.
    o: [B, T, H, D] fp32; lse: [B, T, H] fp32 (-inf = no contribution)."""
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    w1 = jnp.where(lse1 <= NEG_INF / 2, 0.0, jnp.exp(lse1 - m_safe))
    w2 = jnp.where(lse2 <= NEG_INF / 2, 0.0, jnp.exp(lse2 - m_safe))
    denom = w1 + w2
    denom_safe = jnp.where(denom == 0.0, 1.0, denom)
    o = (o1 * w1[..., None] + o2 * w2[..., None]) / denom_safe[..., None]
    lse = jnp.where(denom == 0.0, NEG_INF, m_safe + jnp.log(denom_safe))
    return o, lse


def _ring_attention_local_kernel(q, k, v, axis_name: str, causal: bool,
                                 sm_scale: float, interpret):
    """Ring accumulation where each round's local attention IS the Pallas
    flash kernel (forward + backward): round 0 is the diagonal block
    (causal mask inside the kernel); later rounds are all-or-nothing blocks
    (full attend when the KV block comes from earlier in the sequence,
    skipped when later), merged by kernel-emitted logsumexp. The lse output
    is differentiable (ops/kernels/flash_attention._flash_lse), so the whole
    ring trains through jax.grad with kernel fwd+bwd."""
    from ..ops.kernels import flash_attention

    from ..utils.jax_compat import axis_size
    sp = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def attend(kb, vb, causal_flag):
        o, lse = flash_attention(q, kb, vb, causal=causal_flag,
                                 sm_scale=sm_scale, layout="BTHD",
                                 interpret=interpret, return_lse=True)
        return o.astype(jnp.float32), lse.swapaxes(1, 2)   # [B,T,H,D],[B,T,H]

    # round 0 holds the locally-originated KV: the diagonal block
    o_acc, lse_acc = attend(k, v, causal)
    k_blk = comm.ppermute(k, perm, axis_name=axis_name)
    v_blk = comm.ppermute(v, perm, axis_name=axis_name)

    def step(carry, r):
        o_acc, lse_acc, k_blk, v_blk = carry
        # the block held at round r originated on device (my_idx - r) mod sp
        src = (my - r) % sp

        def full_block(_):
            return attend(k_blk, v_blk, False)

        def skip(_):
            return (jnp.zeros_like(o_acc),
                    jnp.full(lse_acc.shape, NEG_INF, jnp.float32))

        if causal:
            o_r, lse_r = jax.lax.cond(src < my, full_block, skip, None)
        else:
            o_r, lse_r = full_block(None)
        o_acc, lse_acc = _merge_partials(o_acc, lse_acc, o_r, lse_r)
        k_nxt = comm.ppermute(k_blk, perm, axis_name=axis_name)
        v_nxt = comm.ppermute(v_blk, perm, axis_name=axis_name)
        return (o_acc, lse_acc, k_nxt, v_nxt), None

    if sp > 1:
        (o_acc, lse_acc, _, _), _ = jax.lax.scan(
            step, (o_acc, lse_acc, k_blk, v_blk), jnp.arange(1, sp))
    return o_acc.astype(q.dtype)


def ring_attention(query: jnp.ndarray, key: jnp.ndarray, value: jnp.ndarray,
                   mesh: Mesh, seq_axis: str = SEQ_AXIS, causal: bool = True,
                   sm_scale: Optional[float] = None,
                   use_kernel: Optional[bool] = None,
                   interpret: Optional[bool] = None) -> jnp.ndarray:
    """Context-parallel attention. q/k/v: [B, T, H, D] with T sharded over
    ``seq``; returns [B, T, H, D] with the same sharding.

    ``use_kernel``: run each round's local attention as the Pallas flash
    kernel (default on TPU); False keeps the pure-jnp blockwise path."""
    D = query.shape[-1]
    sm_scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)
    sp = mesh.shape[seq_axis]
    if use_kernel is None:
        use_kernel = jax.default_backend() == "tpu"
    if sp == 1:
        if use_kernel:
            from ..ops.kernels import flash_attention
            return flash_attention(query, key, value, causal=causal,
                                   sm_scale=sm_scale, layout="BTHD",
                                   interpret=interpret)
        return jax.nn.dot_product_attention(query, key, value, is_causal=causal,
                                            scale=sm_scale)

    # batch dim rides the data axis when the mesh has one (avoids replicating
    # a DP-sharded batch across data groups)
    dp = mesh.shape.get(DATA_AXIS, 1)
    batch_axis = DATA_AXIS if dp > 1 and query.shape[0] % dp == 0 else None
    spec = P(batch_axis, seq_axis, None, None)
    if use_kernel:
        fn = functools.partial(_ring_attention_local_kernel,
                               axis_name=seq_axis, causal=causal,
                               sm_scale=sm_scale, interpret=interpret)
    else:
        fn = functools.partial(_ring_attention_local, axis_name=seq_axis,
                               causal=causal, sm_scale=sm_scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(query, key, value)
