from .topology import (Topology, build_mesh, get_topology, set_topology, has_topology,
                       get_data_parallel_world_size, get_model_parallel_world_size,
                       get_sequence_parallel_world_size, get_expert_parallel_world_size,
                       get_pipe_parallel_world_size)
from .pipeline import (LayerSpec, TiedLayerSpec, PipelineModule,
                       StackedPipelineModule, partition_layers,
                       pipeline_apply, stack_stage_params)
from .ulysses import DistributedAttention, ulysses_attention, sp_cross_entropy
from .ring_attention import ring_attention
