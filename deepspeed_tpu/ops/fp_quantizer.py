"""Floating-point (minifloat) quantization — FP6 / FP8 / FP12.

Capability parity with the reference's ``csrc/fp_quantizer/`` (850 LoC of
CUDA selective-GEMM quantization powering fp6/fp8/fp12 quantized parameters,
``deepspeed/linear/quantization.py`` QuantizedParameter — SURVEY.md §2.6).
The TPU version is pure VPU math XLA fuses into the consumer matmul:

  - values are scaled per group so the group max hits the format's max
    representable, then rounded to the nearest representable minifloat
    (exponent/mantissa split emulated with frexp-style bit math);
  - storage is int8 codes (sign + exp + mantissa packed little-endian per
    value; fp6 packs 4 codes into 3 bytes, fp12 packs 2 into 3).

Formats follow the reference: fp6 = e3m2, fp8 = e4m3, fp12 = e4m7.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: q_bits -> (exp_bits, man_bits), matching the reference's supported trio
FORMATS = {6: (3, 2), 8: (4, 3), 12: (4, 7)}


class FPQuantizedTensor(NamedTuple):
    """Minifloat-quantized tensor: bit-packed uint8 codes + f32 scales.

    Storage is real ``q_bits``/value: fp8 is one byte per code, fp6 packs 4
    codes into 3 bytes, fp12 packs 2 codes into 3 bytes."""
    codes: jnp.ndarray            # uint8, bit-packed
    scale: jnp.ndarray            # (groups, 1) f32
    shape: Tuple[int, ...]
    q_bits: int
    group_size: int
    packed: bool


jax.tree_util.register_pytree_node(
    FPQuantizedTensor,
    lambda t: ((t.codes, t.scale),
               (t.shape, t.q_bits, t.group_size, t.packed)),
    lambda aux, ch: FPQuantizedTensor(*ch, *aux),
)


def _minifloat_encode(x: jnp.ndarray, exp_bits: int, man_bits: int):
    """Round |x| <= max_representable to nearest minifloat; return int codes.

    Code layout: sign << (exp_bits + man_bits) | exp << man_bits | mantissa.
    Denormals (exp field 0) represent mantissa * 2^(1 - bias) / 2^man_bits.
    """
    bias = 2 ** (exp_bits - 1) - 1
    sign = (x < 0).astype(jnp.int32)
    ax = jnp.abs(x.astype(jnp.float32))

    # exponent of the value (floor(log2)), clamped into field range
    e = jnp.floor(jnp.log2(jnp.maximum(ax, 1e-38))).astype(jnp.int32)
    e = jnp.clip(e, 1 - bias, bias)
    # normal: mantissa in [1, 2) -> man_bits fraction; denormal handled by
    # clamping e to (1 - bias) so the scale below still applies
    scale = jnp.exp2(e.astype(jnp.float32))
    frac = ax / scale                           # [1, 2) for normals
    m = jnp.round((frac - 1.0) * (1 << man_bits)).astype(jnp.int32)
    # rounding can overflow mantissa -> bump exponent
    bump = m >= (1 << man_bits)
    e = jnp.where(bump & (e < bias), e + 1, e)
    m = jnp.where(bump, 0, m)
    m = jnp.clip(m, 0, (1 << man_bits) - 1)

    # subnormal region: values below 2^(1-bias) use exp field 0
    min_normal = 2.0 ** (1 - bias)
    sub = ax < min_normal
    m_sub = jnp.round(ax / min_normal * (1 << man_bits)).astype(jnp.int32)
    m_sub = jnp.clip(m_sub, 0, (1 << man_bits) - 1)
    efield = jnp.where(sub, 0, e + bias)
    m = jnp.where(sub, m_sub, m)

    code = (sign << (exp_bits + man_bits)) | (efield << man_bits) | m
    return code.astype(jnp.int16)


def _minifloat_decode(code: jnp.ndarray, exp_bits: int, man_bits: int):
    bias = 2 ** (exp_bits - 1) - 1
    code = code.astype(jnp.int32)
    m = code & ((1 << man_bits) - 1)
    efield = (code >> man_bits) & ((1 << exp_bits) - 1)
    sign = (code >> (exp_bits + man_bits)) & 1
    min_normal = 2.0 ** (1 - bias)
    normal = efield > 0
    mag = jnp.where(
        normal,
        jnp.exp2(efield.astype(jnp.float32) - bias) *
        (1.0 + m.astype(jnp.float32) / (1 << man_bits)),
        min_normal * m.astype(jnp.float32) / (1 << man_bits))
    return jnp.where(sign == 1, -mag, mag)


def _pack_codes(codes: jnp.ndarray, q_bits: int) -> jnp.ndarray:
    """Bit-pack a flat int16 code array (values < 2**q_bits) into uint8."""
    c = codes.reshape(-1).astype(jnp.uint32)
    if q_bits == 8:
        return c.astype(jnp.uint8)
    if q_bits == 6:                            # 4 codes -> 3 bytes
        pad = (-c.shape[0]) % 4
        c = jnp.pad(c, (0, pad)).reshape(-1, 4)
        v = c[:, 0] | (c[:, 1] << 6) | (c[:, 2] << 12) | (c[:, 3] << 18)
    elif q_bits == 12:                         # 2 codes -> 3 bytes
        pad = (-c.shape[0]) % 2
        c = jnp.pad(c, (0, pad)).reshape(-1, 2)
        v = c[:, 0] | (c[:, 1] << 12)
    else:
        raise ValueError(q_bits)
    return jnp.stack([v & 0xFF, (v >> 8) & 0xFF, (v >> 16) & 0xFF],
                     axis=1).reshape(-1).astype(jnp.uint8)


def _unpack_codes(packed: jnp.ndarray, q_bits: int, n: int) -> jnp.ndarray:
    """Inverse of :func:`_pack_codes`; returns ``n`` int16 codes."""
    if q_bits == 8:
        return packed.astype(jnp.int16)[:n]
    b = packed.astype(jnp.uint32).reshape(-1, 3)
    v = b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16)
    if q_bits == 6:
        c = jnp.stack([v & 0x3F, (v >> 6) & 0x3F, (v >> 12) & 0x3F,
                       (v >> 18) & 0x3F], axis=1)
    else:                                      # 12
        c = jnp.stack([v & 0xFFF, (v >> 12) & 0xFFF], axis=1)
    return c.reshape(-1)[:n].astype(jnp.int16)


def _max_representable(exp_bits: int, man_bits: int) -> float:
    bias = 2 ** (exp_bits - 1) - 1
    return float(2.0 ** bias * (2.0 - 2.0 ** (-man_bits)))


def fp_quantize(x: jnp.ndarray, q_bits: int = 6,
                group_size: int = 128) -> FPQuantizedTensor:
    """Group-scale + minifloat-round ``x`` (any shape)."""
    if q_bits not in FORMATS:
        raise ValueError(f"q_bits must be one of {sorted(FORMATS)}, "
                         f"got {q_bits}")
    exp_bits, man_bits = FORMATS[q_bits]
    shape = tuple(x.shape)
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    pad = (-n) % group_size
    gr = jnp.pad(flat, (0, pad)).reshape(-1, group_size)
    absmax = jnp.max(jnp.abs(gr), axis=1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / _max_representable(exp_bits, man_bits)
    codes = _minifloat_encode(gr / scale, exp_bits, man_bits)
    return FPQuantizedTensor(codes=_pack_codes(codes, q_bits), scale=scale,
                             shape=shape, q_bits=q_bits,
                             group_size=group_size, packed=True)


def fp_dequantize(t: FPQuantizedTensor, dtype=jnp.float32) -> jnp.ndarray:
    exp_bits, man_bits = FORMATS[t.q_bits]
    n = int(np.prod(t.shape)) if t.shape else 1
    n_codes = -(-n // t.group_size) * t.group_size
    codes = _unpack_codes(t.codes, t.q_bits, n_codes)
    vals = _minifloat_decode(codes.reshape(-1, t.group_size),
                             exp_bits, man_bits) * t.scale
    return vals.reshape(-1)[:n].reshape(t.shape).astype(dtype)


def fp_quant_dequant(x: jnp.ndarray, q_bits: int = 6,
                     group_size: int = 128) -> jnp.ndarray:
    """Fake-quant round trip in the target minifloat format."""
    return fp_dequantize(fp_quantize(x, q_bits, group_size), x.dtype)
