"""Block-sparse attention — sparsity layouts + masked attention.

Capability parity with the reference's ``ops/sparse_attention/`` (Triton
block-sparse matmul + ``SparsityConfig`` family: Dense/Fixed/Variable/
BigBird/BSLongformer, ``sparsity_config.py`` — SURVEY.md §2.6
``csrc/sparse_attention`` row). Layout semantics match the reference:
a (heads, nb, nb) boolean block mask over ``block``-sized tiles where entry
[h, i, j] allows query block i to attend key block j.

Execution is TPU-shaped: the layout expands to a block mask consumed by a
single fused attention (XLA fuses mask+softmax+matmul; a dedicated
skip-blocks Pallas kernel is the splash-attention upgrade path). The
attention math matches ``SparseSelfAttention`` (softmax over allowed blocks
only, optional causal combine).
"""

from __future__ import annotations

import math
import random
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = float(np.finfo(np.float32).min)


class SparsityConfig:
    """Base: dense unless subclass overrides (reference sparsity_config.py:10)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block:
            raise ValueError(
                f"seq_len ({seq_len}) must be divisible by block "
                f"({self.block})")
        nb = seq_len // self.block
        return np.zeros((self.num_heads, nb, nb), dtype=bool)

    def propagate_first_head(self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = True
        return layout


class DenseSparsityConfig(SparsityConfig):
    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = True
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Local windows + periodic global blocks (reference :95; the GPT-3
    'fixed' pattern)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        causal = self.attention == "unidirectional"
        for h in range(self.num_layout_heads):
            # local: dense within each window of num_local_blocks
            for start in range(0, nb, self.num_local_blocks):
                end = min(start + self.num_local_blocks, nb)
                for i in range(start, end):
                    jend = (i + 1) if causal else end
                    layout[h, i, start:jend] = True
            # global: last num_global_blocks of each window attend/attended
            pattern = h % self.num_different_global_patterns
            for start in range(0, nb, self.num_local_blocks):
                end = min(start + self.num_local_blocks, nb)
                g0 = max(start, end - (pattern + 1) * self.num_global_blocks)
                g1 = min(end, g0 + self.num_global_blocks)
                # vertical: global columns visible to all rows
                # (bidirectional) or to rows at/after the window (causal)
                first = 0 if not causal else start
                layout[h, first:, g0:g1] = True
                if self.horizontal_global_attention and not causal:
                    layout[h, g0:g1, :] = True
        if causal:
            tri = np.tril(np.ones((nb, nb), dtype=bool))
            layout &= tri
        return self.propagate_first_head(layout)


class VariableSparsityConfig(SparsityConfig):
    """Random + custom local windows + leading global blocks (reference :239)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks: int = 0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False, seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        rng = random.Random(self.seed)
        causal = self.attention == "unidirectional"
        for h in range(self.num_layout_heads):
            # local windows of varying sizes, repeated cyclically
            i = 0
            w = 0
            while i < nb:
                size = self.local_window_blocks[
                    min(w, len(self.local_window_blocks) - 1)]
                end = min(i + size, nb)
                layout[h, i:end, i:end] = True
                i, w = end, w + 1
            # random blocks per row
            for i in range(nb):
                for j in rng.sample(range(nb), min(self.num_random_blocks, nb)):
                    layout[h, i, j] = True
            # globals
            ends = self.global_block_end_indices
            for gi, g in enumerate(self.global_block_indices):
                g1 = (ends[gi] if ends else g + 1)
                layout[h, :, g:g1] = True
                if self.horizontal_global_attention:
                    layout[h, g:g1, :] = True
        if causal:
            layout &= np.tril(np.ones((nb, nb), dtype=bool))
        return self.propagate_first_head(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """random + sliding window + global (reference :411)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks: int = 1, num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional", seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        rng = random.Random(self.seed)
        w = self.num_sliding_window_blocks // 2
        causal = self.attention == "unidirectional"
        for h in range(self.num_layout_heads):
            for i in range(nb):
                layout[h, i, max(0, i - w):min(nb, i + w + 1)] = True
                for j in rng.sample(range(nb),
                                    min(self.num_random_blocks, nb)):
                    layout[h, i, j] = True
            g = min(self.num_global_blocks, nb)
            layout[h, :, :g] = True
            layout[h, :g, :] = True
        if causal:
            layout &= np.tril(np.ones((nb, nb), dtype=bool))
        return self.propagate_first_head(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """sliding window + selected global blocks (reference Longformer)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices=None, global_block_end_indices=None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for i in range(nb):
                layout[h, i, max(0, i - w):min(nb, i + w + 1)] = True
            ends = self.global_block_end_indices
            for gi, g in enumerate(self.global_block_indices):
                g1 = (ends[gi] if ends else g + 1)
                layout[h, :, g:g1] = True
                layout[h, g:g1, :] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((nb, nb), dtype=bool))
        return self.propagate_first_head(layout)


# --------------------------------------------------------------------------- #
# execution
# --------------------------------------------------------------------------- #


def coarsen_layout(layout: np.ndarray, from_block: int,
                   to_block: int = 128) -> np.ndarray:
    """Re-tile a block layout to the kernel granularity.

    ``from_block > to_block`` expands by repetition (always exact);
    ``from_block < to_block`` OR-reduces — callers that need exactness must
    check with :func:`coarsening_is_exact` (adding attention silently would
    break causal layouts)."""
    if from_block >= to_block:
        if from_block % to_block:
            raise ValueError(f"{from_block} not a multiple of {to_block}")
        r = from_block // to_block
        return np.repeat(np.repeat(layout, r, axis=1), r, axis=2)
    if to_block % from_block:
        raise ValueError(f"{to_block} not a multiple of {from_block}")
    r = to_block // from_block
    h, nq, nk = layout.shape
    pad_q, pad_k = (-nq) % r, (-nk) % r
    if pad_q or pad_k:
        layout = np.pad(layout, ((0, 0), (0, pad_q), (0, pad_k)))
        nq, nk = layout.shape[1:]
    return layout.reshape(h, nq // r, r, nk // r, r).any(axis=(2, 4))


def coarsening_is_exact(layout: np.ndarray, from_block: int,
                        to_block: int = 128) -> bool:
    """True when re-tiling to ``to_block`` adds no attention (every coarse
    block is either fully allowed or fully masked in the fine layout)."""
    if from_block >= to_block:
        return True
    coarse = coarsen_layout(layout, from_block, to_block)
    back = coarsen_layout(coarse, to_block, from_block)
    h, nq, nk = layout.shape
    return bool((back[:, :nq, :nk] == layout.astype(bool)).all())


def sparse_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     sparsity_config: SparsityConfig, *,
                     sm_scale: Optional[float] = None,
                     layout: Optional[np.ndarray] = None,
                     layout_mask: Optional[jnp.ndarray] = None,
                     impl: str = "xla") -> jnp.ndarray:
    """Block-sparse attention over BHTD tensors (reference
    ``SparseSelfAttention.forward``): scores outside the layout get -inf
    before softmax. Pass ``layout`` to reuse a precomputed pattern.

    ``impl="flash"`` dispatches to the Pallas block-skipping kernel
    (forward-only — inference/serving path; masked blocks never touch the
    MXU). The kernel tiles at 128 and applies no intra-block masking, so
    the layout must re-tile to 128 blocks EXACTLY — a layout whose
    coarsening would add attention (e.g. a fine-grained causal pattern)
    raises rather than silently attending extra (or future) tokens. The
    default XLA path applies the exact layout and is differentiable."""
    if impl == "flash":
        if layout_mask is not None:
            raise ValueError(
                "impl='flash' takes a block-level 'layout', not a token-"
                "level 'layout_mask' (the kernel skips whole 128-blocks)")
        if layout is None:
            layout = sparsity_config.make_layout(q.shape[2])
        fine = np.asarray(layout, bool)
        if not coarsening_is_exact(fine, sparsity_config.block):
            raise ValueError(
                "impl='flash': this layout does not re-tile exactly to the "
                "kernel's 128-block granularity (coarsening would ADD "
                "attention — for unidirectional layouts that breaks "
                "causality). Use a block size that divides into 128-aligned "
                "patterns, or impl='xla'")
        from .kernels.flash_attention import flash_attention_sparse
        bm = coarsen_layout(fine, sparsity_config.block)
        return flash_attention_sparse(q, k, v, bm, sm_scale=sm_scale,
                                      layout="BHTD")
    b, h, t, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if layout_mask is None:
        if layout is None:
            layout = sparsity_config.make_layout(t)
        block = sparsity_config.block
        mask = np.kron(layout, np.ones((block, block), dtype=bool))
        layout_mask = jnp.asarray(mask)                  # (H or 1, T, T)
    if layout_mask.shape[0] == 1 and h > 1:
        layout_mask = jnp.broadcast_to(layout_mask, (h, t, t))

    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    s = jnp.where(layout_mask[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # rows with no allowed block (fully masked) produce uniform garbage;
    # zero them like the reference's zero-fill
    any_allowed = layout_mask.any(axis=-1)               # (H, T)
    p = jnp.where(any_allowed[None, :, :, None], p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


class SparseSelfAttention:
    """Thin callable wrapper matching the reference module's surface."""

    def __init__(self, sparsity_config: SparsityConfig,
                 attn_mask_mode: str = "mul"):
        self.sparsity_config = sparsity_config
        self._layout_cache = {}

    def __call__(self, q, k, v):
        t = q.shape[2]
        if t not in self._layout_cache:
            layout = self.sparsity_config.make_layout(t)
            block = self.sparsity_config.block
            # cache HOST arrays only: a jnp constant created while tracing
            # would leak that trace's tracer into later jits
            self._layout_cache[t] = np.kron(
                layout, np.ones((block, block), dtype=bool))
        return sparse_attention(q, k, v, self.sparsity_config,
                                layout_mask=jnp.asarray(self._layout_cache[t]))
