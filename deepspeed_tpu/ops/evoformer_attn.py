"""Evoformer (triangle) attention — DeepSpeed4Science parity.

Capability parity with the reference's ``csrc/deepspeed4science/evoformer_attn/``
(CUTLASS fused EvoformerAttention fwd/bwd powering AlphaFold-style MSA-row /
MSA-column / triangle attention; python surface
``deepspeed/ops/deepspeed4science/evoformer_attn.py`` ``DS4Sci_EvoformerAttention``).

Shapes follow the reference API:
    q, k, v : [B, N, S, H, D]   (batch, MSA rows / pair dim, seq, heads, dim)
    biases  : list of broadcastable additive logit biases, typically
              [B, N, 1, 1, S] (per-row mask bias) and
              [B, 1, H, S, S] (pair / triangle bias)

The TPU form leans on XLA for small shapes (one einsum-softmax-einsum chain
the compiler fuses), and CHUNKS the query dimension for AlphaFold-scale
shapes — the reference's CUTLASS kernel exists precisely because the full
[B, N, H, S, S] bias-added score tensor blows memory at real MSA sizes; the
chunked scan bounds peak memory at O(B*N*H*chunk*S) with ``jax.checkpoint``
recomputing each chunk's scores in backward. fp32 softmax accumulation
regardless of input dtype (the reference kernel does the same).
Differentiable end-to-end.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

#: auto-chunk once the fp32 score tensor would exceed this many bytes
_FUSED_SCORE_BUDGET = 1 << 30


def _attend(q, k, v, biases, scale):
    """[B, N, Cq, H, D] x [B, N, Sk, H, D] -> [B, N, Cq, H, D]; biases
    already sliced to the chunk."""
    logits = jnp.einsum("bnqhd,bnkhd->bnhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    for b in biases:
        logits = logits + b
    # fully-masked rows (every key at -inf) would make softmax emit NaN
    # (max-subtraction yields -inf - -inf); substitute finite logits for
    # those rows and zero their probabilities — matching the flash
    # kernel's 0-output convention, with clean (zero) gradients
    row_max = jnp.max(logits, axis=-1, keepdims=True)
    fully_masked = row_max == -jnp.inf
    logits = jnp.where(fully_masked, 0.0, logits)
    probs = jax.nn.softmax(logits, axis=-1)
    probs = jnp.where(fully_masked, 0.0, probs)
    return jnp.einsum("bnhqk,bnkhd->bnqhd", probs, v.astype(jnp.float32))


def DS4Sci_EvoformerAttention(q: jnp.ndarray, k: jnp.ndarray,
                              v: jnp.ndarray,
                              biases: Optional[Sequence[Optional[jnp.ndarray]]]
                              = None,
                              chunk_size: Optional[int] = None,
                              use_kernel: Optional[bool] = None) -> jnp.ndarray:
    """Fused evoformer attention (reference-API name kept verbatim).

    ``chunk_size``: query-dim tile for the memory-bounded path. None = auto
    (fused below ~1 GiB of fp32 scores, 128-wide chunks above); pass
    ``q.shape[2]`` to force fusion.

    ``use_kernel``: route through the Pallas flash kernel
    (``ops.kernels.evoformer``) when the biases are the two canonical
    reference layouts. None = auto (kernel on TPU, jnp elsewhere);
    non-canonical bias layouts always take the jnp path.
    """
    if q.ndim != 5:
        raise ValueError(f"expected [B, N, S, H, D] tensors, got {q.shape}")
    B, N, Sq, H, d = q.shape
    Sk = k.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    bs = []
    for bias in biases or ():
        if bias is None:
            continue
        b = bias.astype(jnp.float32)
        if b.ndim != 5:
            raise ValueError(
                f"bias must be 5-D broadcastable to "
                f"[B, N, H, Sq, Sk], got {b.shape}")
        # reference bias layouts are [B, N, 1, 1, Sk] / [B, 1, H, Sq, Sk] —
        # already aligned with [B, N, H, Sq, Sk]
        bs.append(b)

    if use_kernel is None:
        use_kernel = jax.default_backend() == "tpu"
    if use_kernel:
        mb = pb = None
        ok = True
        for b in bs:
            if b.shape[1:4] == (N, 1, 1) and mb is None:
                mb = b[:, :, 0, 0, :]                  # [B, N, Sk]
            elif b.shape[1] == 1 and b.shape[2:4] == (H, Sq) and pb is None:
                pb = b[:, 0]                           # [B, H, Sq, Sk]
            else:
                ok = False                             # non-canonical layout
        if ok:
            from .kernels.evoformer import evoformer_flash
            return evoformer_flash(q, k, v, mb, pb)

    if chunk_size is None:
        score_bytes = 4 * B * N * H * Sq * Sk
        chunk_size = Sq if score_bytes <= _FUSED_SCORE_BUDGET else 128
    if chunk_size >= Sq:
        return _attend(q, k, v, bs, scale).astype(q.dtype)

    nc = -(-Sq // chunk_size)

    @jax.checkpoint
    def chunk(i):
        # the last chunk clamps back instead of padding (its overlap with
        # the previous chunk recomputes identical rows)
        start = jnp.minimum(i * chunk_size, Sq - chunk_size)
        qc = jax.lax.dynamic_slice_in_dim(q, start, chunk_size, 2)
        bc = [b if b.shape[3] == 1 else
              jax.lax.dynamic_slice_in_dim(b, start, chunk_size, 3)
              for b in bs]
        return _attend(qc, k, v, bc, scale)

    outs = jax.lax.map(chunk, jnp.arange(nc))    # [nc, B, N, c, H, D]
    out = jnp.zeros((B, N, Sq, H, d), jnp.float32)
    for i in range(nc):
        start = min(i * chunk_size, Sq - chunk_size)
        out = jax.lax.dynamic_update_slice_in_dim(out, outs[i], start, 2)
    return out.astype(q.dtype)
