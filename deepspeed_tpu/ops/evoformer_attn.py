"""Evoformer (triangle) attention — DeepSpeed4Science parity.

Capability parity with the reference's ``csrc/deepspeed4science/evoformer_attn/``
(CUTLASS fused EvoformerAttention fwd/bwd powering AlphaFold-style MSA-row /
MSA-column / triangle attention; python surface
``deepspeed/ops/deepspeed4science/evoformer_attn.py`` ``DS4Sci_EvoformerAttention``).

Shapes follow the reference API:
    q, k, v : [B, N, S, H, D]   (batch, MSA rows / pair dim, seq, heads, dim)
    biases  : list of broadcastable additive logit biases, typically
              [B, N, 1, 1, S] (per-row mask bias) and
              [B, 1, H, S, S] (pair / triangle bias)

The TPU form leans on XLA: one einsum-softmax-einsum chain the compiler
fuses; fp32 softmax accumulation regardless of input dtype (the reference
kernel does the same). Differentiable end-to-end (no custom VJP needed).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp


def DS4Sci_EvoformerAttention(q: jnp.ndarray, k: jnp.ndarray,
                              v: jnp.ndarray,
                              biases: Optional[Sequence[Optional[jnp.ndarray]]]
                              = None) -> jnp.ndarray:
    """Fused evoformer attention (reference-API name kept verbatim)."""
    if q.ndim != 5:
        raise ValueError(f"expected [B, N, S, H, D] tensors, got {q.shape}")
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    # [B, N, H, Sq, Sk]
    logits = jnp.einsum("bnqhd,bnkhd->bnhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    for bias in biases or ():
        if bias is None:
            continue
        b = bias.astype(jnp.float32)
        if b.ndim != 5:
            raise ValueError(
                f"bias must be 5-D broadcastable to {logits.shape}, "
                f"got {b.shape}")
        # reference bias layouts are [B, N, 1, 1, Sk] / [B, 1, H, Sq, Sk] —
        # already aligned with [B, N, H, Sq, Sk]
        logits = logits + b
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnhqk,bnkhd->bnqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
