"""Optimizer factory.

Analogue of the reference's ``_configure_basic_optimizer``
(``runtime/engine.py:1322``) and the ``deepspeed/ops/{adam,lamb,lion,adagrad}``
fused-kernel families. On TPU, "fused" means the optimizer update compiles to
one XLA fusion over the flat param pytree — optax already expresses the math;
the MXU/VPU fusion comes from jit. Name strings match ds_config values
(``Adam``, ``AdamW``, ``FusedAdam``, ``Lamb``, ``Lion``, ``Adagrad``, ``SGD``,
``OneBit*`` — the 1-bit variants warm up as their base optimizer and switch to
error-compensated compressed gradient communication, see
``deepspeed_tpu/runtime/compressed_grads.py``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Union

import optax

ScalarOrSchedule = Union[float, Callable]


def _betas(params: Dict[str, Any], default=(0.9, 0.999)):
    betas = params.get("betas", default)
    return float(betas[0]), float(betas[1])


def build_optimizer(
    opt_type: str,
    opt_params: Dict[str, Any],
    learning_rate: Optional[ScalarOrSchedule] = None,
) -> optax.GradientTransformation:
    """Build an optax optimizer from a ds_config ``optimizer`` block.

    ``learning_rate`` (a float or a step->lr schedule) overrides
    ``opt_params["lr"]`` when given — the engine passes its LR schedule here.
    """
    params = dict(opt_params)
    lr = learning_rate if learning_rate is not None else params.get("lr", 1e-3)
    wd = float(params.get("weight_decay", 0.0))
    eps = float(params.get("eps", 1e-8))
    name = opt_type.lower()

    if name in ("adam", "fusedadam", "onebitadam", "zerooneadam", "muadam"):
        b1, b2 = _betas(params)
        # reference FusedAdam defaults adam_w_mode=True (decoupled decay);
        # adam_w_mode=False means classic L2 (decay folded into the gradient
        # before the Adam moments)
        if params.get("adam_w_mode", True):
            return optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
        tx = optax.adam(lr, b1=b1, b2=b2, eps=eps)
        if wd > 0:
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx
    if name in ("adamw", "fusedadamw", "muadamw", "cpuadam", "deepspeedcpuadam"):
        b1, b2 = _betas(params)
        return optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    if name in ("lamb", "fusedlamb", "onebitlamb"):
        b1, b2 = _betas(params)
        return optax.lamb(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    if name in ("lion", "fusedlion"):
        b1, b2 = _betas(params, default=(0.9, 0.99))
        return optax.lion(lr, b1=b1, b2=b2, weight_decay=wd)
    if name == "adagrad":
        return optax.adagrad(lr, eps=eps)
    if name in ("sgd", "musgd"):
        momentum = float(params.get("momentum", 0.0)) or None
        tx = optax.sgd(lr, momentum=momentum, nesterov=bool(params.get("nesterov", False)))
        if wd > 0:
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx
    raise ValueError(f"Unknown optimizer type '{opt_type}'")


#: optimizer names whose 1-bit compressed-communication variant is requested
ONEBIT_OPTIMIZERS = {"onebitadam", "onebitlamb", "zerooneadam"}


def is_onebit(opt_type: str) -> bool:
    return opt_type.lower() in ONEBIT_OPTIMIZERS
