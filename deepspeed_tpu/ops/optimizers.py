"""Optimizer factory.

Analogue of the reference's ``_configure_basic_optimizer``
(``runtime/engine.py:1322``) and the ``deepspeed/ops/{adam,lamb,lion,adagrad}``
fused-kernel families. On TPU, "fused" means the optimizer update compiles to
one XLA fusion over the flat param pytree — optax already expresses the math;
the MXU/VPU fusion comes from jit. Name strings match ds_config values
(``Adam``, ``AdamW``, ``FusedAdam``, ``Lamb``, ``Lion``, ``Adagrad``, ``SGD``,
``OneBit*`` — the 1-bit variants warm up as their base optimizer and switch to
error-compensated compressed gradient communication, see
``deepspeed_tpu/runtime/compressed_grads.py``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Union

import optax

ScalarOrSchedule = Union[float, Callable]


def _betas(params: Dict[str, Any], default=(0.9, 0.999)):
    betas = params.get("betas", default)
    return float(betas[0]), float(betas[1])


def build_optimizer(
    opt_type: str,
    opt_params: Dict[str, Any],
    learning_rate: Optional[ScalarOrSchedule] = None,
) -> optax.GradientTransformation:
    """Build an optax optimizer from a ds_config ``optimizer`` block.

    ``learning_rate`` (a float or a step->lr schedule) overrides
    ``opt_params["lr"]`` when given — the engine passes its LR schedule here.
    """
    params = dict(opt_params)
    lr = learning_rate if learning_rate is not None else params.get("lr", 1e-3)
    wd = float(params.get("weight_decay", 0.0))
    eps = float(params.get("eps", 1e-8))
    name = opt_type.lower()

    if name in ("onebitadam", "zerooneadam"):
        b1, b2 = _betas(params)
        return onebit_adam(
            lr, b1=b1, b2=b2, eps=eps, weight_decay=wd,
            freeze_step=int(params.get("freeze_step", 100)),
            var_update_interval=(int(params.get("var_update_scaler", 16))
                                 if name == "zerooneadam" else 0))
    if name == "onebitlamb":
        b1, b2 = _betas(params)
        return onebit_lamb(
            lr, b1=b1, b2=b2, eps=eps, weight_decay=wd,
            freeze_step=int(params.get("freeze_step", 100)))
    if name in ("adam", "fusedadam", "muadam"):
        b1, b2 = _betas(params)
        # reference FusedAdam defaults adam_w_mode=True (decoupled decay);
        # adam_w_mode=False means classic L2 (decay folded into the gradient
        # before the Adam moments)
        if params.get("adam_w_mode", True):
            return optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
        tx = optax.adam(lr, b1=b1, b2=b2, eps=eps)
        if wd > 0:
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx
    if name in ("adamw", "fusedadamw", "muadamw", "cpuadam", "deepspeedcpuadam"):
        b1, b2 = _betas(params)
        if params.get("moment_dtype"):
            # TPU extension (no ds_config analogue): store BOTH Adam moments
            # in a compact dtype with fp32 update math. At 16 GiB HBM/chip
            # this is what makes billion-param single-chip training state
            # chip-resident (1.3B x fp32 m+v alone is 10.5 GiB; bf16 halves
            # it) — the role the reference fills with CPU-offloaded fp32
            # state (runtime/zero/stage_1_and_2.py cpu_offload), which on a
            # TPU host would serialize every step over PCIe.
            return adamw_compact(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd,
                                 moment_dtype=params["moment_dtype"])
        return optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    if name in ("lamb", "fusedlamb", "onebitlamb"):
        b1, b2 = _betas(params)
        return optax.lamb(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    if name in ("lion", "fusedlion"):
        b1, b2 = _betas(params, default=(0.9, 0.99))
        return optax.lion(lr, b1=b1, b2=b2, weight_decay=wd)
    if name == "adagrad":
        return optax.adagrad(lr, eps=eps)
    if name in ("sgd", "musgd"):
        momentum = float(params.get("momentum", 0.0)) or None
        tx = optax.sgd(lr, momentum=momentum, nesterov=bool(params.get("nesterov", False)))
        if wd > 0:
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx
    raise ValueError(f"Unknown optimizer type '{opt_type}'")


class _CompactAdamState(NamedTuple):
    count: Any
    mu: Any
    nu: Any


def adamw_compact(lr, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
                  moment_dtype="bfloat16"):
    """AdamW with moments STORED in ``moment_dtype`` (bf16 halves optimizer
    state vs fp32) and all update arithmetic in fp32. nu (the squared-grad
    EMA) is stored as sqrt(nu): bf16 carries ~3 significant digits, and the
    square root halves the dynamic range so tiny variances don't flush to
    zero; the update squares it back up in fp32."""
    import jax
    import jax.numpy as jnp

    mdt = jnp.dtype(moment_dtype)

    def init(params):
        z = lambda p: jnp.zeros_like(p, dtype=mdt)  # noqa: E731
        return _CompactAdamState(count=jnp.zeros((), jnp.int32),
                                 mu=jax.tree_util.tree_map(z, params),
                                 nu=jax.tree_util.tree_map(z, params))

    def update(grads, state, params=None):
        count = state.count + 1
        lr_t = lr(state.count) if callable(lr) else lr
        c1 = 1 - b1 ** count.astype(jnp.float32)
        c2 = 1 - b2 ** count.astype(jnp.float32)

        def mom(g, m):
            return (b1 * m.astype(jnp.float32)
                    + (1 - b1) * g.astype(jnp.float32)).astype(mdt)

        def var(g, s):       # s stores sqrt(nu)
            v = s.astype(jnp.float32) ** 2
            v = b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32))
            return jnp.sqrt(v).astype(mdt)

        mu = jax.tree_util.tree_map(mom, grads, state.mu)
        nu = jax.tree_util.tree_map(var, grads, state.nu)

        def upd(m, s, p):
            v = s.astype(jnp.float32) ** 2
            u = (m.astype(jnp.float32) / c1) / (jnp.sqrt(v / c2) + eps)
            if weight_decay:
                u = u + weight_decay * p.astype(jnp.float32)
            return (-lr_t * u).astype(p.dtype)

        updates = jax.tree_util.tree_map(upd, mu, nu, params)
        return updates, _CompactAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


#: optimizer names whose 1-bit compressed-communication variant is requested
ONEBIT_OPTIMIZERS = {"onebitadam", "onebitlamb", "zerooneadam"}


def is_onebit(opt_type: str) -> bool:
    return opt_type.lower() in ONEBIT_OPTIMIZERS


def onebit_freeze_step(opt_params: Dict[str, Any]) -> int:
    return int(opt_params.get("freeze_step", 100))


# --------------------------------------------------------------------------- #
# 1-bit optimizer math (reference runtime/fp16/onebit/{adam,lamb,zoadam}.py):
# standard moments during warmup; after freeze_step the second moment (and
# its bias correction) is frozen so the update direction depends only on the
# (compressed-communicated) first moment. ZeroOneAdam additionally refreshes
# the variance on a fixed interval (simplification of its learning-rate /
# variance update schedules).
# --------------------------------------------------------------------------- #


class _OnebitState(NamedTuple):
    count: Any
    mu: Any
    nu: Any


def _onebit_base(lr, b1, b2, eps, weight_decay, freeze_step,
                 var_update_interval=0, trust_ratio=False):
    """Shared 1-bit optimizer core; ``trust_ratio`` adds LAMB's layer
    adaptation. Moments are updated with two independent tree_maps so
    tuple-structured param trees work (no pair-splitting)."""
    import jax
    import jax.numpy as jnp

    def init(params):
        z = lambda p: jnp.zeros_like(p, dtype=jnp.float32)  # noqa: E731
        return _OnebitState(count=jnp.zeros((), jnp.int32),
                            mu=jax.tree_util.tree_map(z, params),
                            nu=jax.tree_util.tree_map(z, params))

    def update(grads, state, params=None):
        count = state.count + 1
        in_warmup = count <= freeze_step
        if var_update_interval:
            in_warmup = jnp.logical_or(in_warmup,
                                       count % var_update_interval == 0)

        mu = jax.tree_util.tree_map(
            lambda g, m: b1 * m + (1 - b1) * g.astype(jnp.float32),
            grads, state.mu)
        nu = jax.tree_util.tree_map(
            lambda g, n: jnp.where(
                in_warmup,
                b2 * n + (1 - b2) * jnp.square(g.astype(jnp.float32)), n),
            grads, state.nu)

        lr_t = lr(state.count) if callable(lr) else lr
        c1 = 1 - b1 ** count.astype(jnp.float32)
        # nu's bias correction freezes with nu itself
        c2 = 1 - b2 ** jnp.minimum(count, freeze_step).astype(jnp.float32)

        def upd(m, v, p):
            u = (m / c1) / (jnp.sqrt(v / c2) + eps)
            if weight_decay:
                u = u + weight_decay * p.astype(jnp.float32)
            if trust_ratio:
                pn = jnp.linalg.norm(p.astype(jnp.float32).reshape(-1))
                un = jnp.linalg.norm(u.reshape(-1))
                u = jnp.where((pn > 0) & (un > 0), pn / un, 1.0) * u
            return (-lr_t * u).astype(p.dtype)

        updates = jax.tree_util.tree_map(upd, mu, nu, params)
        return updates, _OnebitState(count=count, mu=mu, nu=nu)

    import optax
    return optax.GradientTransformation(init, update)


def onebit_adam(lr, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
                freeze_step=100, var_update_interval=0):
    return _onebit_base(lr, b1, b2, eps, weight_decay, freeze_step,
                        var_update_interval=var_update_interval)


def onebit_lamb(lr, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
                freeze_step=100):
    return _onebit_base(lr, b1, b2, eps, weight_decay, freeze_step,
                        trust_ratio=True)
