"""Flash attention (Pallas TPU) — forward + full backward.

TPU-native replacement for the capability class of the reference's fused
attention kernels (``csrc/transformer/`` softmax/attention fusions and the
training transformer block, SURVEY.md §2.6): online-softmax tiling keeps the
S×S score matrix out of HBM, so activation memory is O(S) and the matmuls
stay MXU-shaped (block_q × d, block_k × d tiles).

Layout: kernels operate on (batch, heads, seq, head_dim). The public wrapper
accepts BTHD (flax convention) or BHTD, pads sequence lengths to block
multiples (masked), and broadcasts GQA KV heads.

Backward follows the standard FlashAttention-2 recipe: forward additionally
emits logsumexp; dq is accumulated over KV blocks, dk/dv over Q blocks, with
delta = rowsum(dO * O) precomputed outside the kernels.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from ...utils.jax_compat import tpu_compiler_params as _compat_tpu_compiler_params

_NEG_INF = float("-inf")
_LANES = 128


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, sm_scale, causal, block_q, block_k, kv_len, causal_offset):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    run = True
    if causal:
        # skip blocks strictly above the (bottom-right-aligned) diagonal
        run = ki * block_k <= qi * block_q + (block_q - 1) + causal_offset

    @pl.when(run)
    def _compute():
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row + causal_offset >= col)
        _online_softmax_block(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                              mask, sm_scale)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        # fully-masked padded rows have l == 0; emit zeros, lse = -inf
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse carried as (..., tq, 1): a trailing unit lane dim keeps the
        # block shape Mosaic-tileable ((block_q, 1) is legal; (1, block_q)
        # as the last two dims of a 3-D block is not).
        lse_ref[0, 0] = (m_scr[:, :1] + jnp.log(l_safe))


# --------------------------------------------------------------------------- #
# block-sparse variant: a (h, nq, nk) int32 layout in SMEM (scalar prefetch)
# gates each grid step — masked blocks skip the MXU work entirely (the
# "splash"-style sparsity path used by ops/sparse_attention.py)
# --------------------------------------------------------------------------- #


def _online_softmax_block(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                          s_mask, sm_scale):
    """One flash block update (shared by the dense and sparse kernels):
    scores for the current (q, k) tile, ``s_mask`` applied, online-softmax
    accumulators advanced. Matmul operands stay in their storage dtype
    (bf16 runs the MXU at full rate) with fp32 accumulation."""
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    s = jnp.where(s_mask, s, _NEG_INF)
    m_prev = m_scr[:]
    l_prev = l_scr[:]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_next = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_next)
    p = jnp.exp(s - m_next[:, :1])
    l_scr[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    m_scr[:] = m_next
    v = v_ref[0, 0]
    pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    acc_scr[:] = acc_scr[:] * alpha[:, :1] + pv


def _fwd_sparse_kernel(mask_ref, fetch_ref, q_ref, k_ref, v_ref, o_ref,
                       m_scr, l_scr, acc_scr, *, sm_scale, block_q, block_k,
                       kv_len, nq, nk):
    hi = pl.program_id(1)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    del fetch_ref  # consumed by the k/v index maps

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    run = mask_ref[hi * nq * nk + qi * nk + ki] > 0

    @pl.when(run)
    def _compute():
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        _online_softmax_block(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                              col < kv_len, sm_scale)

    @pl.when(ki == pl.num_programs(3) - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _sparse_fetch_schedule(block_mask: np.ndarray) -> np.ndarray:
    """Per grid step, the KV block index to have resident: allowed steps
    fetch their own block; masked steps repeat the previous allowed index so
    the block revisit costs no DMA (the splash-attention fetch trick)."""
    bm = np.asarray(block_mask) > 0
    h, nq, nk = bm.shape
    fetch = np.zeros((h, nq, nk), np.int32)
    for hi in range(h):
        for qi in range(nq):
            cur = int(np.argmax(bm[hi, qi])) if bm[hi, qi].any() else 0
            for j in range(nk):
                if bm[hi, qi, j]:
                    cur = j
                fetch[hi, qi, j] = cur
    return fetch


def _fwd_sparse(q, k, v, block_mask, sm_scale, block_q, block_k, kv_len,
                interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    nq, nk = tq // block_q, tk // block_k
    kernel = functools.partial(
        _fwd_sparse_kernel, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, kv_len=kv_len, nq=nq, nk=nk)

    def kv_index(bb, hh, i, j, mask_ref, fetch_ref):
        del mask_ref
        return (bb, hh, fetch_ref[hh * nq * nk + i * nk + j], 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, h, i, j, *_: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b, h, i, j, *_: (b, h, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    fetch = _sparse_fetch_schedule(block_mask)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
        compiler_params=_compat_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(block_mask.reshape(-1).astype(np.int32), fetch.reshape(-1), q, k, v)


def flash_attention_sparse(q, k, v, block_mask, *, sm_scale=None,
                           block_q: int = 128, block_k: int = 128,
                           layout: str = "BTHD",
                           interpret: Optional[bool] = None):
    """Block-sparse flash attention (forward): ``block_mask`` is a
    (heads, ceil(T/block_q), ceil(T/block_k)) boolean/int layout — masked
    blocks are skipped on the MXU. Used by ops/sparse_attention.py when the
    layout sparsity pays for the kernel switch. Inference-oriented (no VJP);
    training paths use the masked XLA attention."""
    if interpret is None:
        from . import default_interpret
        interpret = default_interpret()
    if layout == "BTHD":
        q, k, v = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    elif layout != "BHTD":
        raise ValueError(f"unknown layout {layout!r}")
    b, h, tq, d = q.shape
    hk = k.shape[1]
    if hk != h:
        if h % hk:
            raise ValueError(f"GQA requires q_heads % kv_heads == 0 ({h}/{hk})")
        k = jnp.repeat(k, h // hk, axis=1)
        v = jnp.repeat(v, h // hk, axis=1)
    tk = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    block_q = min(block_q, _round_up(tq, _LANES))
    block_k = min(block_k, _round_up(tk, _LANES))
    tq_p, tk_p = _round_up(tq, block_q), _round_up(tk, block_k)
    if tq_p - tq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, tq_p - tq), (0, 0)))
    if tk_p - tk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, tk_p - tk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, tk_p - tk), (0, 0)))
    nq, nk = tq_p // block_q, tk_p // block_k
    try:
        # the layout is STATIC: it parameterizes the compiled grid (fetch
        # schedule is host-side) — a traced mask cannot work here
        bm = np.asarray(block_mask)
    except jax.errors.TracerArrayConversionError as e:
        raise ValueError(
            "flash_attention_sparse needs a static (host/numpy) block_mask; "
            "it determines the compiled fetch schedule and cannot be a "
            "traced value") from e
    if bm.shape != (h, nq, nk):
        raise ValueError(
            f"block_mask shape {bm.shape} != (heads={h}, nq={nq}, nk={nk}) "
            f"for block_q={block_q}, block_k={block_k}")
    o = _fwd_sparse(q, k, v, bm, float(sm_scale), block_q, block_k, tk,
                    interpret)
    o = o[:, :, :tq, :]
    if layout == "BTHD":
        o = jnp.swapaxes(o, 1, 2)
    return o


def _fwd(q, k, v, causal, sm_scale, block_q, block_k, kv_len, causal_offset,
         interpret, group=1):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    nq, nk = tq // block_q, tk // block_k
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=kv_len,
        causal_offset=causal_offset)
    grid = (b, h, nq, nk)
    out_shape = [
        jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
        jax.ShapeDtypeStruct((b, h, tq, 1), jnp.float32),
    ]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            # GQA: K/V stay (b, h//group, t, d); the index map broadcasts a
            # KV head across its q-head group — no materialized repeat
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h // group, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        out_shape=out_shape,
        compiler_params=_compat_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, sm_scale, causal, block_q, block_k, kv_len,
                   causal_offset):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, dq_scr.dtype)

    run = True
    if causal:
        run = ki * block_k <= qi * block_q + (block_q - 1) + causal_offset

    @pl.when(run)
    def _compute():
        # matmul operands stay in storage dtype (bf16 MXU) w/ f32 accumulation
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                                   # (bq, 1)
        delta = delta_ref[0, 0]                               # (bq, 1)

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row + causal_offset >= col)
        # padded q rows have lse == -inf; exp(s - lse) would be inf there
        mask = jnp.logical_and(mask, jnp.isfinite(lse))
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        dq_scr[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, sm_scale, causal, block_q, block_k, kv_len,
                    causal_offset, nq):
    # GQA grouped accumulation: the grid's innermost dim fuses (q-head in
    # group, q block) as gq = qh * nq + qi, so ONE kv head's dk/dv
    # accumulates over every q head it serves before the block is written
    # (init at the first step, finish at the last). group == 1 reduces to
    # the ungrouped order exactly.
    ki = pl.program_id(2)
    gq = pl.program_id(3)
    ng = pl.num_programs(3)
    qi = gq % nq

    @pl.when(gq == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, dk_scr.dtype)
        dv_scr[:] = jnp.zeros(dv_scr.shape, dv_scr.dtype)

    run = True
    if causal:
        run = qi * block_q + (block_q - 1) + causal_offset >= ki * block_k

    @pl.when(run)
    def _compute():
        # matmul operands stay in storage dtype (bf16 MXU) w/ f32 accumulation
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                                   # (bq, 1)
        delta = delta_ref[0, 0]                               # (bq, 1)

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row + causal_offset >= col)
        mask = jnp.logical_and(mask, jnp.isfinite(lse))
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)            # (bq, bk)
        dv_scr[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)    # (bq, bk)
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(gq == ng - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(causal, sm_scale, block_q, block_k, kv_len, causal_offset, interpret,
         res, g, dlse=None, group=1):
    q, k, v, o, lse = res
    do = g[0]
    b, h, tq, d = q.shape
    hk = k.shape[1]
    tk = k.shape[2]
    nq, nk = tq // block_q, tk // block_k

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                    # (b, h, tq, 1)
    if dlse is not None:
        # lse is a differentiable output here (ring attention combines
        # per-round partials by lse). Its cotangent folds into the FA-2
        # backward exactly: ds = p*(dp - delta) gains + p*dlse, i.e. the
        # same kernels run with delta' = delta - dlse.
        delta = delta - dlse.astype(jnp.float32)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, kv_len=kv_len,
                          causal_offset=causal_offset),
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b, h, i, j: (b, h, i, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=_compat_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: grid walks KV heads (hk = h // group); the innermost dim fuses
    # (q-head in group, q block) so each kv head's cotangent sums its whole
    # q-head group in-scratch — the index maps pick the q-side head as
    # hh * group + gq // nq and the q block as gq % nq.
    q_spec = pl.BlockSpec(
        (1, 1, block_q, d),
        lambda b, hh, i, gq: (b, hh * group + gq // nq, gq % nq, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d),
                           lambda b, hh, i, gq: (b, hh, i, 0))
    row_spec = pl.BlockSpec(
        (1, 1, block_q, 1),
        lambda b, hh, i, gq: (b, hh * group + gq // nq, gq % nq, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, kv_len=kv_len,
                          causal_offset=causal_offset, nq=nq),
        grid=(b, hk, nk, group * nq),
        in_specs=[
            q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, hh, i, gq: (b, hh, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, hh, i, gq: (b, hh, i, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        compiler_params=_compat_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, kv_len, causal_offset,
           interpret, group):
    o, _ = _fwd(q, k, v, causal, sm_scale, block_q, block_k, kv_len,
                causal_offset, interpret, group)
    return o


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, kv_len,
               causal_offset, interpret, group):
    o, lse = _fwd(q, k, v, causal, sm_scale, block_q, block_k, kv_len,
                  causal_offset, interpret, group)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, kv_len, causal_offset,
               interpret, group, res, g):
    return _bwd(causal, sm_scale, block_q, block_k, kv_len, causal_offset,
                interpret, res, (g,), group=group)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash_lse(q, k, v, causal, sm_scale, block_q, block_k, kv_len,
               causal_offset, interpret, group):
    """(o, lse) with lse a differentiable output (used by ring attention)."""
    return _fwd(q, k, v, causal, sm_scale, block_q, block_k, kv_len,
                causal_offset, interpret, group)


def _flash_lse_fwd(q, k, v, causal, sm_scale, block_q, block_k, kv_len,
                   causal_offset, interpret, group):
    o, lse = _fwd(q, k, v, causal, sm_scale, block_q, block_k, kv_len,
                  causal_offset, interpret, group)
    return (o, lse), (q, k, v, o, lse)


def _flash_lse_bwd(causal, sm_scale, block_q, block_k, kv_len, causal_offset,
                   interpret, group, res, cts):
    do, dlse = cts
    return _bwd(causal, sm_scale, block_q, block_k, kv_len, causal_offset,
                interpret, res, (do,), dlse=dlse, group=group)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


# ---------------------------------------------------------------------------
# public wrapper
# ---------------------------------------------------------------------------


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = True, sm_scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    layout: str = "BTHD",
                    interpret: Optional[bool] = None,
                    return_lse: bool = False):
    """Tiled online-softmax attention; differentiable (custom VJP).

    Args:
      q: (B, T, H, D) [layout="BTHD", flax convention] or (B, H, T, D).
      k, v: same layout; KV head count may divide H (GQA — heads broadcast).
      causal: lower-triangular mask.
      sm_scale: softmax scale, default 1/sqrt(D).
      block_q/block_k: tile sizes (clamped to the padded sequence). 512/512
        measured ~1.25x faster than XLA fused attention at T=512 and ~1.9x
        at T=2048 on v5e (fwd+bwd); 128/128 is ~2x SLOWER — small tiles
        leave the MXU idle between grid steps.
      interpret: run the Pallas interpreter (defaults to True off-TPU).
      return_lse: also return the per-row logsumexp (B, H, Tq) fp32 — itself
        differentiable, so callers (ring attention) can combine partials.
    """
    if interpret is None:
        from . import default_interpret
        interpret = default_interpret()
    if layout == "BTHD":
        q, k, v = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    elif layout != "BHTD":
        raise ValueError(f"unknown layout {layout!r}")

    b, h, tq, d = q.shape
    hk = k.shape[1]
    if hk != h:
        if h % hk:
            raise ValueError(f"GQA requires q_heads % kv_heads == 0 ({h}/{hk})")
    # GQA KV heads are broadcast inside the kernels via h -> h // group
    # BlockSpec index maps (dk/dv use a grouped accumulation grid), so K/V
    # are never materialized per q-head — hk-headed tiles stream straight
    # from HBM and the cotangents come back hk-headed.
    group = h // hk
    tk = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    block_q = min(block_q, _round_up(tq, _LANES))
    block_k = min(block_k, _round_up(tk, _LANES))
    tq_p, tk_p = _round_up(tq, block_q), _round_up(tk, block_k)
    pad_q, pad_k = tq_p - tq, tk_p - tk
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))

    # bottom-right-aligned causal diagonal (matches jnp.tril(..., k=tk-tq)
    # and jax.nn.dot_product_attention): decode-style tq < tk attends the
    # whole prefix.
    args = (q, k, v, causal, float(sm_scale), block_q, block_k, tk,
            tk - tq, interpret, group)
    if return_lse:
        o, lse = _flash_lse(*args)
        lse = lse[..., 0]                                  # (b, h, tq_p)
        if pad_q:
            lse = lse[:, :, :tq]
    else:
        o = _flash(*args)
    if pad_q:
        o = o[:, :, :tq, :]
    if layout == "BTHD":
        o = jnp.swapaxes(o, 1, 2)
    return (o, lse) if return_lse else o


def sharded_flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                            mesh, *, causal: bool = True,
                            sm_scale: Optional[float] = None,
                            layout: str = "BTHD",
                            block_q: int = 512, block_k: int = 512,
                            batch_axes=("data", "data_inner"),
                            head_axis: str = "model",
                            interpret: Optional[bool] = None) -> jnp.ndarray:
    """``flash_attention`` under ``shard_map``: batch over the data axes,
    heads over the model axis, full sequence local. This is the DP/ZeRO/TP
    wrapping (batch and heads are embarrassingly parallel for attention) —
    Pallas custom calls carry no GSPMD rules, so without this a multi-device
    jit would replicate q/k/v around the kernel. SP meshes go through
    ``parallel/ulysses.py`` / ``parallel/ring_attention.py`` instead, which
    use the kernel as their local attention.

    Falls back to fewer sharded dims when sizes don't divide. q/k/v are
    (B, T, H, D) for layout="BTHD" (flax convention) or (B, H, T, D).
    """
    from ...utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    if layout == "BTHD":
        b_dim, h_dim = 0, 2
    elif layout == "BHTD":
        b_dim, h_dim = 0, 1
    else:
        raise ValueError(f"unknown layout {layout!r}")

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    bat = tuple(a for a in batch_axes
                if sizes.get(a, 1) > 1 and q.shape[b_dim] % sizes[a] == 0)
    bsz = int(np.prod([sizes[a] for a in bat])) if bat else 1
    if bat and q.shape[b_dim] % bsz:
        bat = bat[:1]
        bsz = sizes[bat[0]]
    hd = (head_axis if head_axis and sizes.get(head_axis, 1) > 1
          and q.shape[h_dim] % sizes[head_axis] == 0
          and k.shape[h_dim] % sizes[head_axis] == 0 else None)

    spec = [None, None, None, None]
    spec[b_dim] = bat if bat else None
    spec[h_dim] = hd
    pspec = P(*spec)
    if pspec == P(None, None, None, None):
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               layout=layout, block_q=block_q,
                               block_k=block_k, interpret=interpret)

    def local(q_, k_, v_):
        return flash_attention(q_, k_, v_, causal=causal, sm_scale=sm_scale,
                               layout=layout, block_q=block_q,
                               block_k=block_k, interpret=interpret)

    return shard_map(local, mesh=mesh, in_specs=(pspec, pspec, pspec),
                     out_specs=pspec, check_vma=False)(q, k, v)


def attention_reference(q, k, v, *, causal=True, sm_scale=None,
                        layout="BTHD"):
    """Pure-jnp reference used by the kernel parity tests."""
    if layout == "BTHD":
        q, k, v = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    b, h, tq, d = q.shape
    hk = k.shape[1]
    if hk != h:
        k = jnp.repeat(k, h // hk, axis=1)
        v = jnp.repeat(v, h // hk, axis=1)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        tk = k.shape[2]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    o = o.astype(q.dtype)
    if layout == "BTHD":
        o = jnp.swapaxes(o, 1, 2)
    return o
