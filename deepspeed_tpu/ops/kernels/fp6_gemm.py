"""Fused FP6 (e3m2) weight-only GEMM — Pallas TPU.

Kernel answer to the reference's FP6 serving path
(``deepspeed/inference/v2/kernels/core_ops/cuda_linear/`` — ~2k LoC of
CUDA that dequantizes 6-bit minifloat weights inside the GEMM): weights
stream through HBM at REAL 6 bits/value (3 byte-planes per 4 codes) and
are decoded to the compute dtype tile-by-tile in VMEM, feeding the MXU —
decode-bound GEMV/GEMM reads 2.67x fewer weight bytes than bf16.

Storage layout (``fp6_gemm_pack``): a [K, N] weight becomes
  bytes3 [3, K, N/4] uint8 — byte planes of the 24-bit word packing the
      4 codes for true columns (j, j+N/4, j+N/2, j+3N/4);
  scale  [4, N/4] f32     — per-column scales, plane-major,
so the kernel's output tile [Mt, 4, Jt] reshapes to the true [M, N]
column order with no gather (row-major (p, j) == column p*N/4+j).

Serving-dtype entry: ``inference/quantization.py`` with
``num_bits: 6`` stores FPQuantizedTensor leaves (generic bit-packed
form); this kernel is the fused fast path for 2-D matmul weights.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from ...utils.jax_compat import tpu_compiler_params as _compat_tpu_compiler_params

_E, _M = 3, 2                      # e3m2
_BIAS = 2 ** (_E - 1) - 1          # 3
_MAX = 2.0 ** _BIAS * (2.0 - 2.0 ** (-_M))      # 14.0


class Fp6GemmWeight(NamedTuple):
    bytes3: jnp.ndarray            # [3, K, N/4] uint8
    scale: jnp.ndarray             # [4, N/4] f32
    shape: Tuple[int, int]         # (K, N)


jax.tree_util.register_pytree_node(
    Fp6GemmWeight,
    lambda t: ((t.bytes3, t.scale), (t.shape,)),
    lambda aux, ch: Fp6GemmWeight(*ch, *aux),
)


def fp6_gemm_pack(w: jnp.ndarray) -> Fp6GemmWeight:
    """Quantize a [K, N] weight (N % 4 == 0) to the GEMM layout with
    per-column scales."""
    from ..fp_quantizer import _minifloat_encode
    K, N = w.shape
    if N % 4:
        raise ValueError(f"N ({N}) must be divisible by 4")
    J = N // 4
    wf = w.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=0), 1e-12) / _MAX  # [N]
    codes = _minifloat_encode(wf / scale[None, :], _E, _M)  # [K, N] int16
    planes = [codes[:, p * J:(p + 1) * J].astype(jnp.uint32)
              for p in range(4)]
    word = (planes[0] | (planes[1] << 6) | (planes[2] << 12)
            | (planes[3] << 18))                            # [K, J]
    bytes3 = jnp.stack([word & 0xFF, (word >> 8) & 0xFF,
                        (word >> 16) & 0xFF]).astype(jnp.uint8)
    return Fp6GemmWeight(bytes3=bytes3,
                         scale=scale.reshape(4, J), shape=(K, N))


def _decode_plane(word, p):
    """fp6 e3m2 decode of plane ``p`` from 24-bit words (f32 out) — the
    shared minifloat decode (pure jnp, Pallas-safe), so the fused kernel
    can never diverge from fp_dequantize/fp6_gemm_unpack."""
    from ..fp_quantizer import _minifloat_decode
    return _minifloat_decode((word >> (6 * p)) & 0x3F, _E, _M)


def _fp6_kernel(x_ref, b_ref, s_ref, o_ref, a0, a1, a2, a3):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    accs = (a0, a1, a2, a3)

    @pl.when(ki == 0)
    def _init():
        for a in accs:
            a[:] = jnp.zeros(a.shape, a.dtype)

    b = b_ref[...].astype(jnp.int32)                 # [3, Kt, Jt]
    word = b[0] | (b[1] << 8) | (b[2] << 16)         # [Kt, Jt]
    x = x_ref[...]                                   # [Mt, Kt]
    for p in range(4):
        w = _decode_plane(word, p) * s_ref[p:p + 1, :]
        accs[p][:] = accs[p][:] + jax.lax.dot_general(
            x, w.astype(x.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        for p in range(4):
            o_ref[:, p, :] = accs[p][:].astype(o_ref.dtype)


def _pick_tile(dim: int, prefs=(512, 256, 128)) -> int:
    for t in prefs:
        if dim % t == 0:
            return t
    return 0


def fp6_matmul(x: jnp.ndarray, fw: Fp6GemmWeight,
               interpret=None) -> jnp.ndarray:
    """``x @ W`` with W stored fp6-packed. x: [..., K] in bf16/f32.
    Falls back to full dequant + XLA dot when K or N/4 has no
    MXU-aligned tile divisor."""
    if interpret is None:
        from . import default_interpret
        interpret = default_interpret()
    K, N = fw.shape
    J = N // 4
    lead = x.shape[:-1]
    x2 = x.reshape(-1, K)
    M = x2.shape[0]
    Kt, Jt = _pick_tile(K), _pick_tile(J)
    if not Kt or not Jt or M == 0:
        return (x @ fp6_gemm_unpack(fw).astype(x.dtype)).reshape(
            *lead, N)
    Mt = min(256, ((M + 7) // 8) * 8)
    M2 = ((M + Mt - 1) // Mt) * Mt
    if M2 != M:
        x2 = jnp.pad(x2, ((0, M2 - M), (0, 0)))

    out = pl.pallas_call(
        _fp6_kernel,
        grid=(M2 // Mt, J // Jt, K // Kt),
        in_specs=[
            pl.BlockSpec((Mt, Kt), lambda mi, ji, ki: (mi, ki)),
            pl.BlockSpec((3, Kt, Jt), lambda mi, ji, ki: (0, ki, ji)),
            pl.BlockSpec((4, Jt), lambda mi, ji, ki: (0, ji)),
        ],
        out_specs=pl.BlockSpec((Mt, 4, Jt),
                               lambda mi, ji, ki: (mi, 0, ji)),
        out_shape=jax.ShapeDtypeStruct((M2, 4, J), x.dtype),
        scratch_shapes=[pltpu.VMEM((Mt, Jt), jnp.float32)] * 4,
        compiler_params=_compat_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x2, fw.bytes3, fw.scale)
    # [M, 4, J] row-major == true column order p*J + j
    return out.reshape(M2, N)[:M].reshape(*lead, N)


def fp6_gemm_unpack(fw: Fp6GemmWeight) -> jnp.ndarray:
    """Full f32 decode of the GEMM layout (fallback / reference)."""
    b = fw.bytes3.astype(jnp.int32)
    word = b[0] | (b[1] << 8) | (b[2] << 16)         # [K, J]
    cols = [_decode_plane(word, p) * fw.scale[p][None, :]
            for p in range(4)]
    return jnp.concatenate(cols, axis=1)             # [K, N]
