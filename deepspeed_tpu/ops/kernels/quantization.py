"""Group quantization kernels — int8 / int4, symmetric / asymmetric.

Capability parity with the reference's ``csrc/quantization/`` family
(SURVEY.md §2.6): group-wise quantize/dequantize used by ZeRO++ (quantized
weights qwZ, quantized gradients qgZ), MoQ, and inference WOQ. A fused
``quant_dequant`` provides the fake-quant path (MoQ training, qgZ
dequant-reduce-requant emulation on the CPU mesh).

Layout: input is reshaped to (num_groups, group_size); per-group statistics
are computed in f32. int4 values are packed two-per-int8 (low nibble first).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


class QuantizedTensor(NamedTuple):
    """Packed group-quantized tensor. ``values`` is int8 (packed for 4-bit),
    ``scale``/``zero`` are (num_groups, 1) f32; ``shape``/``bits``/``group``
    record how to undo the packing.

    Registered as a pytree whose ``shape``/``bits``/``group_size`` are static
    aux data, so a QuantizedTensor can cross jit boundaries (qwZ holds
    quantized weights between steps) without the metadata becoming tracers.
    """
    values: jnp.ndarray
    scale: jnp.ndarray
    zero: Optional[jnp.ndarray]
    shape: Tuple[int, ...]
    bits: int
    group_size: int


jax.tree_util.register_pytree_node(
    QuantizedTensor,
    lambda qt: ((qt.values, qt.scale, qt.zero),
                (qt.shape, qt.bits, qt.group_size)),
    lambda aux, children: QuantizedTensor(*children, *aux),
)


# --------------------------------------------------------------------------- #
# row-wise comm-precision helpers (shared with the ZeRO++ quantized
# collectives, runtime/zero/quantized_collectives.py)
# --------------------------------------------------------------------------- #


def sym_quantize_rowwise(x: jnp.ndarray, bits: int):
    """Symmetric per-row (last-dim) quantization to int8 storage.
    Returns (q, scale) with scale shaped ``x.shape[:-1] + (1,)``."""
    qmax = float(2 ** (bits - 1) - 1)
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / qmax
    q = jnp.clip(jnp.round(xf / scale), -qmax, qmax).astype(jnp.int8)
    return q, scale


def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """Pack int4 values (in int8 storage) two-per-byte, low nibble first."""
    lo = q[..., 0::2] & 0x0F
    hi = (q[..., 1::2] & 0x0F) << 4
    return (lo | hi).astype(jnp.int8)


def unpack_int4(p: jnp.ndarray) -> jnp.ndarray:
    lo = (p << 4) >> 4                       # arithmetic shift sign-extends
    hi = p >> 4
    return jnp.stack([lo, hi], axis=-1).reshape(p.shape[:-1] + (-1,))


def _reshape_groups(x: jnp.ndarray, group_size: int) -> jnp.ndarray:
    flat = x.reshape(-1)
    n = flat.shape[0]
    if n % group_size:
        flat = jnp.pad(flat, (0, group_size - n % group_size))
    return flat.reshape(-1, group_size)


def _quant_kernel(x_ref, v_ref, s_ref, *, qmax):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / qmax
    s_ref[:] = scale
    v_ref[:] = jnp.clip(jnp.round(x / scale), -qmax, qmax).astype(jnp.int8)


def _quant_asym_kernel(x_ref, v_ref, s_ref, z_ref, *, qmax):
    x = x_ref[:].astype(jnp.float32)
    lo = jnp.min(x, axis=-1, keepdims=True)
    hi = jnp.max(x, axis=-1, keepdims=True)
    scale = jnp.maximum(hi - lo, 1e-12) / (2 * qmax)
    s_ref[:] = scale
    z_ref[:] = lo
    v_ref[:] = jnp.clip(jnp.round((x - lo) / scale) - qmax,
                        -qmax, qmax).astype(jnp.int8)


def quantize_blockwise(x: jnp.ndarray, *, bits: int = 8, group_size: int = 256,
                       symmetric: bool = True,
                       interpret: Optional[bool] = None) -> QuantizedTensor:
    """Group-quantize ``x`` to int8/int4 with per-group f32 scales."""
    assert bits in (8, 4), bits
    if bits == 4 and group_size % 2:
        raise ValueError(f"4-bit packing requires even group_size, "
                         f"got {group_size}")
    if interpret is None:
        from . import default_interpret
        interpret = default_interpret()
    groups = _reshape_groups(x, group_size)
    ng, gs = groups.shape
    qmax = float(2 ** (bits - 1) - 1)
    gb = min(256, ng)
    while ng % gb:
        gb //= 2
    gb = max(gb, 1)
    grid = (ng // gb,)
    row = pl.BlockSpec((gb, gs), lambda i: (i, 0))
    stat = pl.BlockSpec((gb, 1), lambda i: (i, 0))
    if symmetric:
        v, s = pl.pallas_call(
            functools.partial(_quant_kernel, qmax=qmax),
            grid=grid, in_specs=[row], out_specs=[row, stat],
            out_shape=[jax.ShapeDtypeStruct((ng, gs), jnp.int8),
                       jax.ShapeDtypeStruct((ng, 1), jnp.float32)],
            interpret=interpret,
        )(groups)
        z = None
    else:
        v, s, z = pl.pallas_call(
            functools.partial(_quant_asym_kernel, qmax=qmax),
            grid=grid, in_specs=[row], out_specs=[row, stat, stat],
            out_shape=[jax.ShapeDtypeStruct((ng, gs), jnp.int8),
                       jax.ShapeDtypeStruct((ng, 1), jnp.float32),
                       jax.ShapeDtypeStruct((ng, 1), jnp.float32)],
            interpret=interpret,
        )(groups)
    if bits == 4:
        # pack adjacent pairs: low nibble = even index, high nibble = odd
        lo = v[:, 0::2].astype(jnp.int32) & 0xF
        hi = v[:, 1::2].astype(jnp.int32) & 0xF
        v = (lo | (hi << 4)).astype(jnp.int8)
    return QuantizedTensor(v, s, z, tuple(x.shape), bits, group_size)


def dequantize_blockwise(qt: QuantizedTensor,
                         dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of :func:`quantize_blockwise` (jnp; XLA fuses the unpack)."""
    v = qt.values
    if qt.bits == 4:
        raw = v.astype(jnp.int32) & 0xFF
        lo = (raw & 0xF).astype(jnp.int8)
        hi = ((raw >> 4) & 0xF).astype(jnp.int8)
        # sign-extend 4-bit two's complement
        lo = jnp.where(lo > 7, lo - 16, lo)
        hi = jnp.where(hi > 7, hi - 16, hi)
        v = jnp.stack([lo, hi], axis=-1).reshape(v.shape[0], -1)
    x = v.astype(jnp.float32) * qt.scale
    if qt.zero is not None:
        qmax = float(2 ** (qt.bits - 1) - 1)
        x = x + qt.zero + qmax * qt.scale
    n = 1
    for d in qt.shape:
        n *= d
    return x.reshape(-1)[:n].reshape(qt.shape).astype(dtype)


def quant_dequant(x: jnp.ndarray, *, bits: int = 8, group_size: int = 256,
                  symmetric: bool = True,
                  interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fake-quant round trip (straight-through in callers that need grads)."""
    qt = quantize_blockwise(x, bits=bits, group_size=group_size,
                            symmetric=symmetric, interpret=interpret)
    return dequantize_blockwise(qt, dtype=x.dtype)
