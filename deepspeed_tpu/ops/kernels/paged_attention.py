"""Paged-KV flash attention (Pallas TPU) — the FastGen decode hot loop.

TPU-native analogue of the reference's blocked flash decode
(``inference/v2/kernels/ragged_ops/blocked_flash/``, wired at
``inference/v2/model_implementations/inference_transformer_base.py``): flash
attention reads K/V DIRECTLY through per-sequence block tables, so each step
touches only the blocks a sequence actually occupies. The block tables ride
scalar prefetch (their values drive the K/V BlockSpec index maps), and dead
grid steps (past a sequence's live block count) repeat the previous block
index — a revisited block costs no DMA (same trick as the splash-style
sparse kernel in flash_attention.py). Replaces the dense
``[max_seqs, max_context]`` gather-then-mask attention, whose per-step HBM
traffic scaled with ``max_context`` regardless of actual lengths.

Layout contract (matches BlockedKVCache): the flat KV pool
``[slots, KV_heads, D]`` has ``slots = (num_blocks + 1) * block_size`` — the
final block is the trash block (padded query positions scatter there), so
``pool.reshape(num_blocks + 1, block_size, KV, D)`` is a free reshape, never
a copy. Block tables only ever reference blocks < num_blocks.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")
_LANES = 128


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _paged_kernel(starts_ref, fetch_ref, nlive_ref, lo_ref, slopes_ref,
                  q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                  *, bs, C, H, KV, D, sm_scale, use_alibi, window):
    s = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)
    HC = H * C
    g = H // KV

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    @pl.when(jnp.logical_and(j >= lo_ref[s], j < nlive_ref[s]))
    def _compute():
        q = q_ref[0]                                   # [C, H, D]
        kb = k_ref[0]                                  # [bs, KV, D]
        vb = v_ref[0]
        # per-chunk-position query positions and this block's column range
        pos_q = starts_ref[s] + jax.lax.broadcasted_iota(
            jnp.int32, (C, bs), 0)                     # [C, bs]
        col = j * bs + jax.lax.broadcasted_iota(jnp.int32, (C, bs), 1)
        causal = col <= pos_q
        if window is not None:                         # mistral sliding window
            causal = jnp.logical_and(causal, col > pos_q - window)
        dist = (pos_q - col).astype(jnp.float32)

        # rows are head-major: scores row h*C + c <-> (head h, chunk pos c)
        parts = []
        for h in range(H):
            qh = q[:, h, :]                            # [C, D]
            kh = kb[:, h // g, :]                      # [bs, D]
            sc = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            if use_alibi:
                sc = sc - slopes_ref[h] * dist         # static-index SMEM read
            parts.append(jnp.where(causal, sc, _NEG_INF))
        scores = jnp.concatenate(parts, axis=0)        # [HC, bs] f32

        m_prev, l_prev = m_scr[:], l_scr[:]
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        # a row can be fully masked in its first processed block (sliding
        # window): m_next stays -inf there, and exp(-inf - -inf) would be
        # nan — clamp through a finite stand-in (p comes out 0 either way)
        m_safe = jnp.where(jnp.isfinite(m_next), m_next, 0.0)
        alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        p = jnp.exp(jnp.where(jnp.isfinite(scores),
                              scores - m_safe[:, :1], _NEG_INF))
        l_scr[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_next
        pv_parts = []
        for h in range(H):
            ph = p[h * C:(h + 1) * C, :].astype(vb.dtype)    # [C, bs]
            pv_parts.append(jax.lax.dot_general(
                ph, vb[:, h // g, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        acc_scr[:] = acc_scr[:] * alpha[:, :1] + jnp.concatenate(pv_parts, 0)

    @pl.when(j == nb - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)           # idle slots emit zeros
        o = acc_scr[:] / l_safe                        # [HC, D]
        o_ref[0] = o.reshape(H, C, D).swapaxes(0, 1).astype(o_ref.dtype)


def flash_paged_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                          v_pool: jnp.ndarray, block_tables: jnp.ndarray,
                          start_pos: jnp.ndarray, seq_lens: jnp.ndarray,
                          *, block_size: int,
                          sm_scale: Optional[float] = None,
                          alibi_slopes: Optional[jnp.ndarray] = None,
                          sliding_window: Optional[int] = None,
                          interpret: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention over paged KV.

    Args:
      q: [S, C, H, D] — C query tokens per slot (1 for pure decode;
        SplitFuse prefill chunks are larger). The step's K/V must ALREADY be
        scattered into the pool (causal masking handles the chunk interior).
      k_pool/v_pool: [slots, KV, D] with slots = (num_blocks+1)*block_size
        (trailing trash block).
      block_tables: [S, MAXB] int32 — pool block id per sequence block.
      start_pos: [S] int32 — absolute position of q[s, 0].
      seq_lens: [S] int32 — total live context length (incl. this chunk);
        0 marks an idle slot (emits zeros).
      alibi_slopes: optional [H] f32 — in-kernel ALiBi bias (falcon/bloom).

    Returns [S, C, H, D] attention outputs in q.dtype. HBM traffic per step
    is O(sum of live blocks), not O(S * max_context).
    """
    if interpret is None:
        from . import default_interpret
        interpret = default_interpret()
    S, C, H, D = q.shape
    slots, KV, Dk = k_pool.shape
    bs = block_size
    if Dk != D:
        raise ValueError(f"head_dim mismatch q={D} pool={Dk}")
    if H % KV:
        raise ValueError(f"GQA requires H % KV == 0 ({H}/{KV})")
    if slots % bs:
        raise ValueError(
            f"pool slots ({slots}) must be a multiple of block_size ({bs}); "
            f"allocate (num_blocks+1)*block_size with a trailing trash block")
    nb_pool = slots // bs
    maxb = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)

    kp = k_pool.reshape(nb_pool, bs, KV, D)
    vp = v_pool.reshape(nb_pool, bs, KV, D)

    nlive = jnp.minimum((seq_lens + bs - 1) // bs, maxb).astype(jnp.int32)
    # sliding window: blocks entirely below every query's window are dead too
    if sliding_window is not None:
        lo = jnp.maximum(start_pos - sliding_window + 1, 0) // bs
        lo = jnp.minimum(lo.astype(jnp.int32), jnp.maximum(nlive - 1, 0))
    else:
        lo = jnp.zeros_like(nlive)
    # dead steps re-fetch a live block: no new DMA
    jj = jnp.arange(maxb, dtype=jnp.int32)[None, :]
    fetch = jnp.take_along_axis(
        block_tables.astype(jnp.int32),
        jnp.clip(jj, lo[:, None], jnp.maximum(nlive[:, None] - 1, 0)), axis=1)

    use_alibi = alibi_slopes is not None
    slopes = (jnp.asarray(alibi_slopes, jnp.float32) if use_alibi
              else jnp.zeros((H,), jnp.float32))

    HC = H * C
    kernel = functools.partial(
        _paged_kernel, bs=bs, C=C, H=H, KV=KV, D=D, sm_scale=float(sm_scale),
        use_alibi=use_alibi,
        window=int(sliding_window) if sliding_window is not None else None)

    def kv_index(s, j, starts_ref, fetch_ref, nlive_ref, lo_ref, slopes_ref):
        del starts_ref, nlive_ref, lo_ref, slopes_ref
        return (fetch_ref[s * maxb + j], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(S, maxb),
        in_specs=[
            pl.BlockSpec((1, C, H, D), lambda s, j, *_: (s, 0, 0, 0)),
            pl.BlockSpec((1, bs, KV, D), kv_index),
            pl.BlockSpec((1, bs, KV, D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, C, H, D), lambda s, j, *_: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((HC, _LANES), jnp.float32),
            pltpu.VMEM((HC, _LANES), jnp.float32),
            pltpu.VMEM((HC, D), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, C, H, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(start_pos.astype(jnp.int32), fetch.reshape(-1),
      nlive, lo, slopes, q, kp, vp)
