"""Paged-KV flash attention (Pallas TPU) — the FastGen decode hot loop.

TPU-native analogue of the reference's blocked flash decode
(``inference/v2/kernels/ragged_ops/blocked_flash/``, wired at
``inference/v2/model_implementations/inference_transformer_base.py``): flash
attention reads K/V DIRECTLY through per-sequence block tables, so each step
touches only the blocks a sequence actually occupies. The block tables ride
scalar prefetch (their values drive the K/V BlockSpec index maps), and dead
grid steps (past a sequence's live block count) repeat the previous block
index — a revisited block costs no DMA (same trick as the splash-style
sparse kernel in flash_attention.py). Replaces the dense
``[max_seqs, max_context]`` gather-then-mask attention, whose per-step HBM
traffic scaled with ``max_context`` regardless of actual lengths.

Layout contract (matches BlockedKVCache): the flat KV pool
``[slots, KV_heads, D]`` has ``slots = (num_blocks + 1) * block_size`` — the
final block is the trash block (padded query positions scatter there), so
``pool.reshape(num_blocks + 1, block_size, KV, D)`` is a free reshape, never
a copy. Block tables only ever reference blocks < num_blocks.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")
_LANES = 128


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _paged_kernel(starts_ref, fetch_ref, lo_ref, hi_ref, slopes_ref,
                  q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                  *, bs, Cb, nCb, H, KV, D, sm_scale, use_alibi, window):
    s = pl.program_id(0)
    qc = pl.program_id(1)
    j = pl.program_id(2)
    nb = pl.num_programs(2)
    sq = s * nCb + qc
    g = H // KV

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    @pl.when(jnp.logical_and(j >= lo_ref[sq], j < hi_ref[sq]))
    def _compute():
        q = q_ref[0]                                   # [Cb, H, D]
        kb = k_ref[0]                                  # [bs, KV, D]
        vb = v_ref[0]
        # per-row query positions at the head-group row layout [g*Cb, bs]:
        # row r <-> (head i = r // Cb, tile pos c = r % Cb) — built directly
        # at full width (Mosaic cannot concatenate i1 mask vregs)
        c_of_row = jax.lax.rem(
            jax.lax.broadcasted_iota(jnp.int32, (g * Cb, bs), 0), Cb)
        pos_q = starts_ref[s] + qc * Cb + c_of_row     # [gCb, bs]
        col = j * bs + jax.lax.broadcasted_iota(jnp.int32, (g * Cb, bs), 1)
        causal = col <= pos_q
        if window is not None:                         # mistral sliding window
            causal = jnp.logical_and(causal, col > pos_q - window)
        dist = (pos_q - col).astype(jnp.float32)

        # rows are head-major: scores row h*Cb + c <-> (head h, tile pos c).
        # Heads are batched per KV group — one [g*Cb, D] x [D, bs] matmul
        # per kv head instead of H separate [Cb, D] ones (at decode Cb=1
        # the per-head variant fed the MXU single-row operands)
        parts = []
        for kvh in range(KV):
            qg = q[:, kvh * g:(kvh + 1) * g, :]        # [Cb, g, D]
            qg = qg.swapaxes(0, 1).reshape(g * Cb, D)  # rows (i*Cb + c)
            kh = kb[:, kvh, :]                         # [bs, D]
            sc = jax.lax.dot_general(
                qg, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale  # [gCb, bs]
            if use_alibi:
                # static SMEM reads per head; rows i*Cb..(i+1)*Cb share one
                slope_rows = jnp.concatenate(
                    [jnp.full((Cb, 1), slopes_ref[kvh * g + i], jnp.float32)
                     for i in range(g)], axis=0)       # [gCb, 1]
                sc = sc - slope_rows * dist
            parts.append(jnp.where(causal, sc, _NEG_INF))
        scores = jnp.concatenate(parts, axis=0)        # [H*Cb, bs] f32

        m_prev, l_prev = m_scr[:], l_scr[:]
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        # a row can be fully masked in its first processed block (sliding
        # window): m_next stays -inf there, and exp(-inf - -inf) would be
        # nan — clamp through a finite stand-in (p comes out 0 either way)
        m_safe = jnp.where(jnp.isfinite(m_next), m_next, 0.0)
        alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        p = jnp.exp(jnp.where(jnp.isfinite(scores),
                              scores - m_safe[:, :1], _NEG_INF))
        l_scr[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_next
        pv_parts = []
        for kvh in range(KV):
            pg = p[kvh * g * Cb:(kvh + 1) * g * Cb, :].astype(vb.dtype)
            pv_parts.append(jax.lax.dot_general(
                pg, vb[:, kvh, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))   # [gCb, D]
        acc_scr[:] = acc_scr[:] * alpha[:, :1] + jnp.concatenate(pv_parts, 0)

    @pl.when(j == nb - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)           # idle slots emit zeros
        o = acc_scr[:] / l_safe                        # [H*Cb, D]
        o_ref[0] = o.reshape(H, Cb, D).swapaxes(0, 1).astype(o_ref.dtype)


def flash_paged_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                          v_pool: jnp.ndarray, block_tables: jnp.ndarray,
                          start_pos: jnp.ndarray, seq_lens: jnp.ndarray,
                          *, block_size: int,
                          sm_scale: Optional[float] = None,
                          alibi_slopes: Optional[jnp.ndarray] = None,
                          sliding_window: Optional[int] = None,
                          interpret: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention over paged KV.

    Args:
      q: [S, C, H, D] — C query tokens per slot (1 for pure decode;
        SplitFuse prefill chunks are larger). The step's K/V must ALREADY be
        scattered into the pool (causal masking handles the chunk interior).
      k_pool/v_pool: [slots, KV, D] with slots = (num_blocks+1)*block_size
        (trailing trash block).
      block_tables: [S, MAXB] int32 — pool block id per sequence block.
      start_pos: [S] int32 — absolute position of q[s, 0].
      seq_lens: [S] int32 — total live context length (incl. this chunk);
        0 marks an idle slot (emits zeros).
      alibi_slopes: optional [H] f32 — in-kernel ALiBi bias (falcon/bloom).

    Returns [S, C, H, D] attention outputs in q.dtype. HBM traffic per step
    is O(sum of live blocks), not O(S * max_context).
    """
    if interpret is None:
        from . import default_interpret
        interpret = default_interpret()
    S, C, H, D = q.shape
    slots, KV, Dk = k_pool.shape
    bs = block_size
    if Dk != D:
        raise ValueError(f"head_dim mismatch q={D} pool={Dk}")
    if H % KV:
        raise ValueError(f"GQA requires H % KV == 0 ({H}/{KV})")
    if slots % bs:
        raise ValueError(
            f"pool slots ({slots}) must be a multiple of block_size ({bs}); "
            f"allocate (num_blocks+1)*block_size with a trailing trash block")
    nb_pool = slots // bs
    maxb = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)

    kp = k_pool.reshape(nb_pool, bs, KV, D)
    vp = v_pool.reshape(nb_pool, bs, KV, D)

    # query-chunk tiling: scratch rows are H*Cb, so bound Cb to keep the
    # online-softmax state (m/l at 128 lanes + f32 acc) well under VMEM —
    # prefill chunks (C up to 512+) previously sized scratch at H*C and
    # blew the 16 MB budget on real chips
    Cb = min(C, max(8, 4096 // H))
    nCb = -(-C // Cb)

    nlive = jnp.minimum((seq_lens + bs - 1) // bs, maxb).astype(jnp.int32)
    qcs = jnp.arange(nCb, dtype=jnp.int32)[None, :]         # [1, nCb]
    # per-(seq, q-chunk) live range: blocks past the chunk's last query
    # position are dead by causality (big win for early prefill chunks)
    chunk_end = start_pos[:, None] + (qcs + 1) * Cb         # exclusive
    hi = jnp.minimum(nlive[:, None], (chunk_end - 1) // bs + 1)
    hi = jnp.maximum(hi, 0).astype(jnp.int32)               # [S, nCb]
    # sliding window: blocks entirely below every query's window are dead
    if sliding_window is not None:
        first_q = start_pos[:, None] + qcs * Cb
        lo = jnp.maximum(first_q - sliding_window + 1, 0) // bs
        lo = jnp.minimum(lo.astype(jnp.int32), jnp.maximum(hi - 1, 0))
    else:
        lo = jnp.zeros_like(hi)
    # dead steps re-fetch a live block: no new DMA
    jj = jnp.arange(maxb, dtype=jnp.int32)[None, :]
    fetch = jnp.take_along_axis(
        block_tables.astype(jnp.int32),
        jnp.clip(jj, 0, jnp.maximum(nlive[:, None] - 1, 0)), axis=1)

    use_alibi = alibi_slopes is not None
    slopes = (jnp.asarray(alibi_slopes, jnp.float32) if use_alibi
              else jnp.zeros((H,), jnp.float32))

    kernel = functools.partial(
        _paged_kernel, bs=bs, Cb=Cb, nCb=nCb, H=H, KV=KV, D=D,
        sm_scale=float(sm_scale), use_alibi=use_alibi,
        window=int(sliding_window) if sliding_window is not None else None)

    def kv_index(s, qc, j, starts_ref, fetch_ref, lo_ref, hi_ref, slopes_ref):
        del starts_ref, slopes_ref
        # clamp into this (s, qc)'s live range so dead grid steps revisit a
        # fetched block (no DMA) instead of pulling a new one
        sq = s * nCb + qc
        jc = jnp.clip(j, lo_ref[sq], jnp.maximum(hi_ref[sq] - 1, 0))
        return (fetch_ref[s * maxb + jc], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(S, nCb, maxb),
        in_specs=[
            pl.BlockSpec((1, Cb, H, D), lambda s, qc, j, *_: (s, qc, 0, 0)),
            pl.BlockSpec((1, bs, KV, D), kv_index),
            pl.BlockSpec((1, bs, KV, D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, Cb, H, D),
                               lambda s, qc, j, *_: (s, qc, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H * Cb, _LANES), jnp.float32),
            pltpu.VMEM((H * Cb, _LANES), jnp.float32),
            pltpu.VMEM((H * Cb, D), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, C, H, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(start_pos.astype(jnp.int32), fetch.reshape(-1),
      lo.reshape(-1), hi.reshape(-1), slopes, q, kp, vp)
