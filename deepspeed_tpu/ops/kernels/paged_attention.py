"""Paged-KV flash attention (Pallas TPU) — the FastGen decode hot loop.

TPU-native analogue of the reference's blocked flash decode
(``inference/v2/kernels/ragged_ops/blocked_flash/``, wired at
``inference/v2/model_implementations/inference_transformer_base.py``): flash
attention reads K/V DIRECTLY through per-sequence block tables, so each step
touches only the blocks a sequence actually occupies. The block tables ride
scalar prefetch (their values drive the K/V BlockSpec index maps), and dead
grid steps (past a sequence's live block count) repeat the previous block
index — a revisited block costs no DMA.

Layout contract (matches BlockedKVCache): the flat KV pool is
``[slots, KV_heads * D]`` with ``slots = (num_blocks + 1) * block_size`` —
one LANE-ALIGNED row per token. The earlier ``[slots, KV, D]`` layout let
XLA pad the trailing ``(4, 64)`` dims to the (8, 128) tile — 4x the HBM
footprint AND 4x the DMA traffic on the serving hot path. A 3-D pool is
still accepted and viewed flat (same bytes, contiguous reshape).

GQA is handled by LANE WINDOWING instead of a per-kv-head matmul unroll:
the caller expands q so the row for head h carries its values in lane
window ``(h // group) * D .. + D`` and zeros elsewhere; one
``[H*Cb, KV*D] x [KV*D, width]`` matmul then yields every head's scores
(cross-head lanes contract against zeros), and the P*V product emits
``[H*Cb, KV*D]`` rows from which the caller slices each head's window.
This keeps the MXU on one large operand per grid step — at decode the old
per-head unroll fed it [1, 64] slivers.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from ...utils.jax_compat import tpu_compiler_params as _compat_tpu_compiler_params

_NEG_INF = float("-inf")
_LANES = 128


def _paged_kernel(starts_ref, fetch_ref, lo_ref, hi_ref, slopes_ref, *rest,
                  bs, Cb, nCb, H, KV, D, sm_scale, use_alibi, window, R,
                  windowed, quant=False):
    if R is None:
        if quant:
            (q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_scr, l_scr,
             acc_scr) = rest
        else:
            q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = rest
            ks_ref = vs_ref = None
        rcount_ref = lens_ref = rk_ref = rv_ref = None
    else:
        if quant:
            (rcount_ref, lens_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
             rk_ref, rv_ref, o_ref, m_scr, l_scr, acc_scr) = rest
        else:
            (rcount_ref, lens_ref, q_ref, k_ref, v_ref, rk_ref, rv_ref,
             o_ref, m_scr, l_scr, acc_scr) = rest
            ks_ref = vs_ref = None
    s = pl.program_id(0)
    qc = pl.program_id(1)
    j = pl.program_id(2)
    nb = pl.num_programs(2)
    sq = s * nCb + qc
    g = H // KV

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    def _attend(kb, vb, width, mask, dist, ks=None, vs=None):
        """One online-softmax round over ``width`` columns. kb/vb are
        [width, KV*D] token rows; mask/dist are [H*Cb, width]. Rows are
        head-major (row h*Cb + c <-> head h, tile pos c).

        windowed (decode, Cb==1): q rows are lane-windowed per head
        (module docstring) and ONE [H, KV*D] x [KV*D, width] matmul covers
        every head — at Cb=1 per-head operands would be single-row MXU
        slivers. grouped (prefill): per-kv-head [g*Cb, D] matmuls against
        64-lane slices of the flat rows — no zero-lane FLOP inflation
        (windowing would cost KV x the useful MACs, ruinous for MHA).

        ks/vs ([KV, width], int8 pool only): per-(token, kv-head) dequant
        scales — K scales multiply score columns, V scales multiply
        probability columns (exact; constant along the contracted D axis).
        The ring round passes None (the ring is never quantized)."""
        q = q_ref[0]                  # [H*Cb, KV*D] windowed / [H*Cb, D]
        if quant and kb.dtype == jnp.int8:
            kb = kb.astype(q.dtype)
        g = H // KV

        def _exp_rows(s):
            """[KV, width] -> [H*Cb, width] head-major row expansion."""
            return jnp.broadcast_to(
                s[:, None, :], (KV, g * Cb, width)).reshape(H * Cb, width)

        if use_alibi:
            slope_rows = jnp.concatenate(
                [jnp.full((Cb, 1), slopes_ref[h], jnp.float32)
                 for h in range(H)], axis=0)           # [HCb, 1]
        if windowed:
            sc = jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            if ks is not None:
                sc = sc * _exp_rows(ks)
            if use_alibi:
                sc = sc - slope_rows * dist
            scores = jnp.where(mask, sc, _NEG_INF)     # [HCb, width]
        else:
            parts = []
            for kvh in range(KV):
                rows = slice(kvh * g * Cb, (kvh + 1) * g * Cb)
                kh = kb[:, kvh * D:(kvh + 1) * D]      # [width, D]
                sc = jax.lax.dot_general(
                    q[rows], kh, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * sm_scale
                if ks is not None:
                    sc = sc * ks[kvh:kvh + 1, :]
                if use_alibi:
                    sc = sc - slope_rows[rows] * dist[rows]
                parts.append(jnp.where(mask[rows], sc, _NEG_INF))
            scores = jnp.concatenate(parts, axis=0)    # [HCb, width]

        m_prev, l_prev = m_scr[:], l_scr[:]
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        # a row can be fully masked in its first processed block (sliding
        # window): m_next stays -inf there, and exp(-inf - -inf) would be
        # nan — clamp through a finite stand-in (p comes out 0 either way)
        m_safe = jnp.where(jnp.isfinite(m_next), m_next, 0.0)
        alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        p = jnp.exp(jnp.where(jnp.isfinite(scores),
                              scores - m_safe[:, :1], _NEG_INF))
        l_scr[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_next
        if quant and vb.dtype == jnp.int8:
            vb = vb.astype(q.dtype)
        if vs is not None:
            p = p * _exp_rows(vs)
        if windowed:
            pv = jax.lax.dot_general(
                p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)    # [HCb, KV*D]
        else:
            pv = jnp.concatenate([
                jax.lax.dot_general(
                    p[kvh * g * Cb:(kvh + 1) * g * Cb].astype(vb.dtype),
                    vb[:, kvh * D:(kvh + 1) * D], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                for kvh in range(KV)], axis=0)         # [HCb, D]
        acc_scr[:] = acc_scr[:] * alpha[:, :1] + pv

    @pl.when(jnp.logical_and(j >= lo_ref[sq], j < hi_ref[sq]))
    def _compute():
        # per-row query positions at the head-major row layout [H*Cb, bs]:
        # row r <-> (head r // Cb, tile pos r % Cb) — built directly at
        # full width (Mosaic cannot concatenate i1 mask vregs)
        c_of_row = jax.lax.rem(
            jax.lax.broadcasted_iota(jnp.int32, (H * Cb, bs), 0), Cb)
        pos_q = starts_ref[s] + qc * Cb + c_of_row     # [HCb, bs]
        col = j * bs + jax.lax.broadcasted_iota(jnp.int32, (H * Cb, bs), 1)
        causal = col <= pos_q
        if R is not None:
            # ring mode: the pool only holds SETTLED rows; positions
            # lens..pos_q live in the ring, and the pool rows there are
            # stale — mask them out column-exactly (hi is block-granular)
            causal = jnp.logical_and(causal, col < lens_ref[s])
        if window is not None:                         # mistral sliding window
            causal = jnp.logical_and(causal, col > pos_q - window)
        _attend(k_ref[0], v_ref[0], bs, causal,
                (pos_q - col).astype(jnp.float32),
                ks=ks_ref[0] if quant else None,
                vs=vs_ref[0] if quant else None)

    if R is not None:
        # decode-loop ring round: this step's (and the loop's prior) K/V
        # live in a small per-sequence ring buffer that is only flushed
        # into the pool after the fused loop — ring row r holds the token
        # at absolute position (start_pos - (rcount-1) + r)
        @pl.when(j == nb - 1)
        def _ring():
            r = jax.lax.broadcasted_iota(jnp.int32, (H * Cb, R), 1)
            dist = (rcount_ref[0] - 1 - r).astype(jnp.float32)
            # lens gate keeps idle slots (seq_lens == 0) fully masked so
            # they emit zeros — their ring rows hold garbage K/V
            mask = jnp.logical_and(r < rcount_ref[0], lens_ref[s] > 0)
            if window is not None:
                mask = jnp.logical_and(mask, dist < window)
            _attend(rk_ref[0], rv_ref[0], R, mask, dist)

    @pl.when(j == nb - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)           # idle slots emit zeros
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_grouped_kernel(starts_ref, fetch_ref, lens_ref, rcount_ref,
                           contig_ref, layer_ref, slopes_ref, q_ref,
                           kp_hbm, vp_hbm, rk_ref, rv_ref, *rest, G, bs,
                           ts, H, KV, D, sm_scale, use_alibi, window, R,
                           ring5d, use_pool_full, quant, sc_full):
    """Grouped decode: G sequences per grid step (VERDICT r3 #4 decode
    roofline work). The BlockSpec path pays one grid step per (sequence,
    layer) — at S=256 x 22 layers that is ~11k grid steps per decode step,
    and the fixed cost per step IS the decode wall. Here each grid step
    copies G sequences' whole contexts (linear layout: one contiguous
    block each) into VMEM and computes G full softmaxes. When the G blocks
    are CONSECUTIVE in the pool (the common serving steady state —
    sequences admitted in order), ONE [G*bs]-row DMA replaces the G
    per-sequence copies: the per-DMA issue cost, not the bytes, dominates
    at these sizes. ``contig_ref[i]`` carries the host-side run check."""
    if quant:
        (sck_hbm, scv_hbm, o_ref, k_scr, v_scr, ks_scr, vs_scr, sems,
         ssem) = rest
    else:
        o_ref, k_scr, v_scr, sems = rest
        sck_hbm = scv_hbm = ks_scr = vs_scr = ssem = None
    i = pl.program_id(0)
    KVD = KV * D

    if not use_pool_full:
        def k_src(off, n):
            return kp_hbm.at[pl.ds(off, n)]

        def v_src(off, n):
            return vp_hbm.at[pl.ds(off, n)]
    else:
        # the WHOLE [L, 2, slots, KVD] pool rides into the kernel and the
        # layer index lands here, in the DMA source — slicing pool[li, 0/1]
        # at the model level materialized a full per-layer pool copy for
        # the Pallas operand (the device trace measured those copies at
        # ~45 % of the decode step). The layer arrives via SCALAR PREFETCH
        # (layer_ref), not as a Python constant: all layers then share ONE
        # Mosaic binary instead of compiling L structurally-identical
        # kernels.
        def k_src(off, n):
            return kp_hbm.at[layer_ref[0], 0, pl.ds(off, n)]

        def v_src(off, n):
            return kp_hbm.at[layer_ref[0], 1, pl.ds(off, n)]

    if quant:
        # int8 pool: the [KV, rows] scale windows ride separate (tiny, ~3%)
        # DMAs; dequantization happens on scores/probabilities, never on
        # the K/V tiles (kv_quant.py design)
        if sc_full:
            def ks_src(off, n):
                return sck_hbm.at[layer_ref[0], 0, :, pl.ds(off, n)]

            def vs_src(off, n):
                return scv_hbm.at[layer_ref[0], 1, :, pl.ds(off, n)]
        else:
            def ks_src(off, n):
                return sck_hbm.at[:, pl.ds(off, n)]

            def vs_src(off, n):
                return scv_hbm.at[:, pl.ds(off, n)]

    @pl.when(contig_ref[i] == 1)
    def _copy_contig():
        off = fetch_ref[i * G] * bs
        pltpu.make_async_copy(k_src(off, G * bs), k_scr, sems.at[0]).start()
        pltpu.make_async_copy(v_src(off, G * bs), v_scr, sems.at[1]).start()
        if quant:
            pltpu.make_async_copy(ks_src(off, G * bs), ks_scr,
                                  ssem.at[0]).start()
            pltpu.make_async_copy(vs_src(off, G * bs), vs_scr,
                                  ssem.at[1]).start()
        pltpu.make_async_copy(k_src(off, G * bs), k_scr, sems.at[0]).wait()
        pltpu.make_async_copy(v_src(off, G * bs), v_scr, sems.at[1]).wait()
        if quant:
            pltpu.make_async_copy(ks_src(off, G * bs), ks_scr,
                                  ssem.at[0]).wait()
            pltpu.make_async_copy(vs_src(off, G * bs), vs_scr,
                                  ssem.at[1]).wait()

    @pl.when(contig_ref[i] == 0)
    def _copy_tiled():
        # seq_len-bounded block reads (PROFILE.md serving lever): the
        # per-sequence copy is tiled at ``ts`` rows and HBM reads stop at
        # the sequence's settled length — with the linear layout a
        # 640-slot block holding a 130-token context streams 1 tile, not
        # 5. Dead tiles are ZEROED instead of copied: masked scores drop
        # them, but stale/uninitialized VMEM can hold NaN bit patterns and
        # 0 * NaN would poison the p@v matmul.
        nt = bs // ts

        def tile_live(g, t):
            return t * ts < lens_ref[i * G + g]

        for g in range(G):
            off = fetch_ref[i * G + g] * bs
            for t in range(nt):
                row = g * bs + t * ts

                @pl.when(tile_live(g, t))
                def _dma(off=off, t=t, row=row, g=g):
                    pltpu.make_async_copy(
                        k_src(off + t * ts, ts),
                        k_scr.at[pl.ds(row, ts)], sems.at[2 * g]).start()
                    pltpu.make_async_copy(
                        v_src(off + t * ts, ts),
                        v_scr.at[pl.ds(row, ts)],
                        sems.at[2 * g + 1]).start()
                    if quant:
                        pltpu.make_async_copy(
                            ks_src(off + t * ts, ts),
                            ks_scr.at[:, pl.ds(row, ts)],
                            ssem.at[2 + 2 * g]).start()
                        pltpu.make_async_copy(
                            vs_src(off + t * ts, ts),
                            vs_scr.at[:, pl.ds(row, ts)],
                            ssem.at[3 + 2 * g]).start()

                @pl.when(jnp.logical_not(tile_live(g, t)))
                def _zero(row=row):
                    k_scr[pl.ds(row, ts)] = jnp.zeros((ts, k_scr.shape[1]),
                                                      k_scr.dtype)
                    v_scr[pl.ds(row, ts)] = jnp.zeros((ts, v_scr.shape[1]),
                                                      v_scr.dtype)
                    if quant:
                        ks_scr[:, pl.ds(row, ts)] = jnp.zeros(
                            (KV, ts), ks_scr.dtype)
                        vs_scr[:, pl.ds(row, ts)] = jnp.zeros(
                            (KV, ts), vs_scr.dtype)
        for g in range(G):
            off = fetch_ref[i * G + g] * bs
            for t in range(nt):
                row = g * bs + t * ts

                @pl.when(tile_live(g, t))
                def _wait(off=off, t=t, row=row, g=g):
                    pltpu.make_async_copy(
                        k_src(off + t * ts, ts),
                        k_scr.at[pl.ds(row, ts)], sems.at[2 * g]).wait()
                    pltpu.make_async_copy(
                        v_src(off + t * ts, ts),
                        v_scr.at[pl.ds(row, ts)],
                        sems.at[2 * g + 1]).wait()
                    if quant:
                        pltpu.make_async_copy(
                            ks_src(off + t * ts, ts),
                            ks_scr.at[:, pl.ds(row, ts)],
                            ssem.at[2 + 2 * g]).wait()
                        pltpu.make_async_copy(
                            vs_src(off + t * ts, ts),
                            vs_scr.at[:, pl.ds(row, ts)],
                            ssem.at[3 + 2 * g]).wait()

    # scores per sequence (the matmuls are irreducibly [H, ...] slivers),
    # but ONE batched softmax over the whole group's [G*H, bs(+R)] rows —
    # the per-seq VPU passes (iota/mask/exp/sum), not the DMAs, were the
    # measured wall of the per-seq variant
    def ring_plane(ref, g):
        # ring5d: ref block is [R, 1, 1, G, KVD] (the full decode-loop
        # carry, layer/kv planes picked by the BlockSpec) -> [R, KVD]
        return ref[:, 0, 0, g] if ring5d else ref[g]

    grp = H // KV

    def _exp_heads(s):
        """[KV, w] per-kv-head scales -> [H, w] head rows (head h uses
        kv head h // grp)."""
        return jnp.broadcast_to(
            s[:, None, :], (KV, grp, s.shape[1])).reshape(H, s.shape[1])

    parts = []
    rparts = []
    for g in range(G):
        q = q_ref[g]                                   # [H, KVD] windowed
        kb = k_scr[pl.ds(g * bs, bs)]                  # [bs, KVD]
        if quant:
            kb = kb.astype(q.dtype)
        sc_g = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [H, bs]
        if quant:
            # K dequant scale is constant along the contracted D axis, so
            # it factors out of the matmul onto the score columns (exact)
            sc_g = sc_g * _exp_heads(ks_scr[:, g * bs:(g + 1) * bs])
        parts.append(sc_g)
        if R is not None:
            rparts.append(jax.lax.dot_general(
                q, ring_plane(rk_ref, g), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))   # [H, R]
    sc = jnp.concatenate(parts, axis=0) * sm_scale     # [G*H, bs]

    # per-row (seq, head) metadata at [G*H, 1]
    def per_seq(vals_fn):
        return jnp.concatenate(
            [jnp.full((H, 1), vals_fn(i * G + g), jnp.float32)
             for g in range(G)], axis=0)
    pos_rows = per_seq(lambda s: starts_ref[s].astype(jnp.float32))
    len_rows = per_seq(lambda s: lens_ref[s].astype(jnp.float32))
    col = jax.lax.broadcasted_iota(jnp.int32, (G * H, bs), 1) \
        .astype(jnp.float32)
    dist = pos_rows - col
    mask = col < len_rows
    if window is not None:
        mask = jnp.logical_and(mask, dist < window)
    if use_alibi:
        slope_rows = jnp.concatenate(
            [slopes_ref[...][:, None] for _ in range(G)], axis=0)
        sc = sc - slope_rows * dist
    sc = jnp.where(mask, sc, _NEG_INF)
    if R is not None:
        rsc = jnp.concatenate(rparts, axis=0) * sm_scale   # [G*H, R]
        r = jax.lax.broadcasted_iota(jnp.int32, (G * H, R), 1) \
            .astype(jnp.float32)
        rdist = rcount_ref[0].astype(jnp.float32) - 1.0 - r
        rmask = jnp.logical_and(r < rcount_ref[0], len_rows > 0)
        if window is not None:
            rmask = jnp.logical_and(rmask, rdist < window)
        if use_alibi:
            rsc = rsc - slope_rows * rdist
        rsc = jnp.where(rmask, rsc, _NEG_INF)
        full = jnp.concatenate([sc, rsc], axis=1)      # [G*H, bs + R]
    else:
        full = sc
    m = jnp.max(full, axis=1, keepdims=True)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(jnp.where(jnp.isfinite(full), full - m_safe, _NEG_INF))
    l = jnp.sum(p, axis=1, keepdims=True)
    l_safe = jnp.where(l == 0.0, 1.0, l)               # idle slots emit 0

    for g in range(G):
        vb = v_scr[pl.ds(g * bs, bs)]
        rows = slice(g * H, (g + 1) * H)
        pg = p[rows, :bs]
        if quant:
            # V dequant scale folds onto the probability columns
            pg = pg * _exp_heads(vs_scr[:, g * bs:(g + 1) * bs])
            vb = vb.astype(q_ref.dtype)
        pv = jax.lax.dot_general(
            pg.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [H, KVD]
        if R is not None:
            rvb = ring_plane(rv_ref, g)
            pv = pv + jax.lax.dot_general(
                p[rows, bs:].astype(rvb.dtype), rvb,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        o_ref[g] = (pv / l_safe[rows]).astype(o_ref.dtype)


def _flash_decode_grouped(qw, kp_flat, vp_flat, fetch, start_pos, seq_lens,
                          *, bs, H, KV, D, sm_scale, slopes, use_alibi,
                          window, ring_k, ring_v, ring_full, ring_layer,
                          ring_count, pool_full, pool_layer, scales_full,
                          k_scales, v_scales, out_dtype, interpret):
    """Grouped-decode dispatch: qw [S, H, KV*D] lane-windowed; whole
    contexts (linear layout, one block per sequence) stream via manual
    DMA, G sequences per grid step. The decode-loop ring arrives as the
    FULL [R, L, 2, S, KVD] carry — the BlockSpec picks this layer's k/v
    planes, so no per-layer slice/transpose ever materializes in HBM."""
    S = qw.shape[0]
    KVD = KV * D
    quant = kp_flat.dtype == jnp.int8
    if quant and not interpret and (KVD % 128 or bs % 128):
        # the manual-DMA path slices [off : off+n] windows out of larger
        # arrays: int8 rows need (32, 128)-tile-aligned slice shapes and
        # the f32 scale windows need 128-lane-aligned offsets/widths —
        # block offsets are block_id * block_size, so block_size % 128
        # covers both. Real serving shapes (KV*D >= 512, linear-layout
        # blocks sized to max context) satisfy this naturally.
        raise ValueError(
            f"int8 grouped decode requires KV*D ({KVD}) and block_size "
            f"({bs}) to be multiples of 128 (Mosaic DMA tiling); use an "
            f"aligned block_size or attention_impl='dense'")
    itemsize = kp_flat.dtype.itemsize
    # VMEM budget: k+v scratch is G * bs * KVD * itemsize * 2 (+ the
    # [KV, G*bs] f32 scale scratches in int8 mode)
    budget = 10 << 20
    per_seq = 2 * bs * KVD * itemsize + (2 * KV * bs * 4 if quant else 0)
    G = max(1, min(8, budget // max(1, per_seq)))
    while S % G:
        G -= 1
    if ring_full is not None:
        R = ring_full.shape[0]
        ring5d = True
    elif ring_k is not None:
        R = ring_k.shape[1]
        ring5d = False
    else:
        R = None
        ring5d = False

    use_pool_full = pool_full is not None and pool_layer is not None
    if use_pool_full:
        if pool_full.ndim != 4 or pool_full.shape[1] != 2 \
                or pool_full.shape[3] != KVD:
            raise ValueError(
                f"pool_full must be [L, 2, slots, {KVD}], got "
                f"{pool_full.shape}")
        if not 0 <= int(pool_layer) < pool_full.shape[0]:
            raise ValueError(
                f"pool_layer {pool_layer} out of range for L = "
                f"{pool_full.shape[0]}")
    if ring5d:
        if ring_full.ndim != 5 or ring_full.shape[2] != 2:
            raise ValueError(
                f"ring_full must be [R, L, 2, S, KVD], got "
                f"{ring_full.shape}")
        if not 0 <= int(ring_layer) < ring_full.shape[1]:
            raise ValueError(
                f"ring_layer {ring_layer} out of range for L = "
                f"{ring_full.shape[1]}")
        # over an int8 pool the ring stays in the COMPUTE dtype (= qw's);
        # otherwise it must share the pool's dtype (never cast)
        expect = qw.dtype if quant else (
            pool_full.dtype if use_pool_full else kp_flat.dtype)
        if ring_full.dtype != expect:
            raise ValueError(
                f"ring_full dtype {ring_full.dtype} != expected {expect} "
                f"(the grouped kernel does not cast the full ring)")
    # copy-tile rows for the seq_len-bounded path: the largest 128-multiple
    # dividing bs (DMA offsets stay (int8: 32, else 8/16)x128-tile aligned);
    # blocks under 128 rows stream whole (already small)
    ts = next((d for d in (256, 128) if bs % d == 0), bs)
    kernel = functools.partial(
        _decode_grouped_kernel, G=G, bs=bs, ts=ts, H=H, KV=KV, D=D,
        sm_scale=float(sm_scale), use_alibi=use_alibi, window=window, R=R,
        ring5d=ring5d, use_pool_full=use_pool_full, quant=quant,
        sc_full=scales_full is not None)

    in_specs = [
        pl.BlockSpec((G, H, KVD), lambda i, *_: (i, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    if use_pool_full:
        # the un-sliced [L, 2, slots, KVD] pool; the layer offset lives in
        # the kernel's DMA source (vp operand is a placeholder)
        operands = [qw.reshape(S, H, KVD), pool_full,
                    jnp.zeros((8, _LANES), pool_full.dtype)]
    else:
        operands = [qw.reshape(S, H, KVD), kp_flat, vp_flat]
    if ring5d:
        # the layer index comes from scalar prefetch (refs[5]) so the ring
        # index maps — like the pool DMA source — stay layer-invariant and
        # every layer shares one compiled kernel
        rk_spec = pl.BlockSpec(
            (R, 1, 1, G, KVD), lambda i, *refs: (0, refs[5][0], 0, i, 0))
        rv_spec = pl.BlockSpec(
            (R, 1, 1, G, KVD), lambda i, *refs: (0, refs[5][0], 1, i, 0))
        in_specs += [rk_spec, rv_spec]
        operands += [ring_full, ring_full]
    elif R is not None:
        ring_spec = pl.BlockSpec((G, R, KVD), lambda i, *_: (i, 0, 0))
        in_specs += [ring_spec, ring_spec]
        operands += [ring_k.astype(kp_flat.dtype),
                     ring_v.astype(vp_flat.dtype)]
    else:
        # dummy tiny operands keep one kernel signature
        z = jnp.zeros((S, 8, KVD), kp_flat.dtype)
        in_specs += [pl.BlockSpec((G, 8, KVD), lambda i, *_: (i, 0, 0))] * 2
        operands += [z, z]
    if quant:
        # int8 scale windows: the full [L, 2, KV, slots] array rides twice
        # (k/v planes picked in-kernel) or the per-layer [KV, slots] pair
        in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * 2
        if scales_full is not None:
            operands += [scales_full, scales_full]
        else:
            operands += [k_scales.astype(jnp.float32),
                         v_scales.astype(jnp.float32)]

    # host-side run check: a group whose G block ids are consecutive AND
    # whose sequences are all within ONE copy tile of full takes the
    # single-DMA fast path (the tiled copy could save at most ts rows per
    # sequence there — not worth G x nt DMA issues in the near-full
    # steady state); shorter groups go through the tiled copy so HBM
    # reads stop at each sequence's settled length (seq_len-bounded
    # block reads)
    fg = fetch.astype(jnp.int32).reshape(S // G, G)
    contig = jnp.all(
        fg == fg[:, :1] + jnp.arange(G, dtype=jnp.int32)[None, :],
        axis=1)
    near_full = jnp.all(
        seq_lens.astype(jnp.int32).reshape(S // G, G) > bs - ts, axis=1)
    contig = jnp.logical_and(contig, near_full).astype(jnp.int32)

    scr_dtype = pool_full.dtype if use_pool_full else kp_flat.dtype
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=7,
        grid=(S // G,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((G, H, KVD), lambda i, *_: (i, 0, 0)),
        scratch_shapes=(
            [pltpu.VMEM((G * bs, KVD), scr_dtype),
             pltpu.VMEM((G * bs, KVD), scr_dtype)]
            + ([pltpu.VMEM((KV, G * bs), jnp.float32)] * 2 if quant else [])
            + [pltpu.SemaphoreType.DMA((2 * G,))]
            + ([pltpu.SemaphoreType.DMA((2 * G + 2,))] if quant else [])
        ),
    )
    layer_idx = int(pool_layer) if use_pool_full else (
        int(ring_layer) if ring5d else 0)
    if use_pool_full and ring5d and int(pool_layer) != int(ring_layer):
        raise ValueError("pool_layer and ring_layer must match (one layer "
                         "index drives both prefetch-indexed operands)")
    prefetch = [start_pos.astype(jnp.int32), fetch.astype(jnp.int32),
                seq_lens.astype(jnp.int32),
                (jnp.reshape(ring_count, (1,)).astype(jnp.int32)
                 if ring_count is not None else jnp.zeros((1,), jnp.int32)),
                contig, jnp.full((1,), layer_idx, jnp.int32), slopes]
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, KVD), out_dtype),
        compiler_params=_compat_tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*prefetch, *operands)
    return out[:, None]                                 # [S, 1, H, KVD]


def flash_paged_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                          v_pool: jnp.ndarray, block_tables: jnp.ndarray,
                          start_pos: jnp.ndarray, seq_lens: jnp.ndarray,
                          *, block_size: int,
                          sm_scale: Optional[float] = None,
                          alibi_slopes: Optional[jnp.ndarray] = None,
                          sliding_window: Optional[int] = None,
                          ring_k: Optional[jnp.ndarray] = None,
                          ring_v: Optional[jnp.ndarray] = None,
                          ring_count: Optional[jnp.ndarray] = None,
                          ring_full: Optional[jnp.ndarray] = None,
                          ring_layer: int = 0,
                          pool_full: Optional[jnp.ndarray] = None,
                          pool_layer: Optional[int] = None,
                          scales_full: Optional[jnp.ndarray] = None,
                          k_scales: Optional[jnp.ndarray] = None,
                          v_scales: Optional[jnp.ndarray] = None,
                          num_kv_heads: Optional[int] = None,
                          interpret: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention over paged KV.

    Args:
      q: [S, C, H, D] — C query tokens per slot (1 for pure decode;
        SplitFuse prefill chunks are larger). The step's K/V must ALREADY
        be in the pool (causal masking handles the chunk interior), except
        in ring mode where the loop's tokens live in ring_k/ring_v.
      k_pool/v_pool: [slots, KV*D] flat token rows (or [slots, KV, D],
        viewed flat) with slots = (num_blocks + 1) * block_size (trailing
        trash block).
      block_tables: [S, MAXB] int32 — pool block id per sequence block.
      start_pos: [S] int32 — absolute position of q[s, 0].
      seq_lens: [S] int32 — settled context length (0 marks an idle slot,
        which emits zeros). In ring mode this EXCLUDES the ring tokens.
      ring_k/ring_v: optional [S, R, KV*D] decode-loop ring buffers;
        ring_count: tokens valid in the ring.
      ring_full/ring_layer: the PREFERRED ring form — the full
        [R, L, 2, S, KV*D] decode-loop carry plus this call's (static)
        layer index; the grouped decode path selects the layer/kv planes
        in its BlockSpec, so no per-layer slice/transpose materializes.
        Must share the pool's dtype (never cast).
      pool_full/pool_layer: the PREFERRED pool form for decode — the
        un-sliced [L, 2, slots, KV*D] pool plus the layer index; the
        grouped path indexes the layer inside its DMA source (a
        model-level pool[layer, 0/1] slice materializes a full per-layer
        pool copy for the Pallas operand). When both full forms are given
        the two layer indices must match. k_pool/v_pool remain required
        (shape probing + the multi-block fallback path; dead code under
        jit when the grouped path runs).
      alibi_slopes: optional [H] f32 — in-kernel ALiBi bias (falcon/bloom).
      scales_full / k_scales+v_scales: int8-pool dequantization scales
        (kv_quant.py layout): ``scales_full`` [L, 2, KV, slots] rides whole
        with the layer picked in-kernel; ``k_scales``/``v_scales``
        [KV, slots] are the per-layer form for direct callers. Scales are
        per (token-row, kv-head); the kernel multiplies SCORE columns by
        the K scale and probability columns by the V scale — exact, and no
        dequantized K/V tile ever materializes. q then stays in its own
        (compute) dtype, and the decode-ring stays unquantized.

    Returns [S, C, H, D] attention outputs in q.dtype. HBM traffic per
    step is O(sum of live blocks) of UNPADDED rows.
    """
    if interpret is None:
        from . import default_interpret
        interpret = default_interpret()
    S, C, H, D = q.shape
    if k_pool.ndim == 3:
        KV = k_pool.shape[1]
        k_pool = k_pool.reshape(k_pool.shape[0], -1)
        v_pool = v_pool.reshape(v_pool.shape[0], -1)
    else:
        if num_kv_heads is None:
            raise ValueError("num_kv_heads required with a flat 2-D pool")
        KV = num_kv_heads
    slots, KVD = k_pool.shape
    if KVD != KV * D:
        raise ValueError(f"pool rows {KVD} != KV*D = {KV * D}")
    bs = block_size
    if H % KV:
        raise ValueError(f"GQA requires H % KV == 0 ({H}/{KV})")
    if slots % bs:
        raise ValueError(
            f"pool slots ({slots}) must be a multiple of block_size ({bs}); "
            f"allocate (num_blocks+1)*block_size with a trailing trash block")
    maxb = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    g = H // KV

    # int8 pool: scales required; normalize to the per-layer [KV, slots]
    # form for the BlockSpec (prefill) path — the grouped decode path
    # prefers scales_full (layer picked inside the DMA source)
    quant = k_pool.dtype == jnp.int8
    if quant:
        if scales_full is not None:
            if scales_full.ndim != 4 or scales_full.shape[1] != 2 \
                    or scales_full.shape[2] != KV \
                    or scales_full.shape[3] != slots:
                raise ValueError(
                    f"scales_full must be [L, 2, {KV}, {slots}], got "
                    f"{scales_full.shape}")
            li = int(pool_layer) if pool_layer is not None else 0
            if k_scales is None:
                k_scales = scales_full[li, 0]
                v_scales = scales_full[li, 1]
        if k_scales is None or v_scales is None:
            raise ValueError(
                "an int8 k_pool needs scales (scales_full or "
                "k_scales+v_scales, see kv_quant.py)")
        if k_scales.shape != (KV, slots):
            raise ValueError(
                f"k_scales must be [{KV}, {slots}], got {k_scales.shape}")
        compute_dt = q.dtype if q.dtype != jnp.int8 else jnp.bfloat16
    elif scales_full is not None or k_scales is not None:
        raise ValueError("KV scales passed but the pool is not int8")
    else:
        compute_dt = k_pool.dtype

    # processing granularity decouples from the allocator's block size:
    # decode (C==1, scratch is tiny) streams each block whole — one DMA per
    # sequence with the linear one-block-per-seq layout; prefill processes
    # blocks in sub-tiles so KV tiles + the H*Cb softmax scratch fit VMEM.
    if C == 1:
        # whole blocks, but capped so a K/V tile stays ~<=2 MB of VMEM
        # (large linear block_size x wide rows would blow the budget)
        cap = max(256, (2 << 20) // (KVD * k_pool.dtype.itemsize))
        pbs = next(d for d in range(min(bs, cap), 0, -1) if bs % d == 0)
    else:
        pbs = next(d for d in range(min(bs, 256), 0, -1) if bs % d == 0)
    factor = bs // pbs
    maxb_v = maxb * factor
    nb_pool = slots // pbs

    kp = k_pool.reshape(nb_pool, pbs, KVD)
    vp = v_pool.reshape(nb_pool, pbs, KVD)

    # query-chunk tiling: scratch rows are H*Cb, so bound Cb to keep the
    # online-softmax state (m/l at 128 lanes + f32 acc over KV*D) plus the
    # pipelined KV tiles well under the 16 MB VMEM budget
    kv_tile_bytes = 4 * pbs * KVD * 2                   # 2x dbl-buffer, k+v
    row_bytes = (2 * _LANES + KVD) * 4 + 4 * KVD * q.dtype.itemsize
    row_budget = max(1 << 20, 8 * (1 << 20) - kv_tile_bytes)
    Cb = min(C, max(8, (row_budget // (H * row_bytes)) // 8 * 8))
    nCb = -(-C // Cb)

    nlive = jnp.minimum((seq_lens + pbs - 1) // pbs,
                        maxb_v).astype(jnp.int32)
    qcs = jnp.arange(nCb, dtype=jnp.int32)[None, :]         # [1, nCb]
    # per-(seq, q-chunk) live range: blocks past the chunk's last query
    # position are dead by causality (big win for early prefill chunks)
    chunk_end = start_pos[:, None] + (qcs + 1) * Cb         # exclusive
    hi = jnp.minimum(nlive[:, None], (chunk_end - 1) // pbs + 1)
    hi = jnp.maximum(hi, 0).astype(jnp.int32)               # [S, nCb]
    # sliding window: blocks entirely below every query's window are dead
    if sliding_window is not None:
        first_q = start_pos[:, None] + qcs * Cb
        lo = jnp.maximum(first_q - sliding_window + 1, 0) // pbs
        lo = jnp.minimum(lo.astype(jnp.int32), jnp.maximum(hi - 1, 0))
    else:
        lo = jnp.zeros_like(hi)
    # dead steps re-fetch a live block: no new DMA
    jj = jnp.arange(maxb_v, dtype=jnp.int32)[None, :]
    jjc = jnp.clip(jj, 0, jnp.maximum(nlive[:, None] - 1, 0))
    fetch = (jnp.take_along_axis(block_tables.astype(jnp.int32),
                                 jjc // factor, axis=1) * factor
             + jjc % factor)

    use_alibi = alibi_slopes is not None
    slopes = (jnp.asarray(alibi_slopes, jnp.float32) if use_alibi
              else jnp.zeros((H,), jnp.float32))

    # ring_full [R, L, 2, S, KVD] + ring_layer: the kernel's BlockSpec
    # selects the layer/kv planes itself (the grouped path) — no per-layer
    # host-side slice/transpose ever materializes. ring_k/ring_v
    # [S, R, KVD] remain for the legacy per-sequence path.
    has_ring = ring_k is not None or ring_full is not None
    if has_ring and C != 1:
        raise ValueError("ring decode requires C == 1 (pure decode steps)")
    if ring_k is not None and ring_k.shape[2] != KVD:
        raise ValueError(f"ring rows must be flat [S, R, {KVD}]")
    if ring_full is not None and ring_full.shape[4] != KVD:
        raise ValueError(f"ring_full must be [R, L, 2, S, {KVD}]")
    R = (ring_k.shape[1] if ring_k is not None
         else ring_full.shape[0] if ring_full is not None else None)

    windowed = C == 1
    if windowed:
        # lane-window q: row (h, c) carries q[s, c, h] in lane window
        # (h // g) * D, zeros elsewhere — one matmul covers every head
        # (module docstring). Tiny next to KV traffic at decode.
        sel = (jnp.arange(KV)[None, :] == (jnp.arange(H) // g)[:, None])
        qw = (q.swapaxes(1, 2)[:, :, :, None, :]
              * sel[None, :, None, :, None].astype(q.dtype))  # [S,H,C,KV,D]
        qw = qw.reshape(S, H, C, KVD).astype(compute_dt)
        row_lanes = KVD
        if maxb_v == 1:
            # linear layout, whole context in one block: the grouped
            # kernel processes several sequences per grid step with manual
            # async DMAs — the per-grid-step fixed cost was the decode wall
            out = _flash_decode_grouped(
                qw.reshape(S, H, KVD), k_pool, v_pool, fetch[:, 0],
                start_pos, seq_lens, bs=pbs, H=H, KV=KV, D=D,
                sm_scale=sm_scale, slopes=slopes, use_alibi=use_alibi,
                window=(int(sliding_window) if sliding_window is not None
                        else None),
                ring_k=ring_k, ring_v=ring_v,
                ring_full=ring_full, ring_layer=int(ring_layer),
                ring_count=(ring_count if has_ring else None),
                pool_full=pool_full, pool_layer=pool_layer,
                # the full-scales form indexes layers with the same
                # prefetched layer id as the full pool — without pool_full
                # that id defaults to 0, so fall back to the (already
                # layer-sliced) per-layer scales instead
                scales_full=(scales_full
                             if quant and pool_full is not None
                             and pool_layer is not None else None),
                k_scales=k_scales if quant else None,
                v_scales=v_scales if quant else None,
                out_dtype=q.dtype, interpret=interpret)
            out = out.reshape(S, 1, H, KVD).swapaxes(1, 2)  # [S, H, 1, KVD]
            head_win = (jnp.arange(H) // g)[:, None] * D \
                + jnp.arange(D)[None, :]
            out = jnp.take_along_axis(out, head_win[None, :, None, :],
                                      axis=3)
            return jnp.moveaxis(out, 1, 2)              # [S, 1, H, D]
    else:
        qw = q.swapaxes(1, 2).astype(compute_dt)       # [S, H, C, D]
        row_lanes = D

    kernel = functools.partial(
        _paged_kernel, bs=pbs, Cb=Cb, nCb=nCb, H=H, KV=KV, D=D,
        sm_scale=float(sm_scale), use_alibi=use_alibi,
        window=int(sliding_window) if sliding_window is not None else None,
        R=R, windowed=windowed, quant=quant)

    n_pref = 7 if has_ring else 5

    def _kv_block(s, qc, j, *pref):
        fetch_ref, lo_ref, hi_ref = pref[1], pref[2], pref[3]
        # clamp into this (s, qc)'s live range so dead grid steps (incl.
        # the ring round) revisit a fetched block (no DMA) instead of
        # pulling a new one
        sq = s * nCb + qc
        jc = jnp.clip(j, lo_ref[sq], jnp.maximum(hi_ref[sq] - 1, 0))
        return fetch_ref[s * maxb_v + jc]

    def kv_index(s, qc, j, *pref):
        return (_kv_block(s, qc, j, *pref), 0, 0)

    def sc_index(s, qc, j, *pref):
        return (_kv_block(s, qc, j, *pref), 0, 0)

    # q rows for chunk qc must be one contiguous [H*Cb] row block: reorder
    # chunk-major (pad C up to nCb*Cb first; padded rows compute garbage
    # nobody reads — their rows are sliced off after the call)
    Cpad = nCb * Cb
    if nCb == 1:
        qw = qw.reshape(S, H * C, row_lanes)
    else:
        if Cpad != C:
            qw = jnp.pad(qw, ((0, 0), (0, 0), (0, Cpad - C), (0, 0)))
        qw = qw.reshape(S, H, nCb, Cb, row_lanes).swapaxes(1, 2).reshape(
            S, nCb * H * Cb, row_lanes)
    q_spec = pl.BlockSpec((1, H * Cb, row_lanes),
                          lambda s, qc, j, *_: (s, qc, 0))
    o_spec = pl.BlockSpec((1, H * Cb, row_lanes),
                          lambda s, qc, j, *_: (s, qc, 0))

    in_specs = [
        q_spec,
        pl.BlockSpec((1, pbs, KVD), kv_index),
        pl.BlockSpec((1, pbs, KVD), kv_index),
    ]
    operands = [qw, kp, vp]
    if quant:
        # per-layer [KV, slots] scales re-laid [nb, KV, pbs] so a block's
        # minor dims are (KV, pbs) proper tiles; the same clamped block
        # index feeds both the KV tile and its scale window
        ksb = k_scales.astype(jnp.float32).reshape(
            KV, nb_pool, pbs).swapaxes(0, 1)
        vsb = v_scales.astype(jnp.float32).reshape(
            KV, nb_pool, pbs).swapaxes(0, 1)
        in_specs += [pl.BlockSpec((1, KV, pbs), sc_index)] * 2
        operands += [ksb, vsb]
    grid = (S, nCb, maxb_v + 1 if has_ring else maxb_v)
    if has_ring:
        if ring_k is None:
            # legacy per-sequence path fed from the 5-D ring: materialize
            # the per-layer planes (the grouped fast path above avoids it)
            ring_k = jnp.moveaxis(ring_full[:, ring_layer, 0], 0, 1)
            ring_v = jnp.moveaxis(ring_full[:, ring_layer, 1], 0, 1)
        ring_spec = pl.BlockSpec((1, R, KVD),
                                 lambda s, qc, j, *_: (s, 0, 0))
        in_specs += [ring_spec, ring_spec]
        operands += [ring_k.astype(compute_dt),
                     ring_v.astype(compute_dt)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_pref,
        grid=grid,
        in_specs=in_specs,
        out_specs=o_spec,
        scratch_shapes=[
            pltpu.VMEM((H * Cb, _LANES), jnp.float32),
            pltpu.VMEM((H * Cb, _LANES), jnp.float32),
            pltpu.VMEM((H * Cb, row_lanes), jnp.float32),
        ],
    )
    prefetch = [start_pos.astype(jnp.int32), fetch.reshape(-1),
                lo.reshape(-1), hi.reshape(-1), slopes]
    if has_ring:
        prefetch.append(jnp.reshape(ring_count, (1,)).astype(jnp.int32))
        prefetch.append(seq_lens.astype(jnp.int32))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qw.shape, q.dtype),
        compiler_params=_compat_tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(*prefetch, *operands)
    # undo chunk-major row order, then (windowed mode) slice each head's
    # lane window out of the [KV*D]-wide accumulator rows
    if nCb > 1:
        out = out.reshape(S, nCb, H, Cb, row_lanes).swapaxes(1, 2).reshape(
            S, H, Cpad, row_lanes)[:, :, :C]
    else:
        out = out.reshape(S, H, C, row_lanes)
    if windowed:
        head_win = (jnp.arange(H) // g)[:, None] * D \
            + jnp.arange(D)[None, :]
        out = jnp.take_along_axis(out, head_win[None, :, None, :], axis=3)
    return jnp.moveaxis(out, 1, 2)                      # [S, C, H, D]
