"""Streaming fused LM-head cross-entropy (Pallas TPU) — fwd + bwd.

The LM-head matmul + softmax cross-entropy is the single largest non-layer
cost of causal-LM training (measured 23% of the 124M step — PROFILE.md):
``[N, C] @ [V, C]^T`` logits are V-wide (50k+), and every implementation
that materializes them pays O(N*V) HBM traffic in fp32. The reference
always pays full-logits cost (training goes through torch cross_entropy);
the in-tree ``chunked_lm_xent`` (models/_lm_utils.py) bounds the LIVE
footprint by chunking + remat but still streams each fp32 chunk through
HBM and serializes chunks in a scan.

This kernel never writes logits to HBM at all:

  forward  — grid (token tiles × vocab tiles), online logsumexp exactly
    like flash attention's softmax, plus the target logit extracted via an
    in-tile one-hot reduction. Outputs per-token ``lse`` and ``tgt`` only.
  backward — two passes with OPPOSITE grid orders, each recomputing the
    logits tile on the fly (bf16 MXU, f32 accumulation):
      dh   = (P - onehot) @ E   — token-tile outer, dh accumulates in VMEM
             across the inner vocab walk;
      dE   = (P - onehot)^T @ H — vocab-tile outer, dE accumulates in VMEM
             across the inner token walk.
    Both reductions need the full opposite axis in their inner loop, which
    is exactly why ONE pass cannot emit both (the second output would be
    revisited non-consecutively); the extra logits recompute is one more
    N*V*C matmul — MXU FLOPs traded for zero O(N*V) HBM traffic.

Cost accounting vs the chunked path: 5 MXU passes of N*V*C MACs
(fwd, 2x recompute, dh, dE) vs the chunked path's 4 plus ~8*N*V bytes of
fp32 chunk HBM traffic plus scan serialization. Bandwidth-bound shapes
win; the crossover is measured, not assumed (tools/profile_train.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from ...utils.jax_compat import tpu_compiler_params as _compat_tpu_compiler_params

_NEG_INF = float("-inf")
_LANES = 128


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# --------------------------------------------------------------------- #
# forward: lse + target logit, no logits in HBM
# --------------------------------------------------------------------- #

def _fwd_kernel(h_ref, e_ref, t_ref, lse_ref, tgt_ref, lsum_ref, m_scr,
                l_scr, g_scr, s_scr, *, Tb, Vb, V, Vt, eps):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        g_scr[:] = jnp.zeros(g_scr.shape, g_scr.dtype)
        s_scr[:] = jnp.zeros(s_scr.shape, s_scr.dtype)

    logits = jax.lax.dot_general(
        h_ref[...], e_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # [Tb, Vb]
    col = j * Vb + jax.lax.broadcasted_iota(jnp.int32, (Tb, Vb), 1)
    if eps:
        # label smoothing's uniform term wants sum_j logits_j over the
        # REAL vocab columns — accumulated pre-mask (the -inf form can't
        # be summed). Statically skipped when smoothing is off.
        s_scr[:, :1] = s_scr[:, :1] + jnp.sum(
            jnp.where(col < V, logits, 0.0), axis=1, keepdims=True)

    # target logit: one-hot row reduction inside the tile (a per-row
    # dynamic gather would leave the VPU's vector regime). Accumulated
    # from the PRE-mask logits: a corrupt id in [V, Vt*Vb) then picks up
    # a finite padded-column value (zeros-padded embedding rows) instead
    # of -inf poisoning the whole loss — the row is excluded from loss
    # and gradients by the valid mask either way.
    t_loc = t_ref[...].astype(jnp.int32)                 # [Tb, 1] global id
    hit = col == t_loc
    g_scr[:, :1] = g_scr[:, :1] + jnp.sum(
        jnp.where(hit, logits, 0.0), axis=1, keepdims=True)

    logits = jnp.where(col < V, logits, _NEG_INF)

    m_prev, l_prev = m_scr[:, :1], l_scr[:, :1]
    m_cur = jnp.max(logits, axis=1, keepdims=True)
    m_next = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_next)                     # m_prev=-inf -> 0
    p = jnp.exp(logits - m_next)
    l_next = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    m_scr[:, :1] = m_next
    l_scr[:, :1] = l_next

    @pl.when(j == Vt - 1)
    def _finish():
        lse_ref[...] = m_scr[:, :1] + jnp.log(
            jnp.maximum(l_scr[:, :1], 1e-37))
        tgt_ref[...] = g_scr[:, :1]
        lsum_ref[...] = s_scr[:, :1]


def _fwd(h2, emb, tgt2, *, Tb, Vb, eps, interpret):
    N2, C = h2.shape
    V = emb.shape[0]
    Nt, Vt = N2 // Tb, _round_up(V, Vb) // Vb
    Vpad = Vt * Vb - V
    e = jnp.pad(emb, ((0, Vpad), (0, 0))) if Vpad else emb
    e = e.astype(h2.dtype)
    kernel = functools.partial(_fwd_kernel, Tb=Tb, Vb=Vb, V=V, Vt=Vt,
                               eps=eps)
    lse, tgt, lsum = pl.pallas_call(
        kernel,
        grid=(Nt, Vt),
        in_specs=[
            pl.BlockSpec((Tb, C), lambda i, j: (i, 0)),
            pl.BlockSpec((Vb, C), lambda i, j: (j, 0)),
            pl.BlockSpec((Tb, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((Tb, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((Tb, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((Tb, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((N2, 1), jnp.float32)] * 3,
        scratch_shapes=[pltpu.VMEM((Tb, _LANES), jnp.float32)] * 4,
        compiler_params=_compat_tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(h2, e, tgt2[:, None])
    return lse[:, 0], tgt[:, 0], lsum[:, 0]


# --------------------------------------------------------------------- #
# backward pass 1: dh = scale * (P - onehot) @ E   (token-tile outer)
# --------------------------------------------------------------------- #

def _grad_p(logits, lse_col, t_loc, col, *, V, z, eps, ignore):
    """d loss_row / d logits for one tile (pure jnp, shared by both
    backward kernels so the ignore/z/eps semantics can never diverge):
    ``(1 + 2z*lse) * P - (1-eps)*onehot - eps/V`` over real vocab
    columns, zeroed at ignored positions."""
    p = jnp.where(col < V, jnp.exp(logits - lse_col), 0.0)
    if z:
        p = p * (1.0 + 2.0 * z * lse_col)
    p = p - jnp.where(col == t_loc, 1.0 - eps, 0.0)
    if eps:
        p = p - jnp.where(col < V, eps / V, 0.0)
    # rows whose target id is out of range — negative (ignore ids like
    # -100) or >= V (corrupt labels) — contribute NO gradient, matching
    # their exclusion from the loss and the divisor
    p = jnp.where((t_loc < 0) | (t_loc >= V), 0.0, p)
    if ignore is not None:
        p = jnp.where(t_loc == ignore, 0.0, p)
    return p



def _dh_kernel(s_ref, h_ref, e_ref, t_ref, lse_ref, dh_ref, acc_scr,
               *, Tb, Vb, V, Vt, ignore, z, eps):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    logits = jax.lax.dot_general(
        h_ref[...], e_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    col = j * Vb + jax.lax.broadcasted_iota(jnp.int32, (Tb, Vb), 1)
    p = _grad_p(logits, lse_ref[...], t_ref[...].astype(jnp.int32), col,
                V=V, z=z, eps=eps, ignore=ignore)
    acc_scr[:] = acc_scr[:] + jax.lax.dot_general(
        p.astype(h_ref.dtype), e_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [Tb, C]

    @pl.when(j == Vt - 1)
    def _finish():
        dh_ref[0] = (acc_scr[:] * s_ref[0]).astype(dh_ref.dtype)


# --------------------------------------------------------------------- #
# backward pass 2: dE = scale * (P - onehot)^T @ H  (vocab-tile outer)
# --------------------------------------------------------------------- #

def _de_kernel(s_ref, h_ref, e_ref, t_ref, lse_ref, de_ref, acc_scr,
               *, Tb, Vb, V, N, Nt, ignore, z, eps):
    i = pl.program_id(1)
    j = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    logits = jax.lax.dot_general(
        h_ref[...], e_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # [Tb, Vb]
    col = j * Vb + jax.lax.broadcasted_iota(jnp.int32, (Tb, Vb), 1)
    p = _grad_p(logits, lse_ref[...], t_ref[...].astype(jnp.int32), col,
                V=V, z=z, eps=eps, ignore=ignore)
    # padded token rows carry P = uniform garbage (their h rows are zero
    # but lse is finite): mask them out of the vocab-side reduction
    row = i * Tb + jax.lax.broadcasted_iota(jnp.int32, (Tb, Vb), 0)
    p = jnp.where(row < N, p, 0.0)
    acc_scr[:] = acc_scr[:] + jax.lax.dot_general(
        p.astype(h_ref.dtype), h_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # [Vb, C]

    @pl.when(i == Nt - 1)
    def _finish():
        de_ref[0] = (acc_scr[:] * s_ref[0]).astype(de_ref.dtype)


# --------------------------------------------------------------------- #
# public op with custom VJP
# --------------------------------------------------------------------- #

def _valid_rows(tgt2, N, ignore, V):
    # in-range check mirrors chunked_lm_xent: out-of-range non-ignored
    # ids (corrupt labels) are dropped from loss + divisor, never
    # trained against
    valid = (jnp.arange(tgt2.shape[0]) < N) & (tgt2 >= 0) & (tgt2 < V)
    if ignore is not None:
        valid = jnp.logical_and(valid, tgt2 != ignore)
    return valid


def _core_total(lse, tgt, lsum, V, tgt2, N, ignore, z, eps):
    valid = _valid_rows(tgt2, N, ignore, V)
    # smoothed NLL: lse - (1-eps)*tgt_logit - (eps/V)*sum_j logits_j
    nll = lse - (1.0 - eps) * tgt
    if eps:
        nll = nll - (eps / V) * lsum
    if z:
        nll = nll + z * lse * lse       # PaLM-style z-loss stabilizer
    return jnp.where(valid, nll, 0.0).sum()


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _xent_core(h2, emb, tgt2, N, Tb, Vb, ignore, z, eps, interpret):
    """Sum of next-token NLL (+ optional z-loss) over the first ``N``
    (valid, non-ignored) rows. The SUM — not the mean — is the
    custom-vjp boundary so the incoming cotangent is a SCALAR (the
    mean's 1/count folds outside); per-row cotangents would need a
    non-separable dE scaling the kernels cannot fold."""
    lse, tgt, lsum = _fwd(h2, emb, tgt2, Tb=Tb, Vb=Vb, eps=eps,
                          interpret=interpret)
    return _core_total(lse, tgt, lsum, emb.shape[0], tgt2, N, ignore, z,
                       eps)


def _xent_fwd_rule(h2, emb, tgt2, N, Tb, Vb, ignore, z, eps, interpret):
    lse, tgt, lsum = _fwd(h2, emb, tgt2, Tb=Tb, Vb=Vb, eps=eps,
                          interpret=interpret)
    total = _core_total(lse, tgt, lsum, emb.shape[0], tgt2, N, ignore, z,
                        eps)
    return total, (h2, emb, tgt2, lse)


def _xent_bwd_rule(N, Tb, Vb, ignore, z, eps, interpret, res, g):
    h2, emb, tgt2, lse = res
    N2, C = h2.shape
    V = emb.shape[0]
    Nt, Vt = N2 // Tb, _round_up(V, Vb) // Vb
    Vpad = Vt * Vb - V
    e = jnp.pad(emb, ((0, Vpad), (0, 0))) if Vpad else emb
    e = e.astype(h2.dtype)
    # d(sum nll)/d(logit) = P - onehot per valid row, all scaled by the
    # scalar cotangent g. Padded rows: dE masks them in-kernel (row < N);
    # dh's padded rows are garbage that jnp.pad's own VJP slices off.
    scale = jnp.reshape(g, (1,)).astype(jnp.float32)

    dh = pl.pallas_call(
        functools.partial(_dh_kernel, Tb=Tb, Vb=Vb, V=V, Vt=Vt,
                          ignore=ignore, z=z, eps=eps),
        grid=(Nt, Vt),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((Tb, C), lambda i, j: (i, 0)),
            pl.BlockSpec((Vb, C), lambda i, j: (j, 0)),
            pl.BlockSpec((Tb, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((Tb, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, Tb, C), lambda i, j: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((Nt, Tb, C), h2.dtype),
        scratch_shapes=[pltpu.VMEM((Tb, C), jnp.float32)],
        compiler_params=_compat_tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(scale, h2, e, tgt2[:, None], lse[:, None]).reshape(N2, C)

    de = pl.pallas_call(
        functools.partial(_de_kernel, Tb=Tb, Vb=Vb, V=V, N=N, Nt=Nt,
                          ignore=ignore, z=z, eps=eps),
        grid=(Vt, Nt),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((Tb, C), lambda j, i: (i, 0)),
            pl.BlockSpec((Vb, C), lambda j, i: (j, 0)),
            pl.BlockSpec((Tb, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((Tb, 1), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, Vb, C), lambda j, i: (j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((Vt, Vb, C), jnp.float32),
        scratch_shapes=[pltpu.VMEM((Vb, C), jnp.float32)],
        compiler_params=_compat_tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(scale, h2, e, tgt2[:, None], lse[:, None]).reshape(Vt * Vb, C)[:V]

    return dh, de.astype(emb.dtype), None


_xent_core.defvjp(_xent_fwd_rule, _xent_bwd_rule)


def fused_lm_xent(hidden: jnp.ndarray, embedding: jnp.ndarray,
                  targets: jnp.ndarray, *, token_block: Optional[int] = None,
                  vocab_block: Optional[int] = None,
                  ignore_index: Optional[int] = None,
                  z_loss: float = 0.0,
                  label_smoothing: float = 0.0,
                  interpret: Optional[bool] = None) -> jnp.ndarray:
    """Mean next-token NLL with logits never materialized in HBM.

    hidden [B, T, C] (or [N, C]) in the compute dtype, embedding [V, C]
    (the tied LM head), targets [B, T] (or [N]) int32. Differentiable in
    (hidden, embedding); the backward recomputes P tiles on the MXU.
    ``ignore_index`` (torch cross_entropy semantics, e.g. -100) drops
    those positions from the loss, the divisor, and both gradients.
    ``z_loss`` adds the PaLM-style ``z * logsumexp^2`` stabilizer per
    valid position (folded into the same kernels: the backward's P
    factor becomes ``1 + 2z*lse``). ``label_smoothing`` mixes the
    target with the uniform distribution (the backward subtracts the
    smoothed one-hot ``(1-eps)*onehot + eps/V``; the forward's uniform
    term rides a third per-row accumulator).
    """
    if interpret is None:
        from . import default_interpret
        interpret = default_interpret()
    h2 = hidden.reshape(-1, hidden.shape[-1])
    t1 = targets.reshape(-1).astype(jnp.int32)
    N, C = h2.shape
    if token_block is None:
        # grid-step fixed costs dominate when the per-step matmul is small
        # (Tb*Vb*C MACs): widen token tiles at narrow models. The VMEM
        # budget (h tile + f32 dh accumulator + double-buffered emb tiles)
        # caps Tb at 256 for C ~ 2048.
        token_block = 512 if C <= 1024 else 256
    if vocab_block is None:
        # prefer a lane-aligned tile that DIVIDES V: the pad path copies
        # the whole [V, C] embedding (fwd + both bwd passes) just to add
        # the tail rows. 50304 (gpt2 padded vocab) -> 384; 32000 -> 256.
        V = embedding.shape[0]
        vocab_block = next((c for c in (512, 384, 256, 128)
                            if V % c == 0), 512)
    Tb = min(token_block, _round_up(N, 8))
    N2 = _round_up(N, Tb)
    if N2 != N:
        h2 = jnp.pad(h2, ((0, N2 - N), (0, 0)))
        t1 = jnp.pad(t1, (0, N2 - N))
    # NEGATIVE ids (e.g. -100) need no clamping: the kernels never index
    # with targets — the one-hot compare simply never hits, and the
    # validity masks zero those rows' loss and gradients. Positive
    # out-of-range ids (corrupt labels) are likewise excluded from loss,
    # gradients, and the divisor (chunked_lm_xent semantics — torch
    # cross_entropy would raise; silently training against a clamped id
    # is the one behavior that is never right).
    total = _xent_core(h2, embedding, t1, N, Tb, vocab_block,
                       ignore_index, float(z_loss),
                       float(label_smoothing), interpret)
    tflat = targets.reshape(-1)
    valid = (tflat >= 0) & (tflat < embedding.shape[0])
    if ignore_index is not None:
        valid &= tflat != ignore_index
    return total / jnp.maximum(valid.sum(), 1)


def sharded_fused_lm_xent(hidden: jnp.ndarray, embedding: jnp.ndarray,
                          targets: jnp.ndarray, mesh,
                          batch_axes=("data", "data_inner"),
                          **kwargs) -> jnp.ndarray:
    """``fused_lm_xent`` under ``shard_map``: token rows shard over the
    data axes, the embedding stays replicated, and the loss reduces via
    ``psum`` of per-shard (sum, count) pairs — the same wrapping
    ``sharded_flash_attention`` gives the attention kernel (Pallas custom
    calls carry no GSPMD rules, so a multi-device jit would otherwise
    all-gather the hidden states around the kernel). The embedding
    cotangent is psum'd by shard_map's transpose of the replicated input.

    Falls back to the unsharded kernel when no batch axis divides the
    leading dim.
    """
    from ...utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    ignore = kwargs.get("ignore_index")
    h3 = hidden if hidden.ndim == 3 else hidden[None]
    t2 = targets if targets.ndim == 2 else targets[None]
    bat = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1)
    bsz = 1
    for a in bat:
        bsz *= mesh.shape[a]
    if not bat or h3.shape[0] % bsz:
        return fused_lm_xent(hidden, embedding, targets, **kwargs)

    def local(h_, e_, t_):
        # per-shard sum + RAW valid count; the global mean is the psum
        # ratio with the zero-guard applied AFTER the psum — clamping
        # per shard would inflate the divisor whenever one shard's rows
        # are all ignored (loc * max(raw, 1) recovers the exact
        # per-shard total either way: loc is 0 when raw is 0). The count
        # must mirror fused_lm_xent's own divisor: in-range, non-ignored.
        loc = fused_lm_xent(h_, e_, t_, **kwargs)
        vld = (t_ >= 0) & (t_ < e_.shape[0])
        if ignore is not None:
            vld &= t_ != ignore
        raw = vld.sum().astype(jnp.float32)
        total = jax.lax.psum(loc * jnp.maximum(raw, 1.0), bat)
        count = jax.lax.psum(raw, bat)
        return total / jnp.maximum(count, 1.0)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(bat), P(), P(bat)),
        out_specs=P(),
        check_vma=False,
    )(h3, embedding, t2)
