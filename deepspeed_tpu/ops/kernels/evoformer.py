"""Evoformer (triangle) attention — Pallas TPU forward kernel.

Kernel-level answer to the reference's ``csrc/deepspeed4science/
evoformer_attn/`` (14.9k LoC of CUTLASS fwd+bwd): flash-style online
softmax over [B, N, S, H, D] MSA/triangle attention with the two
canonical additive bias layouts fused into the score tiles —

  mask bias  [B, N, 1, 1, Sk]  (per-row key mask, broadcast over H, Sq)
  pair bias  [B, 1, H, Sq, Sk] (triangle bias, broadcast over N)

so the [B, N, H, Sq, Sk] score tensor never exists in HBM (the reason
the reference kernel exists — AlphaFold-scale shapes blow memory).

Backward is recompute-based (VERDICT r4 #9): a ``jax.custom_vjp`` whose
bwd replays the chunked jnp path (``ops.evoformer_attn``) under the same
numerics — one extra fwd's FLOPs, zero extra resident memory, and the
kernel stays fwd-only (the CUTLASS bwd's 10k LoC is exactly what remat
deletes on TPU).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from ...utils.jax_compat import tpu_compiler_params as _compat_tpu_compiler_params

_NEG_INF = float("-inf")


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _fwd_kernel(q_ref, k_ref, v_ref, mb_ref, pb_ref, o_ref,
                m_scr, l_scr, acc_scr, *, scale, block_q, block_k, kv_len):
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if mb_ref is not None:
        s = s + mb_ref[...].astype(jnp.float32)        # [1, Tk] row bias
    if pb_ref is not None:
        s = s + pb_ref[0, 0].astype(jnp.float32)       # [Tq, Tk] pair bias
    col = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    s = jnp.where(col < kv_len, s, _NEG_INF)

    m_prev, l_prev = m_scr[:], l_scr[:]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_next = jnp.maximum(m_prev, m_cur)
    # fully-masked rows (mask/pair bias -inf across every key) keep the
    # running max at -inf; clamping to a finite floor stops alpha from
    # becoming exp(-inf - -inf) = NaN while exp(-inf - floor) stays 0, so
    # the l==0 guard below sees clean zeros and emits 0 output rows
    m_next = jnp.maximum(m_next, -1e30)
    alpha = jnp.exp(m_prev - m_next)
    p = jnp.exp(s - m_next[:, :1])
    l_scr[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    m_scr[:] = m_next
    v = v_ref[0, 0]
    pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    acc_scr[:] = acc_scr[:] * alpha[:, :1] + pv

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)           # fully-masked rows
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _evo_fwd_pallas(q4, k4, v4, mb2, pb4, *, n_rows, scale, block_q,
                    block_k, interpret):
    """q4/k4/v4: [BN, H, S, D]; mb2: [BN, Sk] or None; pb4: [B, H, Sq, Sk]
    or None (B = BN // n_rows)."""
    BN, H, Sq, D = q4.shape
    Sk = k4.shape[2]
    Tq = min(block_q, _round_up(Sq, 8))
    Tk = min(block_k, _round_up(Sk, 128))
    Sq2, Sk2 = _round_up(Sq, Tq), _round_up(Sk, Tk)
    if Sq2 != Sq:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, Sq2 - Sq), (0, 0)))
    if Sk2 != Sk:
        k4 = jnp.pad(k4, ((0, 0), (0, 0), (0, Sk2 - Sk), (0, 0)))
        v4 = jnp.pad(v4, ((0, 0), (0, 0), (0, Sk2 - Sk), (0, 0)))
        if mb2 is not None:
            mb2 = jnp.pad(mb2, ((0, 0), (0, Sk2 - Sk)))
    if pb4 is not None and (Sq2 != Sq or Sk2 != Sk):
        pb4 = jnp.pad(pb4, ((0, 0), (0, 0), (0, Sq2 - Sq), (0, Sk2 - Sk)))
    nq, nk = Sq2 // Tq, Sk2 // Tk

    in_specs = [
        pl.BlockSpec((1, 1, Tq, D), lambda bn, h, qi, ki: (bn, h, qi, 0)),
        pl.BlockSpec((1, 1, Tk, D), lambda bn, h, qi, ki: (bn, h, ki, 0)),
        pl.BlockSpec((1, 1, Tk, D), lambda bn, h, qi, ki: (bn, h, ki, 0)),
    ]
    args = [q4, k4, v4]
    if mb2 is not None:
        in_specs.append(
            pl.BlockSpec((1, Tk), lambda bn, h, qi, ki: (bn, ki)))
        args.append(mb2)
    if pb4 is not None:
        in_specs.append(pl.BlockSpec(
            (1, 1, Tq, Tk),
            lambda bn, h, qi, ki: (bn // n_rows, h, qi, ki)))
        args.append(pb4)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=Tq, block_k=Tk, kv_len=Sk)
    if mb2 is None or pb4 is None:
        # bind absent refs as None positionally
        base = kernel

        def kernel(q_ref, k_ref, v_ref, *rest):
            refs = list(rest[:-4])       # bias refs before outputs/scratch
            out_scr = rest[-4:]
            mb_ref = refs.pop(0) if mb2 is not None else None
            pb_ref = refs.pop(0) if pb4 is not None else None
            return base(q_ref, k_ref, v_ref, mb_ref, pb_ref, *out_scr)

    out = pl.pallas_call(
        kernel,
        grid=(BN, H, nq, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, Tq, D),
                               lambda bn, h, qi, ki: (bn, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BN, H, Sq2, D), q4.dtype),
        scratch_shapes=[pltpu.VMEM((Tq, 128), jnp.float32),
                        pltpu.VMEM((Tq, 128), jnp.float32),
                        pltpu.VMEM((Tq, D), jnp.float32)],
        compiler_params=_compat_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*args)
    return out[:, :, :Sq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _evo_core(q, k, v, mask_bias, pair_bias, n_rows, scale, block_q,
              block_k, interpret):
    """[B, N, S, H, D] evoformer attention, Pallas fwd / recompute bwd.
    mask_bias [B, N, Sk] or None; pair_bias [B, H, Sq, Sk] or None."""
    B, N, Sq, H, D = q.shape
    to4 = lambda t: t.reshape(B * N, t.shape[2], H, D).swapaxes(1, 2)
    mb2 = (None if mask_bias is None
           else mask_bias.reshape(B * N, mask_bias.shape[-1]))
    o4 = _evo_fwd_pallas(to4(q), to4(k), to4(v), mb2, pair_bias,
                         n_rows=N, scale=scale, block_q=block_q,
                         block_k=block_k, interpret=interpret)
    return o4.swapaxes(1, 2).reshape(B, N, Sq, H, D)


def _evo_ref(q, k, v, mask_bias, pair_bias, scale):
    """Chunked jnp reference (identical math) used for the backward."""
    from ..evoformer_attn import DS4Sci_EvoformerAttention
    B, N, _, H, _ = q.shape
    biases = []
    if mask_bias is not None:
        biases.append(mask_bias[:, :, None, None, :])
    if pair_bias is not None:
        biases.append(pair_bias[:, None])
    return DS4Sci_EvoformerAttention(q, k, v, biases, use_kernel=False)


def _evo_fwd_rule(q, k, v, mask_bias, pair_bias, n_rows, scale, block_q,
                  block_k, interpret):
    out = _evo_core(q, k, v, mask_bias, pair_bias, n_rows, scale, block_q,
                    block_k, interpret)
    return out, (q, k, v, mask_bias, pair_bias)


def _evo_bwd_rule(n_rows, scale, block_q, block_k, interpret, res, g):
    q, k, v, mask_bias, pair_bias = res
    diff = (q, k, v) if mask_bias is None and pair_bias is None else \
        ((q, k, v, pair_bias) if mask_bias is None else
         ((q, k, v, mask_bias) if pair_bias is None else
          (q, k, v, mask_bias, pair_bias)))

    def ref(*args):
        qq, kk, vv = args[:3]
        rest = list(args[3:])
        mb = rest.pop(0) if mask_bias is not None else None
        pb = rest.pop(0) if pair_bias is not None else None
        return _evo_ref(qq, kk, vv, mb, pb, scale)

    _, vjp = jax.vjp(ref, *diff)
    grads = list(vjp(g))
    gq, gk, gv = grads[:3]
    rest = grads[3:]
    gmb = rest.pop(0) if mask_bias is not None else None
    gpb = rest.pop(0) if pair_bias is not None else None
    return gq, gk, gv, gmb, gpb


_evo_core.defvjp(_evo_fwd_rule, _evo_bwd_rule)


def evoformer_flash(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    mask_bias: Optional[jnp.ndarray] = None,
                    pair_bias: Optional[jnp.ndarray] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused evoformer attention: q/k/v [B, N, S, H, D]; ``mask_bias``
    [B, N, Sk] (additive, the reference's [B, N, 1, 1, Sk] squeezed) and
    ``pair_bias`` [B, H, Sq, Sk] (the [B, 1, H, Sq, Sk] squeezed).
    Differentiable; backward recomputes through the chunked jnp path."""
    if interpret is None:
        from . import default_interpret
        interpret = default_interpret()
    B, N, Sq, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    return _evo_core(q, k, v, mask_bias, pair_bias, N, scale, block_q,
                     block_k, interpret)
