"""Fused AdamW update kernel — the multi-tensor-apply analogue.

Capability parity with the reference's ``FusedAdam``
(``csrc/adam/multi_tensor_adam.cu``, SURVEY.md §2.6): one kernel pass updates
param/m/v in place (``input_output_aliases``) from a flat f32 buffer, with
bias correction and decoupled weight decay. The engine's default optimizer
path is optax (XLA already emits one fused loop per dtype); this kernel is
the explicit-VMEM alternative for flat-buffer optimizer paths (e.g. offloaded
ZeRO partitions), validated against optax.adamw in the kernel tests. It is
not yet wired into the ``optimizer.type`` dispatch.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_ROW = 8


def _adamw_kernel(hyper_ref, p_ref, g_ref, m_ref, v_ref,
                  po_ref, mo_ref, vo_ref):
    lr = hyper_ref[0]
    b1 = hyper_ref[1]
    b2 = hyper_ref[2]
    eps = hyper_ref[3]
    wd = hyper_ref[4]
    c1 = hyper_ref[5]          # 1 / (1 - b1^t)
    c2 = hyper_ref[6]          # 1 / (1 - b2^t)

    g = g_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    mo_ref[:] = m
    vo_ref[:] = v
    update = (m * c1) / (jnp.sqrt(v * c2) + eps)
    p = p_ref[:]
    po_ref[:] = p - lr * (update + wd * p)


def fused_adamw_update(
    p: jnp.ndarray, g: jnp.ndarray, m: jnp.ndarray, v: jnp.ndarray,
    step: jnp.ndarray, *, lr, b1: float = 0.9, b2: float = 0.999,
    eps: float = 1e-8, weight_decay: float = 0.0,
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One fused AdamW step over flat f32 buffers.

    Args:
      p, m, v: flat f32 param / first-moment / second-moment buffers.
      g: flat gradient buffer (any float dtype; cast to f32 in-kernel).
      step: 1-based step count (traced scalar ok) for bias correction.
      lr: learning rate (float or traced scalar).
    Returns: (new_p, new_m, new_v).
    """
    if interpret is None:
        from . import default_interpret
        interpret = default_interpret()
    n = p.shape[0]
    width = _ROW * _LANES
    pad = (-n) % width
    if pad:
        p, g, m, v = (jnp.pad(x, (0, pad)) for x in (p, g, m, v))
    rows = (n + pad) // _LANES
    p2, g2, m2, v2 = (x.reshape(rows, _LANES) for x in (p, g, m, v))

    t = jnp.asarray(step, jnp.float32)
    hyper = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(b1, jnp.float32),
        jnp.asarray(b2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        1.0 / (1.0 - jnp.asarray(b1, jnp.float32) ** t),
        1.0 / (1.0 - jnp.asarray(b2, jnp.float32) ** t),
        jnp.float32(0.0),
    ])

    br = _ROW
    for cand in (512, 256, 128, 64, 32, 16, 8):
        if rows % cand == 0:
            br = cand
            break
    row = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    po, mo, vo = pl.pallas_call(
        _adamw_kernel,
        grid=(rows // br,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  row, row, row, row],
        out_specs=[row, row, row],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)] * 3,
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(hyper, p2, g2, m2, v2)
    po, mo, vo = (x.reshape(-1)[:n] for x in (po, mo, vo))
    return po, mo, vo


def adamw_reference(p, g, m, v, step, *, lr, b1=0.9, b2=0.999, eps=1e-8,
                    weight_decay=0.0):
    """jnp reference for the parity tests."""
    g = g.astype(jnp.float32)
    t = jnp.asarray(step, jnp.float32)
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    mhat = m / (1 - b1 ** t)
    vhat = v / (1 - b2 ** t)
    p = p - lr * (mhat / (jnp.sqrt(vhat) + eps) + weight_decay * p)
    return p, m, v
