"""Fused LayerNorm / RMSNorm Pallas kernels.

Capability parity with the reference's norm kernels
(``csrc/transformer/inference/csrc/layer_norm.cu`` / ``rms_norm.cu``,
SURVEY.md §2.6): a single VMEM pass computes statistics and the normalized
output, keeping the row resident on-chip. Backward is hand-derived jnp (one
XLA fusion) via ``jax.custom_vjp`` — on TPU the bwd is bandwidth-bound either
way, so the win is the explicit fwd fusion plus f32 statistics under bf16 IO.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _row_block(n_rows: int) -> int:
    for cand in (256, 128, 64, 32, 16, 8):
        if n_rows % cand == 0:
            return cand
    return n_rows


# ---------------------------------------------------------------------------
# RMSNorm
# ---------------------------------------------------------------------------


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_fwd_pallas(x2d, w, eps, interpret):
    rows, hidden = x2d.shape
    br = _row_block(rows)
    return pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((br, hidden), lambda i: (i, 0)),
                  pl.BlockSpec((hidden,), lambda i: (0,))],
        out_specs=pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        interpret=interpret,
    )(x2d, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms(x2d, w, eps, interpret):
    return _rms_fwd_pallas(x2d, w, eps, interpret)


def _rms_fwd(x2d, w, eps, interpret):
    return _rms_fwd_pallas(x2d, w, eps, interpret), (x2d, w)


def _rms_bwd(eps, interpret, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    xhat = xf * rstd
    dw = jnp.sum(gf * xhat, axis=0).astype(w.dtype)
    gw = gf * wf
    dx = rstd * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    return dx.astype(x.dtype), dw


_rms.defvjp(_rms_fwd, _rms_bwd)


def fused_rms_norm(x: jnp.ndarray, weight: jnp.ndarray, *, eps: float = 1e-6,
                   interpret: Optional[bool] = None) -> jnp.ndarray:
    """RMSNorm over the last axis; f32 statistics regardless of input dtype."""
    if interpret is None:
        from . import default_interpret
        interpret = default_interpret()
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    return _rms(x2d, weight, float(eps), interpret).reshape(shape)


# ---------------------------------------------------------------------------
# LayerNorm
# ---------------------------------------------------------------------------


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_fwd_pallas(x2d, w, b, eps, interpret):
    rows, hidden = x2d.shape
    br = _row_block(rows)
    return pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((br, hidden), lambda i: (i, 0)),
                  pl.BlockSpec((hidden,), lambda i: (0,)),
                  pl.BlockSpec((hidden,), lambda i: (0,))],
        out_specs=pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        interpret=interpret,
    )(x2d, w, b)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln(x2d, w, b, eps, interpret):
    return _ln_fwd_pallas(x2d, w, b, eps, interpret)


def _ln_fwd(x2d, w, b, eps, interpret):
    return _ln_fwd_pallas(x2d, w, b, eps, interpret), (x2d, w)


def _ln_bwd(eps, interpret, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    rstd = jax.lax.rsqrt(jnp.mean(xc * xc, axis=-1, keepdims=True) + eps)
    xhat = xc * rstd
    dw = jnp.sum(gf * xhat, axis=0).astype(w.dtype)
    db = jnp.sum(gf, axis=0).astype(w.dtype)
    gw = gf * wf
    dx = rstd * (gw - jnp.mean(gw, axis=-1, keepdims=True)
                 - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    return dx.astype(x.dtype), dw, db


_ln.defvjp(_ln_fwd, _ln_bwd)


def fused_layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
                     *, eps: float = 1e-5,
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """LayerNorm over the last axis; f32 statistics regardless of input dtype."""
    if interpret is None:
        from . import default_interpret
        interpret = default_interpret()
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    return _ln(x2d, weight, bias, float(eps), interpret).reshape(shape)
