"""Pallas TPU kernels — the analogue of the reference's ``csrc/`` native op
families (SURVEY.md §2.6): fused attention (``csrc/transformer/``), fused
optimizers (``csrc/adam``, ``csrc/lamb``, ``csrc/lion``), group quantization
(``csrc/quantization/``), and fused norms (``csrc/transformer/inference``
layer_norm/rms_norm kernels).

Every kernel ships with a pure-jnp reference path. Dispatch: compiled Pallas on
TPU, interpreter/jnp elsewhere (so the CPU test mesh exercises identical code).
"""

import jax


def default_interpret() -> bool:
    """Pallas kernels compile only on TPU; interpret elsewhere (tests)."""
    return jax.default_backend() != "tpu"


from .flash_attention import (  # noqa: E402,F401
    flash_attention,
    flash_attention_sparse,
    sharded_flash_attention,
)
from .paged_attention import flash_paged_attention  # noqa: E402,F401
from .normalization import fused_layer_norm, fused_rms_norm  # noqa: E402,F401
from .quantization import (  # noqa: E402,F401
    dequantize_blockwise,
    quant_dequant,
    quantize_blockwise,
)
from .fused_optimizer import fused_adamw_update  # noqa: E402,F401
from .fused_xent import fused_lm_xent  # noqa: E402,F401
from .evoformer import evoformer_flash  # noqa: E402,F401
from .fp6_gemm import (  # noqa: E402,F401
    Fp6GemmWeight,
    fp6_gemm_pack,
    fp6_gemm_unpack,
    fp6_matmul,
)
