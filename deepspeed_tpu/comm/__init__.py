"""deepspeed_tpu.comm — the communication facade.

TPU-native replacement for ``deepspeed.comm`` (reference ``comm/comm.py``):
the reference wraps ``torch.distributed`` with a backend zoo (NCCL/gloo/
oneCCL/shm) and ~40 cached process groups; here there is ONE ``jax.sharding``
mesh and the collectives are ``jax.lax`` primitives placed by XLA over
ICI/DCN. What this package keeps from the reference's design:

- ``init_distributed`` (reference ``comm/comm.py:619``) — multi-host
  bring-up: env/MPI/SLURM rank discovery feeding
  ``jax.distributed.initialize``.
- a collective API with the reference's names (``all_reduce``,
  ``all_gather``, ``reduce_scatter``, ``all_to_all_single``, ``broadcast``,
  ``barrier``) usable inside ``shard_map``/``pjit`` bodies (axis-name based).
- comms instrumentation parity: every wrapped collective records message
  volume into :class:`CommsLogger` (reference ``utils/comms_logging.py:67``
  fed by ``@timed_op``), with ``log_summary()`` producing the same
  size-bucketed table. Under jit, per-op wall time comes from the jax
  profiler rather than host timers; at trace time we record volume + count.
"""

from .comm import (  # noqa: F401
    TP_OVERLAP_MODES,
    all_gather,
    all_reduce,
    all_to_all_single,
    barrier,
    broadcast,
    configure,
    decomposed_all_reduce,
    get_local_rank,
    get_rank,
    get_world_size,
    init_distributed,
    inference_all_reduce,
    is_initialized,
    log_summary,
    mpi_discovery,
    overlap_all_reduce,
    ppermute,
    reduce_scatter,
    resolve_tp_overlap,
    ring_all_gather,
    ring_reduce_scatter,
)
from .comms_logging import CommsLogger, get_comms_logger  # noqa: F401
