"""Collective facade + multi-host initialization.

Reference parity map (``deepspeed/comm/comm.py``):

- ``init_distributed`` (:619)            → :func:`init_distributed` (env /
  MPI / SLURM discovery → ``jax.distributed.initialize``; SPMD = one process
  per HOST, so "rank" here is the process index, not a per-chip rank).
- ``mpi_discovery`` (:688)               → :func:`mpi_discovery` (OMPI env).
- collectives (:222-521)                 → axis-name collectives for use
  inside ``shard_map`` / ``pjit`` bodies. The reference's eager tensor ops
  become ``jax.lax`` primitives; XLA schedules/overlaps them (the reference
  hand-manages CUDA streams for the same effect).
- ``@timed_op`` comms logging (:101)     → trace-time volume recording into
  :class:`~.comms_logging.CommsLogger`; pair with the jax profiler for
  wall-clock per-op timing.
- ``inference_all_reduce`` (:500)        → same as all_reduce (XLA picks the
  right ICI algorithm; no shm special case needed on TPU).

There is deliberately no Backend ABC / process-group zoo: named mesh axes
(``parallel/topology.py``) are the group registry.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.logging import log_dist, logger
from .comms_logging import get_comms_logger, note_collective

ReduceOp = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin}

_INITIALIZED = False


# --------------------------------------------------------------------------- #
# process bring-up (multi-host)
# --------------------------------------------------------------------------- #

def mpi_discovery() -> Optional[dict]:
    """Discover (rank, world_size, coordinator) from OpenMPI/MPICH env vars,
    mirroring reference ``comm/comm.py:688`` (which uses mpi4py; env vars
    avoid the dependency)."""
    for rank_var, size_var in (
            ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),
            ("PMI_RANK", "PMI_SIZE"),
            ("SLURM_PROCID", "SLURM_NTASKS")):
        if rank_var in os.environ and size_var in os.environ:
            return {
                "process_id": int(os.environ[rank_var]),
                "num_processes": int(os.environ[size_var]),
                "coordinator_address": os.environ.get("MASTER_ADDR"),
                "coordinator_port": int(os.environ.get("MASTER_PORT", 0)) or None,
            }
    return None


def init_distributed(dist_backend: str = "xla",
                     auto_mpi_discovery: bool = True,
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     timeout=None,
                     init_method=None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Initialize multi-host JAX. Single-host (the common case, and anything
    already initialized) is a no-op. Env protocol matches the launcher
    (``launcher/``): DSTPU_COORDINATOR / DSTPU_NUM_PROCESSES / DSTPU_PROCESS_ID,
    falling back to MPI/SLURM discovery."""
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator_address = coordinator_address or os.environ.get("DSTPU_COORDINATOR")
    if num_processes is None and "DSTPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["DSTPU_NUM_PROCESSES"])
    if process_id is None and "DSTPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["DSTPU_PROCESS_ID"])
    if num_processes is None and world_size > 0:
        num_processes = world_size
    if process_id is None and rank >= 0:
        process_id = rank
    if (num_processes is None or process_id is None) and auto_mpi_discovery:
        found = mpi_discovery()
        if found:
            process_id = found["process_id"] if process_id is None else process_id
            num_processes = (found["num_processes"]
                             if num_processes is None else num_processes)
            coordinator_address = coordinator_address or (
                f"{found['coordinator_address']}:{found['coordinator_port']}"
                if found["coordinator_address"] and found["coordinator_port"]
                else None)
    if num_processes and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        log_dist(
            f"jax.distributed initialized: process {process_id}/{num_processes} "
            f"coordinator={coordinator_address}", ranks=[0])
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED


def get_world_size(group=None) -> int:
    """Number of participating devices (chips), like the reference's world
    size is the number of GPU ranks."""
    return jax.device_count()


def get_rank(group=None) -> int:
    """Host process index (SPMD: one process per host)."""
    return jax.process_index()


def get_local_rank() -> int:
    return 0  # one process drives all local chips under SPMD


def configure(config=None, enabled=None, prof_all=None, prof_ops=None,
              verbose=None, debug=None) -> None:
    """Wire the comms logger from config (reference ``comm/comm.py:72``)."""
    kw = {}
    if config is not None:
        section = getattr(config, "comms_logger", None) or {}
        if isinstance(section, dict):
            kw = {k: section.get(k) for k in
                  ("enabled", "prof_all", "prof_ops", "verbose", "debug")}
        else:
            kw = {k: getattr(section, k, None) for k in
                  ("enabled", "prof_all", "prof_ops", "verbose", "debug")}
    for k, v in (("enabled", enabled), ("prof_all", prof_all),
                 ("prof_ops", prof_ops), ("verbose", verbose),
                 ("debug", debug)):
        if v is not None:
            kw[k] = v
    get_comms_logger().configure(**{k: v for k, v in kw.items() if v is not None})


# --------------------------------------------------------------------------- #
# collectives (axis-name based; use inside shard_map / with pjit axis ctx)
# --------------------------------------------------------------------------- #

def _axis_size(axis_name) -> int:
    from ..utils.jax_compat import axis_size
    try:
        return axis_size(axis_name)
    except NameError:
        return 1


def _record(op: str, x, axis_name, log_name=None, scale: float = 1.0):
    n = _axis_size(axis_name)
    nbytes = int(np.prod(jnp.shape(x)) * jnp.result_type(x).itemsize * scale)
    # unconditional: the resilience watchdog names this collective when a
    # step stalls (docs/resilience.md); also the 'collective' fault site
    note_collective(op, nbytes, n, log_name=log_name)
    from ..resilience.fault_injection import get_fault_injector
    get_fault_injector().maybe_fire("collective")
    get_comms_logger().append(op, nbytes, n, log_name=log_name)


def all_reduce(x, op: str = "sum", axis_name="data", log_name=None):
    """psum/pmax/pmin over a mesh axis. ``op='avg'`` matches the reference's
    ReduceOp.AVG."""
    _record("all_reduce", x, axis_name, log_name)
    if op == "avg":
        return lax.pmean(x, axis_name)
    return ReduceOp[op](x, axis_name)


def inference_all_reduce(x, axis_name="model", log_name=None):
    _record("inference_all_reduce", x, axis_name, log_name)
    return lax.psum(x, axis_name)


def all_gather(x, axis_name="data", axis: int = 0, tiled: bool = True,
               log_name=None):
    """Gather shards along ``axis`` from every rank of the mesh axis
    (reference ``all_gather_into_tensor``, comm.py:296)."""
    _record("all_gather", x, axis_name, log_name)
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, op: str = "sum", axis_name="data", axis: int = 0,
                   log_name=None):
    """Reduce across the axis then keep this rank's shard (reference
    ``reduce_scatter_tensor``, comm.py:257)."""
    _record("reduce_scatter", x, axis_name, log_name)
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all_single(x, axis_name="seq", split_axis: int = 0,
                      concat_axis: int = 0, log_name=None):
    """Scatter ``split_axis`` / gather ``concat_axis`` over the mesh axis
    (reference ``all_to_all_single``, comm.py:222 — the Ulysses/MoE primitive)."""
    _record("all_to_all_single", x, axis_name, log_name)
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def broadcast(x, src: int = 0, axis_name="data", log_name=None):
    """Every rank gets rank ``src``'s value (reference comm.py:361). Inside
    SPMD this is a select+psum."""
    _record("broadcast", x, axis_name, log_name)
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def ppermute(x, perm, axis_name="pipe", log_name=None):
    """Neighbor exchange (the reference's pipeline p2p send/recv pairs,
    ``runtime/pipe/p2p.py`` — one fused collective here)."""
    _record("ppermute", x, axis_name, log_name)
    return lax.ppermute(x, axis_name, perm)


def barrier(group=None):
    """Host-level barrier: synchronize all processes (reference comm.py:421).
    Inside a compiled program there is nothing to do — XLA orders collectives;
    at host level we round-trip a tiny psum through all devices."""
    if jax.process_count() == 1:
        return
    # a zero-sized allreduce across all devices forces a sync point
    x = jnp.zeros((jax.device_count(),))
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.asarray(jax.devices()), ("all",))
    y = jax.jit(lambda a: a.sum(),
                in_shardings=NamedSharding(mesh, P("all")))(x)
    jax.block_until_ready(y)


def log_summary(show_straggler: bool = False) -> str:
    """Print the comms table (reference ``dist.log_summary``, comm.py:422)."""
    return get_comms_logger().log_summary(show_straggler)
