"""Collective facade + multi-host initialization.

Reference parity map (``deepspeed/comm/comm.py``):

- ``init_distributed`` (:619)            → :func:`init_distributed` (env /
  MPI / SLURM discovery → ``jax.distributed.initialize``; SPMD = one process
  per HOST, so "rank" here is the process index, not a per-chip rank).
- ``mpi_discovery`` (:688)               → :func:`mpi_discovery` (OMPI env).
- collectives (:222-521)                 → axis-name collectives for use
  inside ``shard_map`` / ``pjit`` bodies. The reference's eager tensor ops
  become ``jax.lax`` primitives; XLA schedules/overlaps them (the reference
  hand-manages CUDA streams for the same effect).
- ``@timed_op`` comms logging (:101)     → trace-time volume recording into
  :class:`~.comms_logging.CommsLogger`; pair with the jax profiler for
  wall-clock per-op timing.
- ``inference_all_reduce`` (:500)        → same as all_reduce (XLA picks the
  right ICI algorithm; no shm special case needed on TPU).

There is deliberately no Backend ABC / process-group zoo: named mesh axes
(``parallel/topology.py``) are the group registry.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.logging import log_dist, logger
from .comms_logging import get_comms_logger, note_collective

ReduceOp = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin}

_INITIALIZED = False


# --------------------------------------------------------------------------- #
# process bring-up (multi-host)
# --------------------------------------------------------------------------- #

def mpi_discovery() -> Optional[dict]:
    """Discover (rank, world_size, coordinator) from OpenMPI/MPICH env vars,
    mirroring reference ``comm/comm.py:688`` (which uses mpi4py; env vars
    avoid the dependency)."""
    for rank_var, size_var in (
            ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),
            ("PMI_RANK", "PMI_SIZE"),
            ("SLURM_PROCID", "SLURM_NTASKS")):
        if rank_var in os.environ and size_var in os.environ:
            return {
                "process_id": int(os.environ[rank_var]),
                "num_processes": int(os.environ[size_var]),
                "coordinator_address": os.environ.get("MASTER_ADDR"),
                "coordinator_port": int(os.environ.get("MASTER_PORT", 0)) or None,
            }
    return None


def init_distributed(dist_backend: str = "xla",
                     auto_mpi_discovery: bool = True,
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     timeout=None,
                     init_method=None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Initialize multi-host JAX. Single-host (the common case, and anything
    already initialized) is a no-op. Env protocol matches the launcher
    (``launcher/``): DSTPU_COORDINATOR / DSTPU_NUM_PROCESSES / DSTPU_PROCESS_ID,
    falling back to MPI/SLURM discovery."""
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator_address = coordinator_address or os.environ.get("DSTPU_COORDINATOR")
    if num_processes is None and "DSTPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["DSTPU_NUM_PROCESSES"])
    if process_id is None and "DSTPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["DSTPU_PROCESS_ID"])
    if num_processes is None and world_size > 0:
        num_processes = world_size
    if process_id is None and rank >= 0:
        process_id = rank
    if (num_processes is None or process_id is None) and auto_mpi_discovery:
        found = mpi_discovery()
        if found:
            process_id = found["process_id"] if process_id is None else process_id
            num_processes = (found["num_processes"]
                             if num_processes is None else num_processes)
            coordinator_address = coordinator_address or (
                f"{found['coordinator_address']}:{found['coordinator_port']}"
                if found["coordinator_address"] and found["coordinator_port"]
                else None)
    if num_processes and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        log_dist(
            f"jax.distributed initialized: process {process_id}/{num_processes} "
            f"coordinator={coordinator_address}", ranks=[0])
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED


def get_world_size(group=None) -> int:
    """Number of participating devices (chips), like the reference's world
    size is the number of GPU ranks."""
    return jax.device_count()


def get_rank(group=None) -> int:
    """Host process index (SPMD: one process per host)."""
    return jax.process_index()


def get_local_rank() -> int:
    return 0  # one process drives all local chips under SPMD


def configure(config=None, enabled=None, prof_all=None, prof_ops=None,
              verbose=None, debug=None) -> None:
    """Wire the comms logger from config (reference ``comm/comm.py:72``)."""
    kw = {}
    if config is not None:
        section = getattr(config, "comms_logger", None) or {}
        if isinstance(section, dict):
            kw = {k: section.get(k) for k in
                  ("enabled", "prof_all", "prof_ops", "verbose", "debug")}
        else:
            kw = {k: getattr(section, k, None) for k in
                  ("enabled", "prof_all", "prof_ops", "verbose", "debug")}
    for k, v in (("enabled", enabled), ("prof_all", prof_all),
                 ("prof_ops", prof_ops), ("verbose", verbose),
                 ("debug", debug)):
        if v is not None:
            kw[k] = v
    get_comms_logger().configure(**{k: v for k, v in kw.items() if v is not None})


# --------------------------------------------------------------------------- #
# collectives (axis-name based; use inside shard_map / with pjit axis ctx)
# --------------------------------------------------------------------------- #

def _axis_size(axis_name) -> int:
    from ..utils.jax_compat import axis_size
    try:
        return axis_size(axis_name)
    except NameError:
        return 1


def _record(op: str, x, axis_name, log_name=None, scale: float = 1.0):
    n = _axis_size(axis_name)
    nbytes = int(np.prod(jnp.shape(x)) * jnp.result_type(x).itemsize * scale)
    # unconditional: the resilience watchdog names this collective when a
    # step stalls (docs/resilience.md); also the 'collective' fault site
    note_collective(op, nbytes, n, log_name=log_name)
    from ..resilience.fault_injection import get_fault_injector
    get_fault_injector().maybe_fire("collective")
    get_comms_logger().append(op, nbytes, n, log_name=log_name)
    # telemetry: traced-site counters keyed by the program auditor's
    # canonical kinds (docs/observability.md) — no-op with telemetry off
    from ..telemetry.registry import comm_counter
    comm_counter(op)


def all_reduce(x, op: str = "sum", axis_name="data", log_name=None):
    """psum/pmax/pmin over a mesh axis. ``op='avg'`` matches the reference's
    ReduceOp.AVG."""
    _record("all_reduce", x, axis_name, log_name)
    if op == "avg":
        return lax.pmean(x, axis_name)
    return ReduceOp[op](x, axis_name)


def inference_all_reduce(x, axis_name="model", log_name=None):
    _record("inference_all_reduce", x, axis_name, log_name)
    return lax.psum(x, axis_name)


def all_gather(x, axis_name="data", axis: int = 0, tiled: bool = True,
               log_name=None):
    """Gather shards along ``axis`` from every rank of the mesh axis
    (reference ``all_gather_into_tensor``, comm.py:296)."""
    _record("all_gather", x, axis_name, log_name)
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, op: str = "sum", axis_name="data", axis: int = 0,
                   log_name=None):
    """Reduce across the axis then keep this rank's shard (reference
    ``reduce_scatter_tensor``, comm.py:257)."""
    _record("reduce_scatter", x, axis_name, log_name)
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all_single(x, axis_name="seq", split_axis: int = 0,
                      concat_axis: int = 0, log_name=None):
    """Scatter ``split_axis`` / gather ``concat_axis`` over the mesh axis
    (reference ``all_to_all_single``, comm.py:222 — the Ulysses/MoE primitive)."""
    _record("all_to_all_single", x, axis_name, log_name)
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def broadcast(x, src: int = 0, axis_name="data", log_name=None):
    """Every rank gets rank ``src``'s value (reference comm.py:361). Inside
    SPMD this is a select+psum."""
    _record("broadcast", x, axis_name, log_name)
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def ppermute(x, perm, axis_name="pipe", log_name=None):
    """Neighbor exchange (the reference's pipeline p2p send/recv pairs,
    ``runtime/pipe/p2p.py`` — one fused collective here)."""
    _record("ppermute", x, axis_name, log_name)
    return lax.ppermute(x, axis_name, perm)


# --------------------------------------------------------------------------- #
# decomposed (overlappable) TP collectives — ISSUE 6
#
# A monolithic psum is one opaque XLA collective: it finishes before any
# consumer starts, so its latency sits exposed on the critical path. The
# builders below decompose the row-parallel TP all-reduce into nearest-
# neighbor ppermute ring steps (the T3/fused-computation-collective regime,
# arXiv:2401.16677 / 2305.06942): chunked reduce-scatter hops followed by
# all-gather hops, each an independent dataflow edge XLA can schedule under
# adjacent GEMMs. With ``quant_bits`` the wire payload rides int8 with
# per-chunk symmetric scales, quantized once per hop on the partial sums
# (EQuARX, arXiv:2506.17615) — compression composes with the overlap
# instead of being a separate monolithic gather.
#
# The hop implementations are module-level jitted functions on purpose:
# their pjit names ("ring_reduce_scatter" / "ring_all_gather") are the
# canonicalization anchor the program auditor uses to classify the hops as
# reduce_scatter / all_gather collectives (analysis/program_audit.py), and
# the jit cache keeps retracing off the program-build path.
# --------------------------------------------------------------------------- #

#: overlap schedule selected by ``resolve_tp_overlap`` / the engine knob
TP_OVERLAP_MODES = ("off", "rs_ag", "rs_ag_chunked")


def resolve_tp_overlap(mode: Optional[str] = None,
                       chunks: Optional[int] = None):
    """(mode, chunks) for the decomposed TP all-reduce, with env overrides:
    ``DSTPU_TP_OVERLAP`` = off | rs_ag | rs_ag_chunked[:k] (the operational
    kill-switch / force-on for any caller that does not thread a config),
    ``DSTPU_TP_OVERLAP_CHUNKS`` = k. ``chunks`` is meaningful only for
    rs_ag_chunked and collapses to 1 otherwise."""
    def _int(s, knob):
        try:
            return int(s)
        except ValueError:
            raise ValueError(
                f"{knob} chunk count must be an integer, got {s!r}") \
                from None

    env = os.environ.get("DSTPU_TP_OVERLAP")
    if env:
        head, _, k = env.partition(":")
        mode = head
        if k:
            chunks = _int(k, "DSTPU_TP_OVERLAP")
    env_c = os.environ.get("DSTPU_TP_OVERLAP_CHUNKS")
    if env_c:
        chunks = _int(env_c, "DSTPU_TP_OVERLAP_CHUNKS")
    mode = mode or "off"
    if mode not in TP_OVERLAP_MODES:
        raise ValueError(
            f"tp overlap mode must be one of {TP_OVERLAP_MODES}, got "
            f"{mode!r} (env DSTPU_TP_OVERLAP)")
    chunks = int(chunks) if chunks else 2
    if mode != "rs_ag_chunked":
        chunks = 1
    return mode, max(1, chunks)


def _quant_hop(x, bits: int):
    """Per-chunk symmetric quantization of one hop payload: the scale is
    per row OF THIS CHUNK (last dim = chunk width), not of the full
    activation row — an outlier poisons one chunk's scale, not the whole
    row (the EQuARX granularity claim)."""
    from ..ops.kernels.quantization import sym_quantize_rowwise
    return sym_quantize_rowwise(x, bits)


def _ring_reduce_scatter_impl(x, *, axis_name, tp, bits):
    """tp-1 ppermute hops reducing ``x``'s last dim into this chip's
    1/tp shard (chip r ends holding fully-summed chunk r). Each hop sends
    the running partial sum to the next ring neighbor; with ``bits`` the
    payload is quantized per hop (values int8 + per-chunk f32 scales)."""
    r = lax.axis_index(axis_name)
    xs = jnp.stack(jnp.split(x, tp, axis=-1))            # [tp, ..., Ec]
    perm = [(i, (i + 1) % tp) for i in range(tp)]

    def take(j):
        return lax.dynamic_index_in_dim(xs, j % tp, axis=0, keepdims=False)

    # the accumulating chunk index walks BACKWARD from (r-1): after hop s
    # chip r holds partials of chunk (r-1-s) mod tp, so after tp-1 hops it
    # holds its own chunk r, fully reduced
    acc = take(r - 1)
    for s in range(1, tp):
        if bits is None:
            acc = lax.ppermute(acc, axis_name, perm)
        else:
            q, scale = _quant_hop(acc, bits)
            q = lax.ppermute(q, axis_name, perm)
            scale = lax.ppermute(scale, axis_name, perm)
            acc = (q.astype(jnp.float32) * scale).astype(x.dtype)
        acc = acc + take(r - 1 - s)
    return acc


def _ring_all_gather_impl(shard, *, axis_name, tp, bits):
    """tp-1 ppermute hops rotating every chip's shard around the ring and
    assembling the full last dim (inverse of the reduce-scatter above).
    With ``bits`` the shard is quantized ONCE (per-chunk scales) and the
    int8 payload + scales ride the ring unmodified — gather adds no
    accumulation, so no per-hop requantization error."""
    r = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % tp) for i in range(tp)]
    if bits is None:
        blk, scale = shard, None
    else:
        blk, scale = _quant_hop(shard, bits)
    out = jnp.zeros((tp,) + blk.shape, blk.dtype)
    out = lax.dynamic_update_index_in_dim(out, blk, r, axis=0)
    if scale is not None:
        out_s = jnp.zeros((tp,) + scale.shape, scale.dtype)
        out_s = lax.dynamic_update_index_in_dim(out_s, scale, r, axis=0)
    for s in range(1, tp):
        blk = lax.ppermute(blk, axis_name, perm)
        out = lax.dynamic_update_index_in_dim(out, blk, (r - s) % tp,
                                              axis=0)
        if scale is not None:
            scale = lax.ppermute(scale, axis_name, perm)
            out_s = lax.dynamic_update_index_in_dim(out_s, scale,
                                                    (r - s) % tp, axis=0)
    if scale is not None:
        out = (out.astype(jnp.float32) * out_s).astype(shard.dtype)
    out = jnp.moveaxis(out, 0, -2)
    return out.reshape(shard.shape[:-1] + (tp * shard.shape[-1],))


_ring_rs_jit = jax.jit(_ring_reduce_scatter_impl,
                       static_argnames=("axis_name", "tp", "bits"))
_ring_ag_jit = jax.jit(_ring_all_gather_impl,
                       static_argnames=("axis_name", "tp", "bits"))


def ring_reduce_scatter(x, axis_name="model", log_name=None,
                        quant_bits: Optional[int] = None):
    """Ring reduce-scatter over a manual mesh axis: returns this chip's
    fully-reduced 1/tp shard of ``x``'s last dim (chip r gets chunk r).
    tp-1 nearest-neighbor hops; each is recorded for the comms logger and
    the resilience watchdog under ``log_name`` (a stalled hop is named
    like any other collective site in fault drills)."""
    tp = _axis_size(axis_name)
    if tp <= 1:
        return x
    # hop payload = one 1/tp chunk (int8: itemsize ratio vs the input);
    # quantized hops additionally carry the f32 per-chunk scale plane
    # (one f32 per row of the chunk) as a second ppermute — record it
    # too, so comms-logger hop counts/bytes and the 'collective' fault
    # site match the audited schedule (2 collectives per quantized hop)
    itemsize = jnp.result_type(x).itemsize
    hop_scale = (1.0 / tp) * (1.0 / itemsize if quant_bits else 1.0)
    scale_plane = 4.0 / (x.shape[-1] * itemsize) if quant_bits else 0.0
    for _ in range(tp - 1):
        _record("reduce_scatter", x, axis_name, log_name, scale=hop_scale)
        if quant_bits:
            _record("reduce_scatter", x, axis_name, log_name,
                    scale=scale_plane)
    return _ring_rs_jit(x, axis_name=axis_name, tp=tp, bits=quant_bits)


def ring_all_gather(shard, axis_name="model", log_name=None,
                    quant_bits: Optional[int] = None):
    """Ring all-gather over a manual mesh axis: inverse of
    :func:`ring_reduce_scatter` — every chip's shard rotates around the
    ring (tp-1 hops) and concatenates to the full last dim, chunk r at
    offset r. Same per-hop recording for watchdog/comms accounting."""
    tp = _axis_size(axis_name)
    if tp <= 1:
        return shard
    # as in ring_reduce_scatter: quantized hops also rotate the f32
    # per-chunk scale plane — record both ppermutes per hop
    itemsize = jnp.result_type(shard).itemsize
    hop_scale = 1.0 / itemsize if quant_bits else 1.0
    scale_plane = 4.0 / (shard.shape[-1] * itemsize) if quant_bits else 0.0
    for _ in range(tp - 1):
        _record("all_gather", shard, axis_name, log_name, scale=hop_scale)
        if quant_bits:
            _record("all_gather", shard, axis_name, log_name,
                    scale=scale_plane)
    return _ring_ag_jit(shard, axis_name=axis_name, tp=tp, bits=quant_bits)


def decomposed_all_reduce(x, axis_name="model", chunks: int = 1,
                          quant_bits: Optional[int] = None, log_name=None):
    """All-reduce decomposed into ``chunks`` independent (ring
    reduce-scatter → ring all-gather) pipelines over ``x``'s last dim.

    Semantically identical to ``psum`` (bitwise at tp=2 — one commutative
    add — and reassociation-equivalent beyond); structurally it replaces
    the one opaque collective with ``2 * chunks * (tp-1)`` nearest-neighbor
    hops whose dataflow edges XLA can interleave with adjacent compute —
    chunk i's gather hops overlap chunk j's reduce hops, and the whole
    tail overlaps the next layer's GEMM wherever the consumer allows.
    ``quant_bits`` rides every hop at int8 with per-chunk scales
    (quantized once per hop on the partial sums — the EQuARX schedule).

    Degrades loudly-but-safely: a last dim not divisible by ``chunks*tp``
    drops to the largest dividing chunk count, and one not divisible by
    ``tp`` at all falls back to the monolithic :func:`all_reduce` (no ring
    seam exists).
    """
    tp = _axis_size(axis_name)
    if tp <= 1:
        return x
    E = x.shape[-1]
    if E % tp:
        # no ring seam exists: callers without a build-time divisibility
        # check (the MoE training paths) would otherwise audit a schedule
        # that silently lost its decomposition
        logger.warning(
            "decomposed_all_reduce(%s): last dim %d not divisible by "
            "tp=%d — falling back to the monolithic all-reduce",
            log_name or axis_name, E, tp)
        return all_reduce(x, "sum", axis_name, log_name)
    c = max(1, int(chunks))
    while c > 1 and E % (c * tp):
        c -= 1
    if c != max(1, int(chunks)):
        logger.warning(
            "decomposed_all_reduce(%s): last dim %d not divisible by "
            "chunks*tp (%d*%d) — degrading to %d chunk(s)",
            log_name or axis_name, E, chunks, tp, c)
    parts = jnp.split(x, c, axis=-1) if c > 1 else [x]
    outs = [ring_all_gather(
        ring_reduce_scatter(p, axis_name, log_name, quant_bits),
        axis_name, log_name, quant_bits) for p in parts]
    return outs[0] if c == 1 else jnp.concatenate(outs, axis=-1)


def overlap_all_reduce(x, axis_name="model", log_name=None,
                       mode: Optional[str] = None,
                       chunks: Optional[int] = None,
                       quant_bits: Optional[int] = None):
    """The one schedule-dispatch for a TP sum-reduction site: resolve the
    overlap schedule (explicit ``mode``/``chunks`` as the defaults, the
    ``DSTPU_TP_OVERLAP*`` env knobs override — :func:`resolve_tp_overlap`)
    and trace either the decomposed ring (:func:`decomposed_all_reduce`)
    or the monolithic :func:`all_reduce`. Callers that already hold a
    fully-resolved schedule (the v2 serve engine, which resolves env at
    engine construction) can keep calling :func:`decomposed_all_reduce`
    directly; env-driven sites (the MoE training reductions) use this so
    the resolution + dispatch live in exactly one place."""
    mode, chunks = resolve_tp_overlap(mode, chunks)
    if mode != "off":
        return decomposed_all_reduce(x, axis_name=axis_name, chunks=chunks,
                                     quant_bits=quant_bits,
                                     log_name=log_name)
    return all_reduce(x, "sum", axis_name, log_name)


def barrier(group=None):
    """Host-level barrier: synchronize all processes (reference comm.py:421).
    Inside a compiled program there is nothing to do — XLA orders collectives;
    at host level we round-trip a tiny psum through all devices."""
    if jax.process_count() == 1:
        return
    # a zero-sized allreduce across all devices forces a sync point
    x = jnp.zeros((jax.device_count(),))
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.asarray(jax.devices()), ("all",))
    y = jax.jit(lambda a: a.sum(),
                in_shardings=NamedSharding(mesh, P("all")))(x)
    jax.block_until_ready(y)


def log_summary(show_straggler: bool = False) -> str:
    """Print the comms table (reference ``dist.log_summary``, comm.py:422)."""
    return get_comms_logger().log_summary(show_straggler)
