"""Comms volume/bandwidth accounting.

Parity with the reference's ``CommsLogger`` (``utils/comms_logging.py:67``)
and its ``calc_bw_log`` (``:34``): per-op, per-message-size counters with
algorithmic-bandwidth math. The reference times each eager NCCL call via
``@timed_op``; under XLA the collectives are compiled into the step, so the
logger records *trace-time* volume (exact) and, when a host-side wall time is
supplied (non-jit usage or whole-step timing), computes the same algo/bus
bandwidth numbers.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from ..utils.logging import log_dist, logger


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float,
                n: int) -> Tuple[float, float]:
    """(algo_bw, bus_bw) in GB/s for a collective moving ``size_bytes`` over
    ``n`` participants, mirroring reference ``utils/comms_logging.py:34``."""
    if duration_s <= 0:
        return 0.0, 0.0
    size = float(size_bytes)
    if comm_op in ("all_to_all_single", "all_to_all"):
        algo = size / duration_s
        bus = algo * (n - 1) / n if n else algo
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter",
                     "reduce_scatter_tensor"):
        size *= n
        algo = size / duration_s
        bus = algo * (n - 1) / n if n else algo
    elif comm_op in ("all_reduce", "inference_all_reduce"):
        size *= 2
        algo = size / duration_s
        bus = algo * (n - 1) / n if n else algo
    else:  # send/recv/broadcast/ppermute: point-to-point
        algo = size / duration_s
        bus = algo
    return algo / 1e9, bus / 1e9


class CommsLogger:
    """Size-bucketed per-op records; ``log_summary`` prints the reference's
    table (op → msg size → count, total latency, avg latency, bw)."""

    def __init__(self, enabled: bool = False, verbose: bool = False,
                 prof_all: bool = True, prof_ops: Optional[List[str]] = None,
                 debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.debug = debug
        # op -> msg_size -> [count, total_lat_ms, total_algo_bw, total_bus_bw]
        self.comms_dict: Dict[str, Dict[int, List[float]]] = defaultdict(dict)

    def configure(self, enabled=None, verbose=None, prof_all=None,
                  prof_ops=None, debug=None):
        if enabled is not None:
            self.enabled = enabled
        if verbose is not None:
            self.verbose = verbose
        if prof_all is not None:
            self.prof_all = prof_all
        if prof_ops is not None:
            self.prof_ops = prof_ops
        if debug is not None:
            self.debug = debug

    def _should_log(self, op_name: str, log_name: Optional[str]) -> bool:
        if not self.enabled:
            return False
        if self.prof_all:
            return True
        return bool(log_name and log_name in self.prof_ops) or op_name in self.prof_ops

    def append(self, op_name: str, size_bytes: int, n_participants: int,
               duration_s: float = 0.0, log_name: Optional[str] = None):
        if not self._should_log(op_name, log_name):
            return
        algo_bw, bus_bw = calc_bw_log(op_name, size_bytes, duration_s,
                                      n_participants)
        lat_ms = duration_s * 1e3
        rec = self.comms_dict[op_name].setdefault(size_bytes, [0, 0.0, 0.0, 0.0])
        rec[0] += 1
        rec[1] += lat_ms
        rec[2] += algo_bw
        rec[3] += bus_bw
        if self.verbose:
            log_dist(
                f"comm op: {op_name} | msg size: {size_bytes} | "
                f"time (ms): {lat_ms:.2f} | algbw (Gbps): {algo_bw * 8:.2f} | "
                f"busbw (Gbps): {bus_bw * 8:.2f}", ranks=[0])

    def log_summary(self, show_straggler: bool = False) -> str:
        lines = []
        header = (f"{'Comm. Op':<25}{'Message Size':<20}{'Count':<10}"
                  f"{'Total Latency(ms)':<20}{'Avg Latency(ms)':<20}"
                  f"{'tput_avg (GB/s)':<20}{'busbw_avg (GB/s)':<20}")
        lines.append(header)
        for op, sizes in sorted(self.comms_dict.items()):
            lines.append(op)
            for size, (count, tot_ms, algo, bus) in sorted(sizes.items()):
                avg = tot_ms / count if count else 0.0
                lines.append(
                    f"{'':<25}{_fmt_size(size):<20}{count:<10}"
                    f"{tot_ms:<20.2f}{avg:<20.2f}"
                    f"{algo / max(count, 1):<20.2f}{bus / max(count, 1):<20.2f}")
        out = "\n".join(lines)
        logger.info(out)
        return out

    def reset(self):
        self.comms_dict.clear()


def _fmt_size(num: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(num) < 1024.0:
            return f"{num:.1f} {unit}"
        num /= 1024.0
    return f"{num:.1f} PB"


_LOGGER = CommsLogger()


def get_comms_logger() -> CommsLogger:
    return _LOGGER


# --------------------------------------------------------------------------- #
# last-collective tracking (resilience watchdog stall diagnosis)
# --------------------------------------------------------------------------- #

#: the most recent collective seen by comm._record, independent of the
#: CommsLogger enable switch — the step watchdog names it when a step
#: stalls. Collectives are recorded at TRACE time under jit, so this is
#: "the last collective the program being (re)built contains", which for a
#: hung first execution is exactly the right suspect list.
_LAST_COLLECTIVE: Optional[Dict] = None


def note_collective(op_name: str, size_bytes: int, n_participants: int,
                    log_name: Optional[str] = None) -> None:
    global _LAST_COLLECTIVE
    import time
    _LAST_COLLECTIVE = {
        "op": op_name,
        "log_name": log_name,
        "size_bytes": int(size_bytes),
        "n": int(n_participants),
        "time": time.time(),
    }


def last_collective() -> Optional[Dict]:
    return None if _LAST_COLLECTIVE is None else dict(_LAST_COLLECTIVE)
