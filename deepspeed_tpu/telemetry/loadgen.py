"""Open-loop, wall-clock load generation for the v2 ragged engine.

The ROADMAP's fleet item needs capacity numbers a closed-loop bench
cannot produce: a closed loop only offers a new request when an old one
completes, so the engine is never observed *past* its capacity and the
measured "throughput" is just the engine's pace. This module drives any
``InferenceEngineV2`` **open-loop**: request arrival times come from a
seeded stochastic process evaluated against the WALL CLOCK, and the
arrival clock is **never back-pressured by engine state** — when the
engine falls behind, late arrivals queue in the driver (their measured
queue-wait/TTFT grows, which is the phenomenon being measured) or are
shed after ``shed_after_s``; they never stall the generator. That is
the DeepSpeed-FastGen workload-evaluation regime (PAPER.md §7): offered
load is an independent variable, goodput/latency are the response.

Pieces:

  * arrival processes — :class:`PoissonArrivals` (exponential gaps),
    :class:`UniformArrivals` (deterministic spacing),
    :class:`TraceArrivals` (recorded-trace replay). All seeded: the same
    (process, seed, n) always yields the identical schedule, so runs
    are reproducible and on-vs-off comparisons see the same offered
    stream.
  * :class:`WorkloadMix` — prompt/generation length distributions, a
    shared-prefix fraction (those prompts open with one common preamble
    and ride the prefix cache), and a per-request deadline fraction.
  * :func:`run_open_loop` — the driver: admit due arrivals through
    ``put(..., arrivals=..., deadlines=...)`` (so the engine's SLO
    stamps anchor at the request's scheduled arrival, not at whenever
    admission happened), decode in short pipelined bursts between
    admission polls, and emit a structured :class:`LoadResult` — offered
    vs completed vs goodput rates, TTFT/TPOT/queue-wait p50/p90/p99
    aggregated through the telemetry registry's streaming histograms,
    and the shed/deadline-miss breakdown.
  * :func:`sweep_capacity` — offered-QPS sweep locating the knee: the
    highest offered rate whose goodput fraction still meets the SLO
    threshold (``bench.py serve_capacity`` / ``bin/dstpu_loadgen``).

The driver's per-iteration work (:meth:`_OpenLoopDriver._admit_due`,
:meth:`_OpenLoopDriver._decode_burst`) is dslint DSL001-registered: it
brackets the engine's overlapped pipeline, so a blocking host sync here
would serialize the very hot path whose capacity is being measured.
"""

from __future__ import annotations

import heapq
import json
import random
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .registry import Histogram

# ---------------------------------------------------------------------- #
# arrival processes
# ---------------------------------------------------------------------- #


class ArrivalProcess:
    """Seeded generator of nondecreasing arrival offsets (seconds from
    the run's t=0). ``schedule(n)`` is a pure function of the process's
    construction arguments — determinism is the contract the capacity
    bench and the on-vs-off parity gates stand on."""

    kind = "base"

    def schedule(self, n: int) -> np.ndarray:
        raise NotImplementedError

    def describe(self) -> Dict[str, Any]:
        return {"process": self.kind}


class PoissonArrivals(ArrivalProcess):
    """Memoryless arrivals at ``rate_rps`` offered requests/second —
    i.i.d. exponential inter-arrival gaps from a seeded RNG."""

    kind = "poisson"

    def __init__(self, rate_rps: float, seed: int = 0):
        if rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
        self.rate_rps = float(rate_rps)
        self.seed = int(seed)

    def schedule(self, n: int) -> np.ndarray:
        rng = np.random.RandomState(self.seed)
        return np.cumsum(rng.exponential(1.0 / self.rate_rps, size=n))

    def describe(self) -> Dict[str, Any]:
        return {"process": self.kind, "rate_rps": self.rate_rps,
                "seed": self.seed}


class UniformArrivals(ArrivalProcess):
    """Deterministic arrivals: one request every ``1/rate_rps`` seconds
    (the jitter-free control against the Poisson runs)."""

    kind = "uniform"

    def __init__(self, rate_rps: float):
        if rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
        self.rate_rps = float(rate_rps)

    def schedule(self, n: int) -> np.ndarray:
        return (np.arange(n, dtype=np.float64) + 1.0) / self.rate_rps

    def describe(self) -> Dict[str, Any]:
        return {"process": self.kind, "rate_rps": self.rate_rps}


class SpikeArrivals(ArrivalProcess):
    """Piecewise-constant-rate arrivals: ``base_rps`` everywhere except
    a ``[start_s, start_s + dur_s)`` window offered at ``mult x
    base_rps`` — the overload-drill traffic spike. Seeded exponential
    unit-rate gaps are mapped through the closed-form inverse of the
    integrated rate, so the spike's edges are exact and the same seed
    always yields the identical schedule (the controller on-vs-off
    comparison sees the same offered stream)."""

    kind = "spike"

    def __init__(self, base_rps: float, mult: float, start_s: float,
                 dur_s: float, seed: int = 0):
        if base_rps <= 0 or mult <= 0:
            raise ValueError(
                f"base_rps and mult must be > 0, got {base_rps}/{mult}")
        if start_s < 0 or dur_s <= 0:
            raise ValueError(
                f"need start_s >= 0 and dur_s > 0, got "
                f"{start_s}/{dur_s}")
        self.base_rps = float(base_rps)
        self.mult = float(mult)
        self.start_s = float(start_s)
        self.dur_s = float(dur_s)
        self.seed = int(seed)

    def schedule(self, n: int) -> np.ndarray:
        rng = np.random.RandomState(self.seed)
        # cumulative unit-rate exponentials, inverted through the
        # integrated rate L(t): L = base*t up to the spike, slope
        # base*mult inside it, base again past it
        u = np.cumsum(rng.exponential(1.0, size=n))
        a = self.base_rps * self.start_s            # L at spike start
        b = a + self.base_rps * self.mult * self.dur_s   # L at spike end
        t_pre = u / self.base_rps
        t_in = self.start_s + (u - a) / (self.base_rps * self.mult)
        t_post = self.start_s + self.dur_s + (u - b) / self.base_rps
        return np.where(u <= a, t_pre, np.where(u <= b, t_in, t_post))

    def describe(self) -> Dict[str, Any]:
        return {"process": self.kind, "base_rps": self.base_rps,
                "mult": self.mult, "start_s": self.start_s,
                "dur_s": self.dur_s, "seed": self.seed}


class TraceArrivals(ArrivalProcess):
    """Recorded-trace replay: arrival offsets from a captured workload
    (a JSON list of seconds, absolute or already-relative — the
    schedule is normalized to start at 0). ``time_scale`` compresses or
    stretches the trace (0.5 = replay at double speed)."""

    kind = "trace"

    def __init__(self, times: Sequence[float], time_scale: float = 1.0,
                 path: Optional[str] = None):
        if not len(times):
            raise ValueError("empty arrival trace")
        t = np.sort(np.asarray(times, dtype=np.float64))
        self.times = (t - t[0]) * float(time_scale)
        self.time_scale = float(time_scale)
        self.path = path

    @classmethod
    def from_file(cls, path: str,
                  time_scale: float = 1.0) -> "TraceArrivals":
        with open(path, encoding="utf-8") as f:
            blob = json.load(f)
        times = blob["arrivals"] if isinstance(blob, dict) else blob
        return cls(times, time_scale=time_scale, path=path)

    def schedule(self, n: int) -> np.ndarray:
        if n > len(self.times):
            raise ValueError(
                f"trace holds {len(self.times)} arrivals, {n} requested")
        return self.times[:n].copy()

    def describe(self) -> Dict[str, Any]:
        span = float(self.times[-1]) if len(self.times) > 1 else 0.0
        return {"process": self.kind, "n_times": int(len(self.times)),
                "time_scale": self.time_scale, "path": self.path,
                "rate_rps": round(len(self.times) / span, 3)
                if span > 0 else None}


# ---------------------------------------------------------------------- #
# workload mix
# ---------------------------------------------------------------------- #


@dataclass
class Request:
    """One offered request: identity, scheduled arrival offset, prompt,
    decode budget, optional per-request deadline. ``group`` is the
    shared-prefix group index (None for unique-prompt requests) — the
    fleet bench reads it to check routing affinity."""

    uid: int
    arrival_s: float
    prompt: List[int]
    gen_len: int
    deadline_s: Optional[float] = None
    group: Optional[int] = None
    #: traffic class for brownout shedding: 0 = interactive (protected),
    #: 1 = batch/background (shed first at ladder level L4)
    klass: int = 0


@dataclass
class WorkloadMix:
    """Seeded request-shape distribution. ``shared_prefix_frac`` of the
    requests open with a common ``shared_prefix_len``-token preamble
    (the prefix-cache hit population); ``prefix_group_count`` spreads
    those over that many DISTINCT preambles (>1 is the replica-fleet
    workload: more shared-prefix groups than one replica's cache wants
    to hold, so routing affinity — not cache size — decides the
    fleet-wide hit rate); ``deadline_frac`` of the requests carry a
    ``deadline_s`` deadline measured from their scheduled arrival."""

    prompt_lens: Sequence[int] = (128, 256, 512)
    prompt_probs: Sequence[float] = (0.4, 0.4, 0.2)
    gen_lens: Sequence[int] = (32, 64, 128)
    gen_probs: Sequence[float] = (0.3, 0.5, 0.2)
    shared_prefix_frac: float = 0.0
    shared_prefix_len: int = 0
    prefix_group_count: int = 1
    #: hierarchical-KV working-set pattern (0 = off): offer a
    #: shared-prefix working set of ~this many KV blocks — the group
    #: count is derived as ceil(blocks·prefix_block_tokens /
    #: shared_prefix_len) (at least prefix_group_count) and EVERY
    #: request opens with a preamble, assigned by GROUP CYCLING
    #: (request i -> group i mod G) instead of a uniform draw: each
    #: preamble is revisited at exact period G, the honest pattern for
    #: a tier whose whole point is surviving between revisits (uniform
    #: assignment revisits hot groups too soon and cold ones maybe
    #: never). Size it >= 3x the engine's device pool to measure the
    #: host tier (bench.py serve_hier's workload).
    prefix_working_set_blocks: int = 0
    #: tokens per KV block the working-set sizing assumes (the target
    #: engine's block_size; the CLI's tiny engine uses 16)
    prefix_block_tokens: int = 16
    deadline_frac: float = 0.0
    deadline_s: float = 0.0
    #: fraction of requests tagged class-1 (batch/background) — the
    #: traffic the brownout ladder sheds FIRST under overload. Drawn
    #: from an independent seeded stream, so arming it never perturbs
    #: the prompts/budgets existing (mix, seed) pairs produce.
    batch_frac: float = 0.0
    vocab_size: int = 32000
    #: fixed prompt pool (recorded-prompt replay): when set, each
    #: request draws its prompt from this pool (seeded choice) instead
    #: of random tokens — prompt_lens/shared-prefix knobs are then
    #: ignored. This is how content-sensitive workloads (speculative
    #: decoding's self-drafting acceptance, cache-content studies)
    #: ride the observatory: offered load stays the independent
    #: variable while prompt CONTENT stays the controlled one.
    prompt_pool: Optional[Sequence[Sequence[int]]] = None

    @classmethod
    def prefill_heavy(cls, vocab_size: int = 32000,
                      **overrides) -> "WorkloadMix":
        """The disaggregated-serving workload preset
        (``bin/dstpu_loadgen --mix prefill_heavy``, docs/serving.md
        "Disaggregated serving"): prompts an order of magnitude longer
        than generations, so prefill FLOPs dominate the offered work
        and a colocated replica keeps stalling its decode streams
        behind arriving prompt chunks — the regime where splitting the
        fleet into prefill and decode specialists wins on BOTH TTFT and
        TPOT tails. Sized for the tiny CPU-harness engine (sequences
        cap at 256 tokens); real deployments scale the lengths, not the
        ratio. ``overrides`` pass through to the constructor."""
        kw: Dict[str, Any] = dict(
            prompt_lens=(96, 160), prompt_probs=(0.5, 0.5),
            gen_lens=(4, 8), gen_probs=(0.5, 0.5),
            vocab_size=vocab_size)
        kw.update(overrides)
        return cls(**kw)

    @classmethod
    def long_context(cls, pool_span_tokens: int = 256,
                     vocab_size: int = 32000,
                     **overrides) -> "WorkloadMix":
        """The long-context serving preset (``bin/dstpu_loadgen --mix
        long_context``, docs/serving.md "Long-context serving"):
        log-spaced prompt lengths from short up to ``pool_span_tokens``
        (the target engine's whole KV pool span — the longest prompts
        push per-sequence context PAST what a single chip's pool shard
        holds, the regime sequence-parallel serving exists for), drawn
        uniformly so every decade of context length is represented, and
        generations kept small (the long-context interactive shape:
        huge document in, short answer out). Sized by the CALLER's pool
        — pass ``pool_span_tokens = num_blocks_per_seq * block_size``
        for the engine under test."""
        span = max(64, int(pool_span_tokens))
        # 4 log-spaced rungs: span/8, span/4, span/2, ~span (headroom
        # for the generation so the chain never overflows its table)
        lens = sorted({max(16, span // 8), max(32, span // 4),
                       max(48, span // 2), max(56, span - 16)})
        kw: Dict[str, Any] = dict(
            prompt_lens=tuple(lens),
            prompt_probs=tuple([1.0 / len(lens)] * len(lens)),
            gen_lens=(4, 8), gen_probs=(0.5, 0.5),
            vocab_size=vocab_size)
        kw.update(overrides)
        return cls(**kw)

    @classmethod
    def moe_decode_heavy(cls, vocab_size: int = 32000,
                         **overrides) -> "WorkloadMix":
        """The expert-parallel MoE serving preset (``bin/dstpu_loadgen
        --mix moe_decode_heavy``, docs/serving.md "Expert-parallel MoE
        serving"): short prompts with generations several times longer,
        so single-token decode steps dominate the offered work — the
        regime where the per-step dispatch/combine ``all_to_all`` pair
        is the whole comm bill and the sharded experts' HBM saving has
        to be paid for in exchange latency. Pair with ``--ep`` and read
        the ``serve_moe`` report section."""
        kw: Dict[str, Any] = dict(
            prompt_lens=(8, 16), prompt_probs=(0.5, 0.5),
            gen_lens=(24, 48), gen_probs=(0.5, 0.5),
            vocab_size=vocab_size)
        kw.update(overrides)
        return cls(**kw)

    def describe(self) -> Dict[str, Any]:
        return {
            "prompt_mix": list(self.prompt_lens)
            if self.prompt_pool is None
            else f"pool({len(self.prompt_pool)})",
            "gen_mix": list(self.gen_lens),
            "shared_prefix_frac": self.shared_prefix_frac,
            "shared_prefix_len": self.shared_prefix_len,
            "prefix_group_count": self.prefix_group_count,
            "prefix_working_set_blocks": self.prefix_working_set_blocks,
            "deadline_frac": self.deadline_frac,
            "deadline_s": self.deadline_s,
            "batch_frac": self.batch_frac,
        }


def build_requests(process: ArrivalProcess, mix: WorkloadMix, n: int,
                   seed: int = 0, uid_base: int = 0) -> List[Request]:
    """Materialize ``n`` requests: arrival offsets from ``process``,
    shapes/contents from ``mix`` under ``seed``. Pure and deterministic
    — request identity (prompt, budget, deadline) depends only on
    (mix, seed, index), never on engine timing, so per-request token
    streams are comparable across instrumentation settings."""
    arrivals = process.schedule(n)
    rng = np.random.RandomState(seed)
    plens = rng.choice(list(mix.prompt_lens), size=n,
                       p=list(mix.prompt_probs))
    glens = rng.choice(list(mix.gen_lens), size=n, p=list(mix.gen_probs))
    shared = rng.random_sample(n) < mix.shared_prefix_frac
    deadlined = rng.random_sample(n) < mix.deadline_frac
    # shared-prefix preambles: one (the single-group classic),
    # prefix_group_count distinct ones (the fleet workload), or the
    # hierarchical-KV WORKING-SET pattern (prefix_working_set_blocks):
    # enough groups to cover the requested block footprint, every
    # request prefixed, groups CYCLED so each preamble is revisited at
    # exact period G. The pre-existing paths draw exactly what they
    # always drew, so request identity under existing (mix, seed)
    # pairs is unchanged.
    if mix.prefix_working_set_blocks > 0:
        if mix.shared_prefix_len <= 0:
            raise ValueError(
                "prefix_working_set_blocks needs shared_prefix_len > 0")
        if int(min(mix.prompt_lens)) <= mix.shared_prefix_len:
            # the per-request guard below would silently strip the
            # preamble from such prompts — the working-set pattern
            # would then measure NOTHING; fail loud instead
            raise ValueError(
                f"prefix_working_set_blocks: every prompt must exceed "
                f"the {mix.shared_prefix_len}-token preamble (shortest "
                f"prompt_len is {min(mix.prompt_lens)})")
        per = max(1, -(-mix.shared_prefix_len
                       // max(1, mix.prefix_block_tokens)))
        G = max(mix.prefix_group_count,
                -(-mix.prefix_working_set_blocks // per))
        prefixes = [rng.randint(1, mix.vocab_size,
                                size=mix.shared_prefix_len).tolist()
                    for _ in range(G)]
        group_of = np.arange(n, dtype=np.int64) % G
        shared = np.ones(n, bool)
    elif mix.shared_prefix_len and mix.prefix_group_count > 1:
        prefixes = [rng.randint(1, mix.vocab_size,
                                size=mix.shared_prefix_len).tolist()
                    for _ in range(mix.prefix_group_count)]
        group_of = rng.randint(0, mix.prefix_group_count, size=n)
    else:
        prefixes = [rng.randint(1, mix.vocab_size,
                                size=mix.shared_prefix_len).tolist()
                    if mix.shared_prefix_len else []]
        group_of = np.zeros(n, np.int64)
    pool = list(mix.prompt_pool) if mix.prompt_pool else None
    pool_pick = rng.randint(0, len(pool), size=n) if pool else None
    # traffic classes from an INDEPENDENT seeded stream: arming
    # batch_frac must not shift the main RNG's draw sequence, so every
    # pre-existing (mix, seed) pair keeps byte-identical request
    # identity (prompts, budgets, deadlines)
    if mix.batch_frac > 0:
        krng = np.random.RandomState(seed + 7919)
        klasses = (krng.random_sample(n) < mix.batch_frac).astype(int)
    else:
        klasses = np.zeros(n, np.int64)
    out: List[Request] = []
    for i in range(n):
        plen = int(plens[i])
        g = int(group_of[i])
        prefix = prefixes[g]
        if pool is not None:
            # recorded-prompt replay: content from the pool, identity
            # still (mix, seed, index)-deterministic
            prompt = list(pool[int(pool_pick[i])])
            group = None
        elif shared[i] and prefix and plen > len(prefix):
            body = rng.randint(1, mix.vocab_size,
                               size=plen - len(prefix)).tolist()
            prompt = prefix + body
            group = g
        else:
            prompt = rng.randint(1, mix.vocab_size, size=plen).tolist()
            group = None
        out.append(Request(
            uid=uid_base + i, arrival_s=float(arrivals[i]),
            prompt=prompt, gen_len=int(glens[i]),
            deadline_s=mix.deadline_s
            if deadlined[i] and mix.deadline_s > 0 else None,
            group=group, klass=int(klasses[i])))
    return out


# ---------------------------------------------------------------------- #
# the open-loop driver
# ---------------------------------------------------------------------- #


@dataclass
class LoadResult:
    """One open-loop pass: the structured report plus the per-request
    committed token streams (the parity-gate evidence)."""

    report: Dict[str, Any]
    streams: Dict[int, List[int]] = field(default_factory=dict)


class _OpenLoopDriver:
    """One pass of :func:`run_open_loop` — split into the DSL001-
    registered per-iteration methods (`_admit_due`, `_decode_burst`)
    and cold bookkeeping."""

    def __init__(self, engine, requests: Sequence[Request],
                 decode_burst: int, shed_after_s: float,
                 poll_s: float, max_live: Optional[int] = None,
                 sampling: Any = None, admission: Any = None,
                 retry_budget: int = 0, retry_base_s: float = 0.05,
                 retry_seed: int = 0):
        self.engine = engine
        self.requests = sorted(requests, key=lambda r: r.arrival_s)
        self.decode_burst = max(1, int(decode_burst))
        self.shed_after_s = shed_after_s
        self.poll_s = poll_s
        #: SamplingParams template applied to EVERY offered request
        #: (per-uid seeds derive from the uid when the template names
        #: none — streams stay deterministic per request identity)
        self.sampling = sampling
        self.max_live = max(1, int(max_live)) \
            if max_live is not None else None
        #: AdmissionController (serving/admission.py) or None. Armed,
        #: the door REJECTS offers beyond the controller's window
        #: (typed records with retry_after_s hints) instead of holding
        #: them; None keeps the exact pre-controller hold-at-door path
        #: (``max_live`` is the controller's responsibility when armed)
        self.admission = admission
        # client retry discipline: jittered exponential backoff
        # honoring the rejection's retry_after_s hint, bounded by
        # retry_budget attempts per request; retried requests keep
        # their ORIGINAL identity (uid + arrival stamp)
        self.retry_budget = max(0, int(retry_budget))
        self.retry_base_s = float(retry_base_s)
        self._retry_rng = random.Random(retry_seed)
        self.retryq: List[Tuple[float, int, int, Request]] = []
        self._retry_n = 0
        self._retried_uids: set = set()
        self.retry_stats = {"attempts": 0, "exhausted": 0,
                            "abandoned": 0, "succeeded_after_retry": 0}
        #: EWMA of observed admit->complete service time — the client's
        #: estimate of the minimum useful deadline remainder: retrying
        #: with less budget than this left only wastes an engine slot
        self._serv_ewma: Optional[float] = None
        self.pending: deque = deque(self.requests)
        self.live: Dict[int, Dict[str, Any]] = {}
        self.streams: Dict[int, List[int]] = {}
        self.by_uid = {r.uid: r for r in self.requests}
        # outcome bookkeeping
        self.completed: Dict[int, float] = {}    # uid -> completion offset
        self.shed_late: List[int] = []
        #: driver-side structured rejections, SAME record shape as the
        #: engine's (uid/reason/time/retry_after_s) — the report
        #: classifies both through one merged view, so driver sheds and
        #: engine sheds can never be double- or un-counted
        self.rejected_driver: Dict[int, Dict[str, Any]] = {}
        self.offer_lags: List[float] = []
        self.first_seen: Dict[int, float] = {}   # driver-side fallback
        self._stamp_cache: Dict[int, Dict[str, float]] = {}
        # decode accounting (the fastgen HBM-roofline inputs)
        self.decode_time_s = 0.0
        self.decode_tokens = 0
        self.decode_steps = 0
        self.decode_ctx_step_sum = 0
        self.decode_step_lat = Histogram()
        self.t0 = 0.0

    # ------------------ hot loop (DSL001-registered) ------------------- #

    def _admit_due(self, now: float) -> None:
        """Offer every arrival whose scheduled time has passed. The
        schedule is the precomputed process output — engine state never
        delays an offer (the open-loop invariant); it only decides
        whether the offered request is admitted, held at the door
        (``max_live`` concurrency bound — held requests keep their
        ORIGINAL arrival stamp, so door wait lands in queue-wait/TTFT),
        queued into this batch late, or shed (``shed_after_s``).

        With an :class:`~deepspeed_tpu.serving.AdmissionController`
        armed the door changes semantics: offers beyond the
        controller's window (or class-shed by the brownout ladder) are
        REJECTED with typed retriable records instead of held — holding
        past the knee is exactly the collapse the controller exists to
        prevent. Due retries re-offer through the same door."""
        adm = self.admission
        if adm is not None:
            adm.poll(self.t0 + now)
        due: List[Request] = []
        while self.retryq and self.retryq[0][0] <= now:
            _, _, attempt, r = heapq.heappop(self.retryq)
            if adm is not None \
                    and not adm.door(len(self.live) + len(due), r.klass):
                self._door_reject(r, now, attempt)
                continue
            due.append(r)
        while self.pending and self.pending[0].arrival_s <= now:
            if adm is None and self.max_live is not None \
                    and len(self.live) + len(due) >= self.max_live:
                break
            r = self.pending.popleft()
            lag = now - r.arrival_s
            self.offer_lags.append(lag)
            if self.shed_after_s > 0 and lag > self.shed_after_s:
                self.shed_late.append(r.uid)
                self.rejected_driver[r.uid] = {
                    "uid": r.uid, "reason": "shed_late",
                    "time": time.time(), "retry_after_s": None,
                    "lag_s": round(lag, 4)}
                continue
            if adm is not None \
                    and not adm.door(len(self.live) + len(due), r.klass):
                self._door_reject(r, now, 0)
                continue
            due.append(r)
        if not due:
            return
        arrivals: Dict[int, float] = {}
        deadlines: Dict[int, float] = {}
        for r in due:
            t_arr, dl = r.arrival_s, r.deadline_s
            if r.uid in self._retried_uids:
                # a re-offer restarts the ENGINE clock: stamping the
                # original arrival would book the client's backoff as
                # engine queue wait and feed it back into the
                # controller's evidence (a retry storm indistinguishable
                # from real overload). The deadline stays anchored at
                # the original arrival — only the remainder is granted.
                if dl is not None:
                    dl = max(0.0, t_arr + dl - now)
                t_arr = now
            arrivals[r.uid] = self.t0 + t_arr
            if dl is not None:
                deadlines[r.uid] = dl
        sampling = {r.uid: self.sampling for r in due} \
            if self.sampling is not None else None
        res = self.engine.put([r.uid for r in due],
                              [r.prompt for r in due], _greedy=True,
                              arrivals=arrivals, deadlines=deadlines,
                              sampling=sampling)
        t_seen = time.monotonic() - self.t0
        for r in due:
            if r.uid in res:
                tok = res[r.uid]
                self.streams[r.uid] = [tok]
                self.first_seen[r.uid] = t_seen
                if r.uid in self._retried_uids:
                    self._retried_uids.discard(r.uid)
                    self.retry_stats["succeeded_after_retry"] += 1
                if r.gen_len <= 1:
                    self._finish(r.uid, "completed")
                else:
                    self.live[r.uid] = {"last": tok,
                                        "remaining": r.gen_len - 1}
            # admitted-then-rejected (deadline/shed inside put) and
            # refused requests both carry engine.rejections records —
            # the report's breakdown reads them after the pass

    def _door_reject(self, r: Request, now: float, attempt: int) -> None:
        """One typed door rejection plus the client's retry half of the
        contract: re-offer after max(the controller's ``retry_after_s``
        hint, jittered exponential backoff), up to ``retry_budget``
        attempts. A retried request keeps its ORIGINAL uid, and its
        deadline/goodput stay anchored at the first offer — retries
        never launder SLO outcomes. Only the ENGINE clock (queue
        wait/TTFT) restarts at the re-offer, so client backoff is not
        booked as engine queue time (see :meth:`_admit_due`).
        Registered DSL001 hot path: dict/heap stores and host
        arithmetic only."""
        rec = self.admission.reject(r.uid, klass=r.klass)
        if attempt >= self.retry_budget:
            self.retry_stats["exhausted"] += 1
            self._retried_uids.discard(r.uid)
            return
        hint = rec.get("retry_after_s") or 0.0
        back = self.retry_base_s * (2.0 ** attempt) \
            * (0.5 + self._retry_rng.random())
        t_next = now + max(hint, back)
        if r.deadline_s is not None \
                and t_next + (self._serv_ewma or 0.0) \
                >= r.arrival_s + r.deadline_s:
            # the deadline remainder at retry time would not even cover
            # the observed service time — a rational client abandons
            # rather than burn a slot on a request the engine must
            # expire anyway (a zombie that produces no goodput but
            # still displaces requests that could have met their SLO)
            self.retry_stats["abandoned"] += 1
            self._retried_uids.discard(r.uid)
            return
        self.retry_stats["attempts"] += 1
        self._retried_uids.add(r.uid)
        self._retry_n += 1
        heapq.heappush(self.retryq,
                       (t_next, self._retry_n, attempt + 1, r))

    def _decode_burst(self) -> None:
        """One short pipelined decode burst over the live set — short so
        the admission poll (the arrival clock) runs between bursts."""
        eng = self.engine
        # bind the pre-burst views ONCE: against a replica pool these
        # are merged-dict properties rebuilt per access, so a per-uid
        # property read would cost O(live² · replicas) host time inside
        # the very loop being measured (the post-burst rejection check
        # below stays a fresh read — aborts can land DURING the burst)
        seqs = eng.state.sequences
        rejected = eng.rejections
        uids = [u for u in self.live
                if u in seqs and u not in rejected]
        for u in list(self.live):
            if u not in uids:
                self.live.pop(u)            # shed/expired mid-flight
        if not uids:
            return
        burst = self.decode_burst
        adm = self.admission
        if adm is not None and adm.decode_burst_cap < burst:
            # brownout L3 (throughput_cap): shorter bursts return to the
            # admission poll sooner, trading batch throughput for
            # arrival-clock fidelity exactly when the door must act
            burst = max(1, adm.decode_burst_cap)
        budgets = [min(burst, self.live[u]["remaining"])
                   for u in uids]
        ctx = 0
        for u in uids:
            ctx += seqs[u].seen_tokens
        t0 = time.perf_counter()
        outs = eng.decode_pipelined(
            uids, [self.live[u]["last"] for u in uids], budgets)
        dt = time.perf_counter() - t0
        steps = 0
        got_total = 0
        t_seen = time.monotonic() - self.t0
        rejected = eng.rejections           # re-read: aborts can land
        for u in uids:                      # DURING the burst
            got = outs.get(u) or []
            if got:
                self.streams[u].extend(got)
                self.first_seen.setdefault(u, t_seen)
            got_total += len(got)
            if len(got) > steps:
                steps = len(got)
            if u in rejected:
                self.live.pop(u, None)      # aborted inside the burst
                continue
            st = self.live[u]
            st["remaining"] -= len(got)
            if got:
                st["last"] = got[-1]
            if st["remaining"] <= 0:
                self.live.pop(u)
                self._finish(u, "completed")
        self.decode_time_s += dt
        self.decode_tokens += got_total
        self.decode_steps += steps
        self.decode_ctx_step_sum += steps * ctx
        if steps:
            self.decode_step_lat.observe(dt / steps)

    # --------------------------- cold paths ---------------------------- #

    def _finish(self, uid: int, outcome: str) -> None:
        """Clean completion: read the per-seq SLO stamps (PR 8) before
        the flush releases the descriptor, then flush."""
        seq = self.engine.state.get(uid)
        now = time.monotonic() - self.t0
        self.completed[uid] = now
        if seq is not None:
            self._stamps_of(uid, seq)
            self.engine.flush(uid)

    def _stamps_of(self, uid: int, seq) -> None:
        r = self.by_uid[uid]
        st = {"arrival_s": r.arrival_s}
        if seq.admitted_at is not None:
            adm = seq.admitted_at - self.t0
            serv = (time.monotonic() - self.t0) - adm
            if serv > 0:
                self._serv_ewma = serv if self._serv_ewma is None \
                    else 0.8 * self._serv_ewma + 0.2 * serv
            if seq.first_sched_at is not None:
                st["queue_wait_s"] = seq.first_sched_at - seq.admitted_at
            if seq.first_token_at is not None:
                st["ttft_s"] = seq.first_token_at - seq.admitted_at
                n_tok = len(self.streams.get(uid, ()))
                if seq.last_token_at is not None and n_tok > 1:
                    st["tpot_s"] = (seq.last_token_at
                                    - seq.first_token_at) / (n_tok - 1)
            st["admitted_s"] = adm
        self._stamp_cache[uid] = st

    def run(self) -> LoadResult:
        self.t0 = time.monotonic()
        while self.pending or self.live or self.retryq:
            now = time.monotonic() - self.t0
            self._admit_due(now)
            if self.live:
                self._decode_burst()
            elif self.pending or self.retryq:
                # idle until the earlier of the next scheduled arrival
                # and the next due retry (poll_s-capped so the
                # admission controller keeps ticking while idle)
                nxt = [r[0] for r in self.retryq[:1]]
                if self.pending:
                    nxt.append(self.pending[0].arrival_s)
                wait = self.t0 + min(nxt) - time.monotonic()
                if wait > 0:
                    time.sleep(min(wait, self.poll_s))
        duration = time.monotonic() - self.t0
        return LoadResult(report=self._report(duration),
                          streams=self.streams)

    def _report(self, duration: float) -> Dict[str, Any]:
        eng = self.engine
        n = len(self.requests)
        span = self.requests[-1].arrival_s if n else 0.0
        # per-pass latency histograms from the per-seq SLO stamps
        # (telemetry on), falling back to driver-observed first-output
        # times when the engine runs uninstrumented — the report always
        # has TTFT, just at burst granularity in the fallback
        h = {name: Histogram() for name in
             ("ttft_s", "tpot_s", "queue_wait_s")}
        stamps_used = 0
        for uid in self.completed:
            st = self._stamp_cache.get(uid, {})
            if "ttft_s" in st:
                stamps_used += 1
                h["ttft_s"].observe(st["ttft_s"])
                if "queue_wait_s" in st:
                    h["queue_wait_s"].observe(st["queue_wait_s"])
                if "tpot_s" in st:
                    h["tpot_s"].observe(st["tpot_s"])
            elif uid in self.first_seen:
                h["ttft_s"].observe(self.first_seen[uid]
                                    - self.by_uid[uid].arrival_s)
        # outcome breakdown over ONE merged record view: driver-side
        # records (shed_late) and engine records (shed/deadline/drain/
        # door) share a shape, and every offered uid is classified
        # exactly once — so the rows sum to offered - completed in
        # every mode, by construction (balance_ok asserts it)
        merged = dict(self.rejected_driver)
        for uid, rec in eng.rejections.items():
            if uid in self.by_uid:
                merged[uid] = rec
        shed = deadline = drained = adm_rej = other = 0
        shed_late_n = 0
        for uid in self.by_uid:
            if uid in self.completed:
                continue
            rec = merged.get(uid)
            reason = rec.get("reason") if rec else None
            if reason == "kv_pool_exhausted":
                shed += 1
            elif reason == "deadline_exceeded":
                deadline += 1
            elif reason == "draining":
                drained += 1
            elif reason == "shed_late":
                shed_late_n += 1
            elif reason == "admission_overload":
                adm_rej += 1
            else:
                # recordless non-completion should be impossible; fold
                # it into "other" so the balance stays a hard invariant
                other += 1
        completed = len(self.completed)
        # goodput: completed AND met its deadline (deadline-free
        # requests count on completion; the engine aborts most late
        # ones, this closes the completed-just-past-deadline window)
        goodput = 0
        for uid, t_done in self.completed.items():
            r = self.by_uid[uid]
            if r.deadline_s is None \
                    or t_done - r.arrival_s <= r.deadline_s:
                goodput += 1
        offered_rate = n / span if span > 0 else None
        lags = self.offer_lags
        refused = sum(1 for uid, rec in merged.items()
                      if uid not in self.streams
                      and rec.get("reason") != "shed_late")
        report = {
            "requests": {
                "offered": n,
                "admitted": n - shed_late_n - refused,
                "completed": completed,
                "goodput": goodput,
                "shed": shed,
                "deadline_expired": deadline,
                "shed_late": shed_late_n,
                "rejected_draining": drained,
                "rejected_admission": adm_rej,
                "rejected_other": other,
                "balance_ok": completed + shed + deadline + drained
                + shed_late_n + adm_rej + other == n,
            },
            "rates_rps": {
                "offered": round(offered_rate, 3)
                if offered_rate else None,
                "completed": round(completed / duration, 3)
                if duration > 0 else None,
                "goodput": round(goodput / duration, 3)
                if duration > 0 else None,
            },
            "goodput_frac": goodput / n if n else None,
            "latency": {name: hist.summary()
                        for name, hist in h.items()},
            "latency_source": "registry_stamps"
            if stamps_used else "driver_observed",
            "open_loop": {
                "max_offer_lag_s": round(max(lags), 4) if lags else 0.0,
                "mean_offer_lag_s": round(sum(lags) / len(lags), 4)
                if lags else 0.0,
            },
            "decode": {
                "time_s": round(self.decode_time_s, 4),
                "tokens": self.decode_tokens,
                "steps": self.decode_steps,
                "ctx_step_sum": self.decode_ctx_step_sum,
                "step_lat": self.decode_step_lat.summary(),
            },
            "output_tokens": sum(len(s) for s in self.streams.values()),
            "duration_s": round(duration, 4),
        }
        if duration > 0:
            report["output_tokens_per_sec"] = round(
                report["output_tokens"] / duration, 2)
        if self.retry_budget > 0 or self.retry_stats["attempts"]:
            report["retries"] = dict(self.retry_stats,
                                     budget=self.retry_budget)
        if self.admission is not None:
            report["admission"] = self.admission.state()
        return report


def run_open_loop(engine, requests: Sequence[Request],
                  decode_burst: int = 8, shed_after_s: float = 0.0,
                  poll_s: float = 0.02,
                  max_live: Optional[int] = None,
                  sampling: Any = None,
                  admission: Any = None,
                  retry_budget: int = 0,
                  retry_base_s: float = 0.05) -> LoadResult:
    """Drive one open-loop pass of ``requests`` against ``engine``.

    The arrival clock is the precomputed schedule against
    ``time.monotonic()`` — never gated on engine completions. Late
    offers (engine busy in a burst) are admitted with their ORIGINAL
    arrival stamp (``put(..., arrivals=...)``), so measured queue-wait
    and TTFT include the driver-side wait; offers later than
    ``shed_after_s`` past their arrival are shed driver-side
    (0 = queue indefinitely). ``decode_burst`` bounds how long the
    admission poll can starve (smaller = finer arrival granularity,
    more host/dispatch round-trips); ``max_live`` bounds in-engine
    concurrency (further due requests wait at the door with their
    arrival stamp intact — their wait is measured, not hidden).

    ``sampling`` (a SamplingParams template, or None for greedy)
    attaches per-request sampling at admission — the engine then
    selects tokens on-device per slot; speculative decoding (the
    engine's ``spec_decode`` knob) needs no driver support at all,
    because ``decode_pipelined`` routes greedy batches through it
    transparently.

    ``admission`` (an :class:`~deepspeed_tpu.serving.
    AdmissionController`, usually from
    :func:`~deepspeed_tpu.serving.build_admission`) changes the door's
    semantics: offers beyond the controller's window are REJECTED with
    typed retriable records instead of held, and the driver plays the
    client half of the retry contract — up to ``retry_budget``
    re-offers per request after max(the record's ``retry_after_s``
    hint, jittered exponential backoff from ``retry_base_s``), with
    the ORIGINAL arrival identity so goodput accounting stays honest.

    Leaves the engine empty (every request completed, aborted or
    flushed) and accumulates rejection records in
    ``engine.rejections``."""
    return _OpenLoopDriver(engine, requests, decode_burst, shed_after_s,
                           poll_s, max_live=max_live,
                           sampling=sampling, admission=admission,
                           retry_budget=retry_budget,
                           retry_base_s=retry_base_s).run()


# ---------------------------------------------------------------------- #
# capacity search
# ---------------------------------------------------------------------- #


def sweep_capacity(engine, rates: Sequence[float], n_per_rate: int,
                   mix: WorkloadMix, seed: int = 0,
                   goodput_slo_frac: float = 0.9,
                   process: str = "poisson",
                   decode_burst: int = 8, shed_after_s: float = 0.0,
                   max_live: Optional[int] = None,
                   sampling: Any = None,
                   admission: Any = None,
                   retry_budget: int = 0,
                   retry_base_s: float = 0.05) -> Dict[str, Any]:
    """Sweep offered QPS and locate the knee: the highest offered rate
    whose goodput fraction still meets ``goodput_slo_frac``. Each rate
    runs an independent seeded pass (disjoint uid ranges; the engine's
    compiled programs stay warm across passes). Returns the
    goodput-vs-offered-load curve plus the located knee — the
    ``bench.py serve_capacity`` payload."""
    if process not in ("poisson", "uniform"):
        # a recorded trace pins its own rate — sweeping offered rates
        # over it has no meaning, and silently substituting Poisson
        # would measure a different workload than the caller asked for
        raise ValueError(
            f"sweep_capacity supports 'poisson'|'uniform' arrivals, "
            f"got {process!r}")
    curve: List[Dict[str, Any]] = []
    for i, rate in enumerate(sorted(rates)):
        proc = UniformArrivals(rate) if process == "uniform" \
            else PoissonArrivals(rate, seed=seed + i)
        reqs = build_requests(proc, mix, n_per_rate, seed=seed + i,
                              uid_base=(i + 1) * 1_000_000)
        res = run_open_loop(engine, reqs, decode_burst=decode_burst,
                            shed_after_s=shed_after_s, max_live=max_live,
                            sampling=sampling, admission=admission,
                            retry_budget=retry_budget,
                            retry_base_s=retry_base_s)
        rep = res.report
        lat = rep["latency"]
        curve.append({
            "offered_rps": round(rate, 3),
            "offered_realized_rps": rep["rates_rps"]["offered"],
            "completed_rps": rep["rates_rps"]["completed"],
            "goodput_rps": rep["rates_rps"]["goodput"],
            "goodput_frac": round(rep["goodput_frac"], 4)
            if rep["goodput_frac"] is not None else None,
            "ttft_ms_p50": _ms(lat["ttft_s"].get("p50")),
            "ttft_ms_p99": _ms(lat["ttft_s"].get("p99")),
            "shed": rep["requests"]["shed"],
            "deadline_expired": rep["requests"]["deadline_expired"],
            "shed_late": rep["requests"]["shed_late"],
            "rejected_admission": rep["requests"]["rejected_admission"],
        })
    knee = None
    for row in curve:
        gf = row["goodput_frac"]
        if gf is not None and gf >= goodput_slo_frac:
            if knee is None or row["offered_rps"] > knee["offered_rps"]:
                knee = row
    return {
        "curve": curve,
        "slo_goodput_frac": goodput_slo_frac,
        "knee_rps": knee["offered_rps"] if knee else None,
        "knee_goodput_rps": knee["goodput_rps"] if knee else None,
        "n_per_rate": n_per_rate,
        "process": process,
        "seed": seed,
    }


def _ms(v: Optional[float]) -> Optional[float]:
    return round(1e3 * v, 3) if v is not None else None


def disagg_report(pool) -> Dict[str, Any]:
    """The ``disagg`` report section for a phase-specialist fleet
    (docs/serving.md "Disaggregated serving"): handoff volume (source-
    counted), adoptions, fallback replays, the exposed-wait tail the
    serve_disagg bench gates on, and per-role utilization rolled up
    from the per-replica registries (``serve_tokens_committed`` /
    ``serve_steps`` attribute each role's share of the work)."""
    roles: Dict[str, Dict[str, Any]] = {}
    handoffs = {"out": 0.0, "adopted": 0.0, "fallback_replays": 0.0,
                "blocks": 0.0, "bytes": 0.0}
    exposed = Histogram()
    total_tokens = 0.0
    for rep in pool.replicas():
        if rep.state == "dead":
            continue
        r = roles.setdefault(rep.role, {
            "replicas": 0, "requests_admitted": 0,
            "tokens_committed": 0, "steps": 0, "live_sequences": 0})
        r["replicas"] += 1
        r["live_sequences"] += len(rep.engine.state.sequences)
        m = rep.engine.metrics
        if m is None:
            continue
        r["requests_admitted"] += int(
            m.counter("serve_requests_admitted").value)
        tok = m.counter("serve_tokens_committed").value
        r["tokens_committed"] += int(tok)
        total_tokens += tok
        r["steps"] += int(m.counter("serve_steps").value)
        handoffs["out"] += m.counter("serve_handoff_seqs").value
        handoffs["adopted"] += m.counter("serve_handoff_seqs_in").value
        handoffs["fallback_replays"] += m.counter(
            "serve_handoff_fallback_replays").value
        handoffs["blocks"] += m.counter("serve_handoff_blocks").value
        handoffs["bytes"] += m.counter("serve_handoff_bytes").value
        exposed.merge(m.histogram("serve_handoff_exposed_s"))
    for r in roles.values():
        r["token_share"] = round(
            r["tokens_committed"] / total_tokens, 4) \
            if total_tokens else None
    return {
        "roles": roles,
        "handoffs": {k: int(v) if k != "bytes" else v
                     for k, v in handoffs.items()},
        "exposed_wait_s": exposed.summary(),
    }


# ---------------------------------------------------------------------- #
# CLI (bin/dstpu_loadgen)
# ---------------------------------------------------------------------- #


def _tiny_engine(max_seqs: int = 8, num_blocks: int = 96,
                 block_size: int = 16, vocab: int = 96,
                 spec: str = "off", spec_k: int = 4,
                 host_blocks: int = 0, seq_size: int = 1):
    """CPU-harness GPT-2 engine for the CLI's self-contained mode and
    the tier-1 capacity smoke — small enough that a decode step is a
    few ms. ``spec`` arms speculative decoding (``--spec`` /
    ``DSTPU_SPEC_MODE``); ``host_blocks`` arms the hierarchical-KV
    host-RAM tier (``--host-blocks``) so the working-set workload has a
    second tier to hit; ``seq_size`` opens the sequence-parallel axis
    (``--seq``, docs/serving.md "Long-context serving") — the caller
    provides the virtual devices."""
    import jax
    import jax.numpy as jnp

    from ..inference.v2 import InferenceEngineV2, RaggedInferenceConfig
    from ..models.gpt2 import GPT2, GPT2Config
    mcfg = GPT2Config(vocab_size=vocab, max_seq_len=block_size * 16,
                      num_layers=2, num_heads=2, hidden_size=32,
                      dtype=jnp.float32)
    params = GPT2(mcfg).init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 8), jnp.int32))["params"]
    cfg = RaggedInferenceConfig(
        max_seqs=max_seqs, chunk_size=16, block_size=block_size,
        num_blocks=num_blocks, max_blocks_per_seq=16, dtype="float32",
        attention_impl="dense", decode_loop_steps=0,
        serve_pipeline_depth=2, prefix_cache=True,
        prefix_cache_host_blocks=host_blocks,
        spec_decode=spec, spec_k=spec_k, seq_size=max(1, seq_size))
    return InferenceEngineV2(mcfg, params, cfg), mcfg


#: the tiny MoE engine's expert FFN width; its dense-matched reference
#: uses top_k x this (same ACTIVE params per token, no routing)
_TINY_MOE_INTERMEDIATE = 32


def _tiny_moe_engine(max_seqs: int = 8, num_blocks: int = 96,
                     block_size: int = 16, ep: int = 1,
                     dense_match: bool = False):
    """CPU-harness Mixtral-style engine for ``--mix moe_decode_heavy``:
    4 experts, top-2 routing, small enough that a decode step is a few
    ms. ``ep`` opens the expert axis over that many virtual devices
    (``--ep``, docs/serving.md "Expert-parallel MoE serving").
    ``dense_match=True`` instead builds the dense reference at MATCHED
    ACTIVE PARAMS — a plain Llama runner whose FFN width equals
    ``top_k x`` the expert width, so per-token GEMM work matches and
    the throughput ratio isolates routing + dispatch overhead."""
    import jax
    import jax.numpy as jnp

    from ..inference.v2 import InferenceEngineV2, RaggedInferenceConfig
    from ..models import llama, mixtral
    common = dict(vocab_size=96, max_seq_len=block_size * 16,
                  num_layers=2, num_heads=2, num_kv_heads=2,
                  hidden_size=32, dtype=jnp.float32)
    if dense_match:
        mcfg = llama.LlamaConfig(
            intermediate_size=2 * _TINY_MOE_INTERMEDIATE, **common)
        _, init_fn, _ = llama.make_model(mcfg)
    else:
        mcfg = mixtral.MixtralConfig(
            intermediate_size=_TINY_MOE_INTERMEDIATE, num_experts=4,
            experts_top_k=2, **common)
        _, init_fn, _ = mixtral.make_model(mcfg)
    params = init_fn(jax.random.PRNGKey(0), seq_len=16)
    cfg = RaggedInferenceConfig(
        max_seqs=max_seqs, chunk_size=16, block_size=block_size,
        num_blocks=num_blocks, max_blocks_per_seq=16, dtype="float32",
        attention_impl="dense", decode_loop_steps=0,
        serve_pipeline_depth=2, prefix_cache=True,
        ep_size=1 if dense_match else max(1, ep))
    return InferenceEngineV2(mcfg, params, cfg), mcfg


def main(argv: Optional[List[str]] = None) -> int:
    """``bin/dstpu_loadgen`` — run an open-loop pass (or a rate sweep)
    against a self-contained tiny CPU engine and print the report JSON.
    ``--replicas N`` swaps the single engine for an N-replica
    :class:`~deepspeed_tpu.serving.ReplicaPool` (same knobs, same
    report shape, plus a ``fleet`` section) with the routing policy
    from ``--policy`` / ``DSTPU_FLEET_POLICY``. The env knobs mirror
    the flags (flags win); docs/CONFIG.md has the catalog."""
    import argparse
    import os

    ap = argparse.ArgumentParser(
        prog="dstpu_loadgen",
        description="open-loop wall-clock load generator for the v2 "
                    "ragged engine or a replica-pool fleet "
                    "(docs/observability.md)")
    ap.add_argument("--rate", default=os.environ.get(
        "DSTPU_LOADGEN_RATE", "8"),
        help="offered req/s; a comma list runs a capacity sweep")
    ap.add_argument("--requests", type=int, default=int(os.environ.get(
        "DSTPU_LOADGEN_REQS", "32")))
    ap.add_argument("--seed", type=int, default=int(os.environ.get(
        "DSTPU_LOADGEN_SEED", "0")))
    ap.add_argument("--burst", type=int, default=int(os.environ.get(
        "DSTPU_LOADGEN_BURST", "8")),
        help="decode tokens per pipelined burst between admission polls")
    ap.add_argument("--process", choices=("poisson", "uniform", "trace"),
                    default=os.environ.get("DSTPU_LOADGEN_PROCESS",
                                           "poisson"))
    ap.add_argument("--trace", default=os.environ.get(
        "DSTPU_LOADGEN_TRACE"),
        help="JSON arrival-trace file for --process trace")
    ap.add_argument("--shed-after", type=float, default=float(
        os.environ.get("DSTPU_LOADGEN_SHED_AFTER_S", "0")),
        help="driver-side shed bound in seconds (0 = queue forever)")
    ap.add_argument("--temperature", type=float, default=float(
        os.environ.get("DSTPU_LOADGEN_TEMPERATURE", "0") or "0"),
        help="per-request sampling temperature (0 = greedy; the "
             "on-device per-slot sampler, seeds derived per uid)")
    ap.add_argument("--top-k", type=int, default=0,
                    help="sampling top-k filter (with --temperature > 0)")
    ap.add_argument("--top-p", type=float, default=1.0,
                    help="sampling top-p filter (with --temperature > 0)")
    ap.add_argument("--spec", default=os.environ.get(
        "DSTPU_LOADGEN_SPEC", "off"), choices=("off", "ngram"),
        help="arm speculative decoding on the tiny engine(s) — the "
             "observatory then drives draft/verify traffic and the "
             "report carries the acceptance rate")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens per speculation round")
    ap.add_argument("--mix", default=os.environ.get(
        "DSTPU_LOADGEN_MIX", "custom"),
        choices=("custom", "prefill_heavy", "long_context",
                 "moe_decode_heavy"),
        help="workload preset: prefill_heavy offers long prompts with "
             "short generations (the disaggregated-serving regime, "
             "docs/serving.md) and overrides --prompt-len/--gen-len; "
             "long_context offers log-spaced prompts up to the engine's "
             "whole per-sequence pool span with small generations (the "
             "sequence-parallel regime — pair with --seq) and adds a "
             "'longctx' report section; moe_decode_heavy swaps in the "
             "tiny MoE engine with short prompts and long generations "
             "(the expert-parallel regime — pair with --ep) and adds a "
             "'serve_moe' report section")
    ap.add_argument("--seq", type=int, default=int(os.environ.get(
        "DSTPU_LOADGEN_SEQ", "1") or "1"),
        help="sequence-parallel width for the tiny engine(s) — shards "
             "the KV pool round-robin over that many virtual devices "
             "(docs/serving.md Long-context serving)")
    ap.add_argument("--ep", type=int, default=int(os.environ.get(
        "DSTPU_LOADGEN_EP", "1") or "1"),
        help="expert-parallel width for the tiny MoE engine (--mix "
             "moe_decode_heavy) — shards the expert stacks over that "
             "many virtual devices (docs/serving.md Expert-parallel "
             "MoE serving)")
    ap.add_argument("--prompt-len", type=int, default=24)
    ap.add_argument("--gen-len", type=int, default=16)
    ap.add_argument("--shared-prefix-frac", type=float, default=0.0)
    ap.add_argument("--prefix-groups", type=int, default=1,
                    help="distinct shared preambles (>1 = the fleet "
                         "routing workload)")
    ap.add_argument("--prefix-working-set-blocks", type=int,
                    default=int(os.environ.get(
                        "DSTPU_LOADGEN_PREFIX_WS", "0") or "0"),
                    help="offer a group-cycled shared-prefix working "
                         "set of ~this many KV blocks (the hierarchical"
                         "-KV workload; size it >= 3x the device pool)")
    ap.add_argument("--host-blocks", type=int,
                    default=int(os.environ.get(
                        "DSTPU_LOADGEN_HOST_BLOCKS", "0") or "0"),
                    help="arm the tiny engine's host-RAM prefix-cache "
                         "tier with this many blocks (0 = off)")
    ap.add_argument("--num-blocks", type=int,
                    default=int(os.environ.get(
                        "DSTPU_LOADGEN_NUM_BLOCKS", "96") or "96"),
                    help="tiny engine KV pool size in blocks — shrink "
                         "it below the working set to exercise the "
                         "host tier")
    ap.add_argument("--deadline-s", type=float, default=0.0)
    ap.add_argument("--deadline-frac", type=float, default=0.0)
    ap.add_argument("--batch-frac", type=float, default=float(
        os.environ.get("DSTPU_LOADGEN_BATCH_FRAC", "0") or "0"),
        help="fraction of requests tagged lowest-class (klass=1, "
             "batch) — the brownout ladder's shed_lowclass level "
             "sheds these first")
    ap.add_argument("--admission", default=os.environ.get(
        "DSTPU_LOADGEN_ADMISSION", "off"), choices=("on", "off"),
        help="arm the knee-seeking AdmissionController at the door "
             "(docs/serving.md Overload control; DSTPU_ADMISSION=0 "
             "still kills it)")
    ap.add_argument("--retry-budget", type=int, default=int(
        os.environ.get("DSTPU_LOADGEN_RETRY_BUDGET", "0") or "0"),
        help="client retries per door-rejected request (jittered "
             "exponential backoff honoring retry_after_s)")
    ap.add_argument("--retry-base", type=float, default=float(
        os.environ.get("DSTPU_LOADGEN_RETRY_BASE_S", "0.05") or "0.05"),
        help="base backoff seconds for the retry schedule")
    ap.add_argument("--spike-mult", type=float, default=float(
        os.environ.get("DSTPU_LOADGEN_SPIKE_MULT", "0") or "0"),
        help="overlay a rate spike of this multiple on --rate "
             "(0 = steady; poisson process only)")
    ap.add_argument("--spike-start", type=float, default=float(
        os.environ.get("DSTPU_LOADGEN_SPIKE_START_S", "1") or "1"),
        help="spike onset, seconds into the run")
    ap.add_argument("--spike-dur", type=float, default=float(
        os.environ.get("DSTPU_LOADGEN_SPIKE_DUR_S", "2") or "2"),
        help="spike duration in seconds")
    ap.add_argument("--replicas", type=int, default=int(os.environ.get(
        "DSTPU_FLEET_REPLICAS", "1")),
        help="serve through a ReplicaPool of N tiny engines instead of "
             "one engine")
    ap.add_argument("--policy", default=None,
        choices=("random", "round_robin", "prefix_aware"),
        help="fleet routing policy (default: DSTPU_FLEET_POLICY or "
             "prefix_aware)")
    ap.add_argument("--roles", default=os.environ.get(
        "DSTPU_FLEET_ROLES"),
        help="comma list of per-replica phase roles (prefill/decode/"
             "mixed) for --replicas N — arms disaggregated serving; "
             "the report gains a 'disagg' section (DSTPU_DISAGG=0 "
             "still forces everything mixed)")
    ap.add_argument("--slo-goodput", type=float, default=0.9,
                    help="goodput fraction the sweep's knee must meet")
    ap.add_argument("--out", default=None,
                    help="also write the report JSON here")
    args = ap.parse_args(argv)

    pool = None
    if (args.seq > 1 or args.ep > 1) and os.environ.get(
            "JAX_PLATFORMS", "").startswith("cpu"):
        # seq/expert-parallel tiny engines need their virtual devices
        # BEFORE the backend initializes (same shim as the replica path)
        from ..utils.jax_compat import request_cpu_devices
        request_cpu_devices(max(2, max(args.seq, args.ep)
                                * max(1, args.replicas)))
    if args.mix == "moe_decode_heavy" and args.replicas > 1:
        ap.error("--mix moe_decode_heavy drives the single-engine MoE "
                 "harness; use --replicas 1")
    if args.replicas > 1:
        from ..serving import ReplicaPool, build_replica_engines
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            # per-replica host devices BEFORE the backend initializes —
            # without them every tiny engine lands on ONE device and
            # the pool's replica threads serialize, so the fleet
            # numbers would not scale with --replicas (the same shim
            # bench.py serve_fleet uses)
            from ..utils.jax_compat import request_cpu_devices
            request_cpu_devices(max(2, args.replicas))
        mcfg_box = []

        def factory(i, dev):
            e, m = _tiny_engine(num_blocks=args.num_blocks,
                                spec=args.spec, spec_k=args.spec_k,
                                host_blocks=args.host_blocks,
                                seq_size=args.seq)
            mcfg_box.append(m)
            return e

        engines = build_replica_engines(factory, args.replicas)
        mcfg = mcfg_box[0]
        roles = [r.strip() for r in args.roles.split(",")] \
            if args.roles else None
        pool = ReplicaPool(engines, policy=args.policy, roles=roles)
        eng = pool
    elif args.mix == "moe_decode_heavy":
        eng, mcfg = _tiny_moe_engine(num_blocks=args.num_blocks,
                                     ep=args.ep)
    else:
        eng, mcfg = _tiny_engine(num_blocks=args.num_blocks,
                                 spec=args.spec, spec_k=args.spec_k,
                                 host_blocks=args.host_blocks,
                                 seq_size=args.seq)
    sampling = None
    if args.temperature > 0:
        from ..inference.v2 import SamplingParams
        sampling = SamplingParams(temperature=args.temperature,
                                  top_k=args.top_k, top_p=args.top_p)
    if args.mix == "prefill_heavy":
        mix = WorkloadMix.prefill_heavy(
            vocab_size=mcfg.vocab_size,
            deadline_frac=args.deadline_frac,
            deadline_s=args.deadline_s,
            batch_frac=args.batch_frac)
    elif args.mix == "long_context":
        # span = the tiny engine's whole per-sequence table
        # (max_blocks_per_seq=16 x block_size=16 -> 256 tokens)
        mix = WorkloadMix.long_context(
            pool_span_tokens=16 * 16,
            vocab_size=mcfg.vocab_size,
            deadline_frac=args.deadline_frac,
            deadline_s=args.deadline_s,
            batch_frac=args.batch_frac)
    elif args.mix == "moe_decode_heavy":
        mix = WorkloadMix.moe_decode_heavy(
            vocab_size=mcfg.vocab_size,
            deadline_frac=args.deadline_frac,
            deadline_s=args.deadline_s,
            batch_frac=args.batch_frac)
    else:
        mix = WorkloadMix(
            prompt_lens=(args.prompt_len,), prompt_probs=(1.0,),
            gen_lens=(args.gen_len,), gen_probs=(1.0,),
            shared_prefix_frac=args.shared_prefix_frac,
            # full 16-token blocks (the tiny engine's block size) so
            # the shared span is actually cacheable; shorter prompts
            # get no prefix rather than a sub-block span no match can
            # ever hit. The working-set pattern always needs a preamble
            # — it exists to cycle one — and takes the LONGEST
            # block-aligned span the prompt affords (up to 3 blocks),
            # so the group count is working-set/preamble-blocks and a
            # realistic request count actually revisits each group.
            shared_prefix_len=min(
                3, max(1, (args.prompt_len - 8) // 16)) * 16
            if args.prefix_working_set_blocks > 0
            else (16 if args.shared_prefix_frac > 0
                  and args.prompt_len >= 24 else 0),
            prefix_group_count=max(1, args.prefix_groups),
            prefix_working_set_blocks=max(
                0, args.prefix_working_set_blocks),
            prefix_block_tokens=16,
            deadline_frac=args.deadline_frac, deadline_s=args.deadline_s,
            batch_frac=args.batch_frac,
            vocab_size=mcfg.vocab_size)
    adm = None
    if args.admission == "on":
        # explicit opt-in arms the controller; DSTPU_ADMISSION=0 (or
        # telemetry off) still wins inside build_admission
        from ..serving import build_admission
        adm = build_admission(eng)
    rates = [float(r) for r in str(args.rate).split(",") if r]
    if len(rates) > 1:
        if args.process == "trace":
            ap.error("--process trace replays a recorded schedule and "
                     "cannot sweep offered rates; give one --rate or "
                     "use poisson/uniform")
        if args.spike_mult > 0:
            ap.error("--spike-mult overlays a spike on ONE --rate; a "
                     "sweep already varies the offered load")
        out = sweep_capacity(
            eng, rates, args.requests, mix, seed=args.seed,
            goodput_slo_frac=args.slo_goodput, process=args.process,
            decode_burst=args.burst, shed_after_s=args.shed_after,
            sampling=sampling, admission=adm,
            retry_budget=args.retry_budget,
            retry_base_s=args.retry_base)
    else:
        if args.process == "trace":
            if not args.trace:
                ap.error("--process trace needs --trace FILE")
            proc: ArrivalProcess = TraceArrivals.from_file(args.trace)
        elif args.process == "uniform":
            proc = UniformArrivals(rates[0])
        elif args.spike_mult > 0:
            proc = SpikeArrivals(rates[0], args.spike_mult,
                                 args.spike_start, args.spike_dur,
                                 seed=args.seed)
        else:
            proc = PoissonArrivals(rates[0], seed=args.seed)
        reqs = build_requests(proc, mix, args.requests, seed=args.seed)
        res = run_open_loop(eng, reqs, decode_burst=args.burst,
                            shed_after_s=args.shed_after,
                            sampling=sampling, admission=adm,
                            retry_budget=args.retry_budget,
                            retry_base_s=args.retry_base)
        out = {"arrival": proc.describe(), "workload": mix.describe(),
               **res.report}
        slo = eng.slo_report()
        if slo:
            out["slo_cumulative"] = {
                "goodput_frac": slo["goodput_frac"],
                "ttft_ms_p50": _ms(slo["ttft_s"].get("p50")),
                "ttft_ms_p99": _ms(slo["ttft_s"].get("p99")),
                "spec_accept_rate": slo.get("spec_accept_rate"),
            }
    if args.temperature > 0:
        out["sampling"] = {"temperature": args.temperature,
                           "top_k": args.top_k, "top_p": args.top_p}
    if args.spec != "off":
        out["spec"] = {"mode": args.spec, "k": args.spec_k}
    if args.host_blocks > 0 and pool is None:
        # hierarchical-KV evidence: tier residency + churn + the
        # host-served share of all matched tokens
        st = eng.prefix_stats
        out["hier_kv"] = {
            "host_blocks": args.host_blocks,
            "host_cached_blocks": st.get("host_cached_blocks", 0),
            "demoted": st.get("demoted", 0),
            "promoted": st.get("promoted", 0),
            "host_hit_blocks": st.get("host_hit_blocks", 0),
            "host_evicted": st.get("host_evicted", 0),
            "host_hit_frac": round(st.get("host_hit_frac", 0.0), 4),
            "skipped_prefill_frac": round(
                st.get("prefill_chunks_skipped_frac", 0.0), 4),
        }
    if args.mix == "long_context":
        # long-context evidence (docs/serving.md "Long-context
        # serving"): the seq width, the per-chip vs total pool bytes
        # (FLAT per chip is the whole point), and the longest rung
        reps = [r.engine for r in pool.replicas()] if pool is not None \
            else [eng]
        kvrep = reps[0].state.kv_memory_report()
        out["longctx"] = {
            "seq_size": kvrep.get("seq_size", 1),
            "prompt_rungs": list(mix.prompt_lens),
            "longest_prompt": max(mix.prompt_lens),
            "kv_pool_bytes_total": kvrep["kv_pool_bytes_total"],
            "kv_pool_bytes_per_chip": kvrep["kv_pool_bytes_per_chip"],
        }
    if args.mix == "moe_decode_heavy":
        # expert-parallel evidence (docs/serving.md "Expert-parallel
        # MoE serving"): the expert-stack residency gauge (per-chip
        # bytes ∝ 1/ep — the HBM lever), the audited a2a share of the
        # decode step, and tokens/s against a dense reference at
        # MATCHED ACTIVE PARAMS (FFN width = top_k x expert width) —
        # the honest baseline: same per-token GEMMs, no routing
        from ..inference.v2.expert_parallel import expert_memory_report
        from .attribution import comm_share
        mem = expert_memory_report(eng)
        out["serve_moe"] = {
            "ep_size": mem["ep_size"],
            "num_experts": mcfg.num_experts,
            "experts_top_k": mcfg.experts_top_k,
            "expert_bytes_total": mem["expert_bytes_total"],
            "expert_bytes_per_chip": mem["expert_bytes_per_chip"],
            "moe_output_tokens_per_sec": out.get("output_tokens_per_sec"),
            "a2a": comm_share(eng, program="step_greedy_fb"),
        }
        if len(rates) == 1 and args.process != "trace":
            dense_eng, _ = _tiny_moe_engine(num_blocks=args.num_blocks,
                                            dense_match=True)
            dense_proc = (UniformArrivals(rates[0])
                          if args.process == "uniform"
                          else PoissonArrivals(rates[0], seed=args.seed))
            dense_res = run_open_loop(
                dense_eng,
                build_requests(dense_proc, mix, args.requests,
                               seed=args.seed),
                decode_burst=args.burst, shed_after_s=args.shed_after,
                sampling=sampling)
            dense_tps = dense_res.report.get("output_tokens_per_sec")
            out["serve_moe"]["dense_matched_output_tokens_per_sec"] = \
                dense_tps
            moe_tps = out.get("output_tokens_per_sec")
            if moe_tps and dense_tps:
                out["serve_moe"]["tokens_per_sec_vs_dense"] = round(
                    moe_tps / dense_tps, 4)
    if pool is not None:
        from ..serving import fleet_prefix_stats
        out["fleet"] = {
            "replicas": args.replicas,
            "router": pool.router.describe(),
            "prefix": fleet_prefix_stats(pool),
            "slo_merged": bool(pool.fleet_registry() is not None),
        }
        if any(r.role != "mixed" for r in pool.replicas()):
            out["disagg"] = disagg_report(pool)
    blob = json.dumps(out)
    print(blob)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(blob)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
