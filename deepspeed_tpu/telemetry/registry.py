"""Metrics registry — counters, gauges and streaming histograms.

The observability substrate every serving/scheduling decision in
ROADMAP's fleet item keys on (docs/observability.md): per-request SLO
numbers (TTFT/TPOT/queue-wait percentiles, goodput), cache and pool
health, and comm-schedule counters as *first-class engine outputs*
instead of ad-hoc bench arithmetic.

Design constraints (why this is not just a dict of floats):

  * **Host-only, commit-boundary cheap.** Every record call is a few
    Python arithmetic ops on host ints/floats — no device access, no
    locks on the count path. The serve engine records inside its
    existing host-side plan/commit boundaries, so the dslint DSL001
    no-host-sync discipline and the audited zero-callback programs are
    untouched (tier-1 asserts both).
  * **Percentiles without samples.** :class:`Histogram` is a log-bucketed
    streaming sketch (DDSketch-style): bucket ``i`` holds values in
    ``(gamma^(i-1), gamma^i]`` with ``gamma = (1+alpha)/(1-alpha)``, so
    any quantile is answered with relative error <= ``alpha`` (default
    5%) from O(log range) ints — p50/p99 over millions of tokens with no
    sample buffer.
  * **No-op when off.** ``DSTPU_TELEMETRY=0`` routes every caller to the
    :class:`NullRegistry`, whose metric handles are shared do-nothing
    singletons — the zero-overhead kill switch (``bench.py serve_obs``
    measures the on-path against it).

Metric names live in :data:`REGISTERED_METRICS`; the dslint DSL006 rule
keeps that table and the docs/observability.md catalog from drifting in
either direction.
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: metric-name catalog: name -> one-line meaning. The single source of
#: truth dslint DSL006 checks two-way against docs/observability.md's
#: "Metric catalog" table. Keep this a PURE literal dict — the rule
#: reads it from the AST, not by importing this module.
REGISTERED_METRICS = {
    # -- serve request lifecycle (counters) ---------------------------- #
    "serve_requests_admitted": "fresh requests admitted by put()",
    "serve_requests_completed": "requests flushed after clean completion",
    "serve_requests_shed": "requests load-shed (kv_pool_exhausted)",
    "serve_requests_deadline_expired": "requests aborted past deadline",
    "serve_requests_aborted": "requests cancelled via engine.abort()",
    "serve_requests_rejected_draining": "fresh requests refused mid-drain",
    "serve_requests_rejected_admission":
        "offers rejected at the admission door (typed, retriable)",
    "serve_requests_drained": "live requests manifested by drain()",
    "serve_tokens_committed": "output tokens committed (host-visible)",
    "serve_steps": "engine steps dispatched",
    "serve_steps_device_fed": "steps fed from the device token buffer",
    "serve_step_retries": "transient dispatch failures retried",
    # -- speculative decoding (counters) -------------------------------- #
    "spec_proposed": "draft tokens proposed for verification",
    "spec_accepted": "draft tokens accepted by greedy verification",
    "spec_rounds": "speculative propose/verify rounds committed",
    # -- serve latency (histograms, seconds) --------------------------- #
    "serve_ttft_s": "admission -> first committed token",
    "serve_tpot_s": "per-token gap between committed tokens",
    "serve_queue_wait_s": "admission -> first scheduled chunk",
    "serve_plan_s": "per-step plan (scheduler + staging) time",
    "serve_dispatch_s": "per-step dispatch (enqueue) time",
    "serve_commit_block_s": "per-commit blocking readback time",
    # -- step-time attribution (histograms + one labelled counter) ----- #
    "serve_commit_apply_s": "per-commit host-side apply (bookkeeping) time",
    "serve_host_gap_s": "per-step residual host time between brackets",
    "serve_step_wall_s": "per-committed-step wall-clock inside the loop",
    "serve_attrib_seconds_total":
        "cumulative attribution seconds (label: component)",
    # -- prefix cache (counters + gauges) ------------------------------ #
    "prefix_matched_tokens": "prompt tokens served from cached blocks",
    "prefix_prefill_tokens": "prompt tokens that ran a prefill chunk",
    "prefix_cow_copies": "partial-tail copy-on-write block copies",
    "prefix_hit_blocks": "full cached blocks matched",
    "prefix_evicted_blocks": "cached device blocks destroyed (cap + pressure)",
    "prefix_evicted_cap": "cached blocks destroyed by the index cap",
    "prefix_evicted_pressure": "cached blocks destroyed under pool pressure",
    "prefix_cached_blocks": "blocks currently held by the cache",
    "prefix_evictable_blocks": "refcount-0 cached blocks (reclaimable)",
    # -- hierarchical KV: the host-RAM tier (counters + gauge + hist) -- #
    "prefix_demoted_blocks": "device blocks demoted to the host tier",
    "prefix_promoted_blocks": "host-tier blocks promoted back on device",
    "prefix_host_hit_blocks": "matched blocks served from the host tier",
    "prefix_host_evicted_blocks": "host-tier blocks destroyed at its cap",
    "prefix_host_blocks": "blocks currently resident on the host tier",
    "prefix_promote_wait_s": "per-request promotion dispatch wait",
    # -- KV pool (gauges) ---------------------------------------------- #
    "kv_pool_blocks_total": "KV pool capacity in blocks",
    "kv_pool_blocks_free": "allocator-free KV blocks",
    "kv_pool_bytes_total": "KV pool bytes across all chips",
    "kv_pool_bytes_per_chip": "KV pool bytes one chip holds",
    # -- comm schedule (counters, auditor-canonical kinds) ------------- #
    "comm_traced_all_reduce": "all-reduce sites traced (program builds)",
    "comm_traced_all_gather": "all-gather sites traced (incl. ring sites)",
    "comm_traced_reduce_scatter": "reduce-scatter sites traced (incl. ring sites)",
    "comm_traced_ppermute": "raw ppermute sites traced",
    "comm_traced_all_to_all": "all-to-all sites traced",
    "comm_traced_broadcast": "broadcast sites traced",
    # -- FLOPs / roofline (gauges, phase-labelled) --------------------- #
    "achieved_tflops": "achieved TFLOPS for a phase (label: phase)",
    "flops_per_step": "model FLOPs per step for a phase (label: phase)",
    "mxu_utilization": "achieved/peak FLOPs fraction (label: phase)",
    # -- training observatory (telemetry/train.py) --------------------- #
    "train_steps": "committed train steps the observer closed",
    "train_samples": "training samples consumed by committed steps",
    "train_steps_skipped": "overflow-skipped (fp16) train steps",
    "train_nonfinite_steps": "steps with non-finite loss/grad-norm",
    "train_anomalies": "anomaly sentinel trips (nonfinite + z-score)",
    "train_data_wait_s": "between-step span (caller's data fetch)",
    "train_stage_s": "per-step staging (validation, arming, swap-in)",
    "train_dispatch_s": "per-step compiled-step dispatch time",
    "train_device_execute_s": "per-step exposed device wait at readback",
    "train_commit_apply_s": "per-step host bookkeeping after readback",
    "train_host_gap_s": "per-step residual host time between brackets",
    "train_step_wall_s": "per-committed-step wall between exit boundaries",
    "train_attrib_seconds_total":
        "cumulative train attribution seconds (label: component)",
    "train_loss": "last committed step's mean loss",
    "train_grad_norm": "last committed step's global grad norm",
    "train_goodput_frac": "productive fraction of the run's wall clock",
    # -- admission control (serving/admission.py) ----------------------- #
    "admission_window": "admission door's current AIMD concurrency bound",
    "admission_level": "current brownout ladder level (0 = normal)",
    "admission_rejected": "door rejections the controller issued",
    "admission_retry_after_s": "retry hints carried by door rejections",
    "brownout_transitions":
        "brownout ladder moves (label: direction=enter|exit)",
    # -- disaggregated serving handoff (serving/pool.py) ---------------- #
    "serve_handoff_seqs": "sequences handed prefill->decode (source side)",
    "serve_handoff_blocks": "KV blocks moved by handoffs",
    "serve_handoff_bytes": "KV payload bytes moved by handoffs",
    "serve_handoff_seqs_in": "migrated sequences adopted (destination side)",
    "serve_handoff_fallback_replays":
        "handoffs that fell back to manifest replay",
    "serve_handoff_exposed_s": "per-handoff exposed (non-overlapped) wall",
    # -- flight recorder (counter) -------------------------------------- #
    "flight_spans_dropped": "flight-recorder spans evicted by ring wrap",
}


def series_capacity() -> int:
    """Bounded per-metric time-series ring length
    (``DSTPU_SERIES_CAPACITY``, default 120 samples)."""
    return int(os.environ.get("DSTPU_SERIES_CAPACITY", "120") or "120")


def series_interval() -> float:
    """Minimum seconds between time-series samples
    (``DSTPU_SERIES_EVERY_S``, default 1.0; the serve observer calls
    ``maybe_sample`` at every commit boundary and this throttles it)."""
    return float(os.environ.get("DSTPU_SERIES_EVERY_S", "1.0") or "1.0")


def telemetry_enabled() -> bool:
    """The process-wide kill switch: ``DSTPU_TELEMETRY=0`` (or
    ``false``/``off``) disables every registry, recorder and bridge."""
    return os.environ.get("DSTPU_TELEMETRY", "1") \
        not in ("0", "false", "off")


class Counter:
    """Monotone float counter. ``inc`` is the hot path — one add."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, n=1.0):
        self.value += n


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v):
        self.value = v


class Histogram:
    """Log-bucketed streaming histogram (DDSketch-style).

    ``observe(v, n)`` adds ``n`` occurrences of value ``v`` to the bucket
    ``ceil(log_gamma(v))``; ``quantile(q)`` walks the (sorted) buckets
    and returns the geometric midpoint of the covering bucket, clamped
    to the observed [min, max] — relative error <= ``alpha`` by
    construction, exact-ish on single-bucket (constant) distributions.
    Non-positive values land in a dedicated zero bucket.
    """

    __slots__ = ("alpha", "gamma", "_lg", "buckets", "zero", "count",
                 "sum", "min", "max")

    def __init__(self, alpha: float = 0.05):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        self.alpha = alpha
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._lg = math.log(self.gamma)
        self.buckets: Dict[int, int] = {}
        self.zero = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v, n=1):
        self.count += n
        self.sum += v * n
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if v <= 0.0:
            self.zero += n
            return
        i = math.ceil(math.log(v) / self._lg)
        b = self.buckets
        b[i] = b.get(i, 0) + n

    def quantile(self, q: float) -> Optional[float]:
        if self.count <= 0:
            return None
        # nearest-rank (1-based ceil(q*n)) — an upper quantile over a
        # tiny count lands on the top value instead of collapsing into
        # the median bucket; converges to interpolated percentiles as
        # counts grow, within the alpha bucket error
        target = q * self.count
        if self.zero and target <= self.zero:
            return min(0.0, self.max)
        acc = self.zero
        for i in sorted(self.buckets):
            acc += self.buckets[i]
            if acc >= target:
                est = 2.0 * self.gamma ** i / (self.gamma + 1.0)
                return max(self.min, min(est, self.max))
        return self.max

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other`` into this sketch bucket-wise — EXACT: two
        sketches with the same ``gamma`` hold integer counts in the same
        bucket lattice, so the merged buckets (and zero bucket, count,
        min, max) are identical to a single sketch fed the union of the
        two observation streams — merged quantiles therefore equal
        single-stream quantiles on the same data, which is what makes
        this the fleet-rollup primitive (``MetricsRegistry.merge``).
        Mixed-gamma merges are refused rather than silently degraded —
        except when one side holds no positive observations (an idle
        replica's sketch, or one holding only the lattice-free zero
        bucket): such a side carries no bucket information, so the
        merge adopts the populated side's lattice and stays exact."""
        if other.buckets and self.buckets:
            if not math.isclose(self.gamma, other.gamma,
                                rel_tol=1e-12):
                raise ValueError(
                    f"histogram merge needs identical gamma "
                    f"({self.gamma} vs {other.gamma}) — bucket-wise "
                    f"merge is only exact on one bucket lattice")
        elif other.buckets:
            self.alpha = other.alpha
            self.gamma = other.gamma
            self._lg = other._lg
        b = self.buckets
        for i, n in other.buckets.items():
            b[i] = b.get(i, 0) + n
        self.zero += other.zero
        self.count += other.count
        self.sum += other.sum
        if other.min < self.min:
            self.min = other.min
        if other.max > self.max:
            self.max = other.max
        return self

    def state(self) -> Dict[str, Any]:
        """JSON-safe full sketch state (buckets included) — what
        ``snapshot()`` exports so :func:`merge_snapshots` can rebuild
        and merge exactly across processes."""
        out: Dict[str, Any] = {"alpha": self.alpha, "count": self.count,
                               "sum": self.sum, "zero": self.zero,
                               "buckets": {str(i): n for i, n
                                           in self.buckets.items()}}
        if self.count:
            out["min"] = self.min
            out["max"] = self.max
        return out

    @classmethod
    def from_state(cls, state: Dict[str, Any]) -> "Histogram":
        h = cls(alpha=float(state.get("alpha", 0.05)))
        h.count = int(state.get("count", 0))
        h.sum = float(state.get("sum", 0.0))
        h.zero = int(state.get("zero", 0))
        h.buckets = {int(i): int(n)
                     for i, n in state.get("buckets", {}).items()}
        if h.count:
            h.min = float(state["min"])
            h.max = float(state["max"])
        return h

    def summary(self) -> Dict[str, Any]:
        """Percentile summary PLUS the full sketch state: ``buckets`` /
        ``zero`` / ``alpha`` ride along so an exported snapshot stays
        exactly mergeable (:func:`merge_snapshots`). ``alpha`` is kept
        even when empty — an idle replica's sketch rebuilds on the
        lattice it was configured with, not the default."""
        if self.count == 0:
            return {"count": 0, "sum": 0.0, "alpha": self.alpha}
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
            "alpha": self.alpha,
            "zero": self.zero,
            "buckets": {str(i): n for i, n in self.buckets.items()},
        }


def _key(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


_LABEL_RE = None


def _dedupe_source(base: str, labels: Dict[str, Any],
                   used: set) -> None:
    """Suffix ``labels['source']`` until ``(base, labels)`` is a fresh
    key in ``used`` (mutates ``labels``; records the final key). Two
    distinct merge inputs must never silently overwrite one gauge."""
    orig = labels.get("source", "")
    key = _key(base, labels)
    n = 0
    while key in used:
        n += 1
        labels["source"] = f"{orig}#{n}"
        key = _key(base, labels)
    used.add(key)


def _parse_key(key: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of :func:`_key`: ``name{a="b",c="d"}`` -> (name, labels).
    Label values never contain quotes (they come from ``str()`` of knob
    values / phase names), so a non-greedy quoted scan is exact."""
    global _LABEL_RE
    if "{" not in key:
        return key, {}
    if _LABEL_RE is None:
        import re
        _LABEL_RE = re.compile(r'(\w+)="([^"]*)"')
    name, inner = key.split("{", 1)
    return name, {k: v for k, v in _LABEL_RE.findall(inner.rstrip("}"))}


class MetricsRegistry:
    """A named family of metrics with snapshot / Prometheus / JSON
    export and optional monitor bridges (telemetry.attach_monitor).

    Metric handles are get-or-create by (name, labels) and safe to cache
    — the serve observer binds its hot counters once at engine build."""

    enabled = True

    def __init__(self, name: str = "default"):
        self.name = name
        self._metrics: Dict[str, Any] = {}
        self._types: Dict[str, str] = {}
        self._bridges: List[Any] = []
        self.created_at = time.time()
        # bounded per-metric time series: key -> deque[(wall_t, value)]
        # (counters + gauges; histograms export their full sketch state
        # instead). maybe_sample() throttles to one sample per
        # DSTPU_SERIES_EVERY_S; the ring keeps the last
        # DSTPU_SERIES_CAPACITY samples — a month-long process holds a
        # constant-size series.
        self._series: Dict[str, deque] = {}
        self._series_cap = max(2, series_capacity())
        self._series_every = series_interval()
        self._last_sample = 0.0

    # ------------------------- metric handles ------------------------- #

    def _get(self, kind: str, cls, name: str, labels: Dict[str, Any],
             **kw):
        prev = self._types.get(name)
        if prev is not None and prev != kind:
            raise ValueError(
                f"metric {name!r} already registered as {prev}")
        key = _key(name, labels)
        m = self._metrics.get(key)
        if m is None:
            m = cls(**kw)
            self._metrics[key] = m
            self._types[name] = kind
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str, alpha: float = 0.05,
                  **labels) -> Histogram:
        return self._get("histogram", Histogram, name, labels, alpha=alpha)

    def metric_names(self) -> List[str]:
        """Base metric names (labels stripped) registered so far."""
        return sorted(self._types)

    # ------------------------- time series ----------------------------- #

    def sample(self, now: Optional[float] = None) -> None:
        """Append one time-series point per counter/gauge. Bounded ring
        per key; pure host arithmetic (the serve observer drives this
        from its commit boundary via :meth:`maybe_sample`)."""
        now = time.time() if now is None else now
        for key, m in self._metrics.items():
            if isinstance(m, (Counter, Gauge)):
                dq = self._series.get(key)
                if dq is None:
                    dq = deque(maxlen=self._series_cap)
                    self._series[key] = dq
                dq.append((now, m.value))
        self._last_sample = now

    def maybe_sample(self, now: Optional[float] = None) -> None:
        """Sample iff ``DSTPU_SERIES_EVERY_S`` elapsed since the last
        sample — the per-commit throttle."""
        now = time.time() if now is None else now
        if now - self._last_sample >= self._series_every:
            self.sample(now)

    def series(self) -> Dict[str, List[List[float]]]:
        """{metric key: [[t, value], ...]} — the sampled rings, oldest
        first. Exported alongside snapshots; ``bin/dstpu_top`` turns
        counter series into per-window rates and sparklines."""
        return {key: [[t, v] for t, v in dq]
                for key, dq in self._series.items() if len(dq)}

    def rate(self, name: str, window_s: Optional[float] = None,
             **labels) -> Optional[float]:
        """Windowed rate of a sampled counter: (last - earliest-within-
        window) / dt, or None with fewer than two samples. ``window_s``
        None uses the whole ring."""
        dq = self._series.get(_key(name, labels))
        if not dq or len(dq) < 2:
            return None
        t1, v1 = dq[-1]
        t0, v0 = dq[0]
        if window_s is not None:
            for t, v in dq:
                if t >= t1 - window_s:
                    t0, v0 = t, v
                    break
        return (v1 - v0) / (t1 - t0) if t1 > t0 else None

    # ------------------------- fleet rollup ---------------------------- #

    @classmethod
    def merge(cls, registries: Sequence["MetricsRegistry"],
              name: str = "fleet",
              sources: Optional[Sequence[str]] = None
              ) -> "MetricsRegistry":
        """Roll N registries (e.g. one per serving replica) into one:
        counters SUM, gauges keep per-source identity via an added
        ``source`` label (a pool's free-block gauges must stay per
        replica, not averaged into fiction), histograms merge
        bucket-wise EXACTLY (same gamma ⇒ merged quantiles identical to
        a single stream over the union — :meth:`Histogram.merge`).

        ``sources`` is the STABLE label scheme the fleet path uses
        (docs/observability.md "Fleet rollup"): one label per input
        registry, keyed by replica id — NOT by insertion index — so
        repeated rollups of the same replicas produce identical gauge
        keys regardless of membership-list order, and a rollup of
        rollups stays idempotent. Without ``sources`` the labels fall
        back to each registry's ``name``, disambiguated by index on
        collision (index suffixes are order-dependent; fleet callers
        should always pass ids). A short ``sources`` list is refused —
        it would silently drop replicas. A gauge that ALREADY carries a
        ``source`` label (this registry is itself a rollup) keeps it —
        re-merging rollups preserves the original per-replica
        identities — and if two DIFFERENT inputs still land on one
        gauge key (two pools each holding a replica named "a"), the
        later source is suffixed rather than silently overwriting the
        earlier value."""
        registries = list(registries)
        if sources is not None:
            src_list = [str(s) for s in sources]
            if len(src_list) != len(registries):
                raise ValueError(
                    f"sources has {len(src_list)} entries for "
                    f"{len(registries)} registries — a short list would "
                    f"silently drop replicas from the rollup")
        else:
            src_list = [reg.name for reg in registries]
        out = cls(name)
        seen: Dict[str, int] = {}
        gauge_keys: set = set()
        for reg, src in zip(registries, src_list):
            n = seen.get(src, 0)
            seen[src] = n + 1
            if n:
                src = f"{src}#{n}"
            for key, m in reg._metrics.items():
                base, labels = _parse_key(key)
                if isinstance(m, Counter):
                    out.counter(base, **labels).inc(m.value)
                elif isinstance(m, Gauge):
                    labels.setdefault("source", src)
                    _dedupe_source(base, labels, gauge_keys)
                    out.gauge(base, **labels).set(m.value)
                elif isinstance(m, Histogram):
                    out.histogram(base, alpha=m.alpha,
                                  **labels).merge(m)
        return out

    # --------------------------- exports ------------------------------ #

    def snapshot(self) -> Dict[str, Any]:
        """{"counters": {...}, "gauges": {...}, "histograms": {...}} —
        histogram values are ``summary()`` dicts (count/sum/min/max/
        p50/p90/p99)."""
        out: Dict[str, Any] = {"counters": {}, "gauges": {},
                               "histograms": {}}
        for key, m in sorted(self._metrics.items()):
            if isinstance(m, Counter):
                out["counters"][key] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][key] = m.value
            else:
                out["histograms"][key] = m.summary()
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition: counters/gauges as-is, histograms
        as summaries (quantile label rows + _count/_sum)."""
        lines: List[str] = []
        seen_type = set()
        for key, m in sorted(self._metrics.items()):
            base = key.split("{", 1)[0]
            if isinstance(m, Counter):
                if base not in seen_type:
                    lines.append(f"# TYPE {base} counter")
                    seen_type.add(base)
                lines.append(f"{key} {m.value:g}")
            elif isinstance(m, Gauge):
                if base not in seen_type:
                    lines.append(f"# TYPE {base} gauge")
                    seen_type.add(base)
                lines.append(f"{key} {m.value:g}")
            else:
                if base not in seen_type:
                    lines.append(f"# TYPE {base} summary")
                    seen_type.add(base)
                labels = key[len(base):].strip("{}")
                for q in (0.5, 0.9, 0.99):
                    val = m.quantile(q)
                    if val is None:
                        continue
                    ql = f'quantile="{q}"'
                    full = f"{base}{{{labels + ',' if labels else ''}{ql}}}"
                    lines.append(f"{full} {val:g}")
                lines.append(f"{base}_count{{{labels}}} {m.count}"
                             if labels else f"{base}_count {m.count}")
                lines.append(f"{base}_sum{{{labels}}} {m.sum:g}"
                             if labels else f"{base}_sum {m.sum:g}")
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self, extra: Optional[Dict[str, Any]] = None) -> str:
        blob = {"time": time.time(), "registry": self.name,
                "uptime_s": time.time() - self.created_at}
        if extra:
            blob.update(extra)
        blob.update(self.snapshot())
        series = self.series()
        if series:
            blob["series"] = series
        return json.dumps(blob)

    def export(self, path: str,
               extra: Optional[Dict[str, Any]] = None) -> None:
        """Atomic JSON snapshot publish (tmp + rename) — the file
        ``bin/dstpu_top`` tails; a reader never sees a torn snapshot."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(self.to_json(extra))
        os.replace(tmp, path)

    # ---------------------- monitor bridging -------------------------- #

    def tick(self, step: int) -> None:
        """Drive attached monitor bridges (telemetry.attach_monitor):
        each emits a snapshot to its MonitorMaster every
        ``interval_steps``. Called by the serve observer at commit
        boundaries and usable from any train loop."""
        for b in self._bridges:
            b.step(step)


class _NullMetric:
    """Shared do-nothing handle for counters/gauges/histograms when
    telemetry is off — callers keep their cached-handle code shape."""

    __slots__ = ()
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, n=1.0):
        return

    def set(self, v):
        return

    def observe(self, v, n=1):
        return

    def quantile(self, q):
        return None

    def summary(self):
        return {"count": 0, "sum": 0.0}


_NULL_METRIC = _NullMetric()


class NullRegistry(MetricsRegistry):
    """The DSTPU_TELEMETRY=0 path: every handle is the shared no-op
    metric, every export is empty. ``enabled`` lets callers skip work
    (building label dicts, timestamps) entirely."""

    enabled = False

    def counter(self, name, **labels):
        return _NULL_METRIC

    def gauge(self, name, **labels):
        return _NULL_METRIC

    def histogram(self, name, alpha=0.05, **labels):
        return _NULL_METRIC

    def snapshot(self):
        return {"counters": {}, "gauges": {}, "histograms": {}}

    def tick(self, step):
        return

    def sample(self, now=None):
        return

    def maybe_sample(self, now=None):
        return

    def series(self):
        return {}

    def rate(self, name, window_s=None, **labels):
        return None


def merge_snapshots(snaps: Sequence[Dict[str, Any]],
                    sources: Optional[Iterable[str]] = None
                    ) -> Dict[str, Any]:
    """Merge exported snapshot dicts (``MetricsRegistry.snapshot()`` /
    the ``export()`` JSON) with the same semantics as
    :meth:`MetricsRegistry.merge` — counters sum, gauges gain a
    ``source`` label, histograms rebuild from their exported bucket
    state (:meth:`Histogram.from_state`) and merge bucket-wise exactly.
    This is the cross-process path: N replicas each publish a snapshot
    file, the pool rolls them up without sharing memory. ``sources``
    overrides the per-snapshot label (default: the snapshot's
    ``registry`` name, index-disambiguated)."""
    snaps = list(snaps)
    src_list = list(sources) if sources is not None else [
        snap.get("registry") or f"r{i}" for i, snap in enumerate(snaps)]
    if len(src_list) != len(snaps):
        raise ValueError(
            f"sources has {len(src_list)} entries for {len(snaps)} "
            f"snapshots — a short list would silently drop replicas "
            f"from the rollup")
    seen: Dict[str, int] = {}
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    gauge_keys: set = set()
    hists: Dict[str, Histogram] = {}
    for snap, src in zip(snaps, src_list):
        n = seen.get(src, 0)
        seen[src] = n + 1
        if n:
            src = f"{src}#{n}"
        for key, v in snap.get("counters", {}).items():
            counters[key] = counters.get(key, 0.0) + v
        for key, v in snap.get("gauges", {}).items():
            base, labels = _parse_key(key)
            # an already-rolled-up snapshot's gauges keep their
            # original per-replica source (re-merging rollups must not
            # collapse replicas onto one key); residual collisions
            # (two pools each holding a replica named "a") suffix
            # rather than overwrite
            labels.setdefault("source", src)
            _dedupe_source(base, labels, gauge_keys)
            gauges[_key(base, labels)] = v
        for key, state in snap.get("histograms", {}).items():
            h = Histogram.from_state(state)
            if key in hists:
                hists[key].merge(h)
            else:
                hists[key] = h
    return {
        "registry": f"fleet({len(src_list)})",
        "time": max((s.get("time", 0.0) for s in snaps), default=0.0),
        "counters": dict(sorted(counters.items())),
        "gauges": dict(sorted(gauges.items())),
        "histograms": {k: hists[k].summary() for k in sorted(hists)},
    }


_DEFAULT: Optional[MetricsRegistry] = None


def new_registry(name: str = "default") -> MetricsRegistry:
    """A fresh registry honoring the DSTPU_TELEMETRY kill switch."""
    return MetricsRegistry(name) if telemetry_enabled() else \
        NullRegistry(name)


def get_registry() -> MetricsRegistry:
    """The process-default registry (train-side metrics, comm counters).
    Serve engines carry their OWN registry (``engine.metrics``) so two
    engines in one process — e.g. a drill's dead replica and survivor —
    never mix request stats."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = new_registry("default")
    return _DEFAULT


def set_registry(reg: Optional[MetricsRegistry]) -> None:
    """Install a registry (tests), or None to re-read the env lazily."""
    global _DEFAULT
    _DEFAULT = reg


# ---------------------------------------------------------------------- #
# cross-subsystem recording helpers
# ---------------------------------------------------------------------- #

#: comm-facade op name -> the program auditor's canonical collective
#: kind (analysis/program_audit.py COLLECTIVE_PRIMS values) — the ring
#: builders record their decomposed sites as reduce_scatter/all_gather,
#: so these counters and an audited CollectiveBudget speak the same
#: vocabulary (per-hop execution counts come from the auditor's
#: trip-weighted reports, not from here).
COMM_CANONICAL_KINDS = {
    "all_reduce": "all_reduce",
    "inference_all_reduce": "all_reduce",
    "all_gather": "all_gather",
    "reduce_scatter": "reduce_scatter",
    "ppermute": "ppermute",
    "all_to_all_single": "all_to_all",
    "broadcast": "broadcast",
}


def comm_counter(op: str) -> None:
    """Count a traced collective site on the default registry, keyed by
    canonical kind. Called from ``comm._record`` — TRACE time, like the
    CommsLogger: 'sites the programs being built contain', not per-step
    executions (the auditor's trip-weighted counts cover those)."""
    kind = COMM_CANONICAL_KINDS.get(op)
    if kind is None:
        return
    reg = get_registry()
    if reg.enabled:
        reg.counter("comm_traced_" + kind).inc()


def record_phase_tflops(phase: str, flops_per_step: float,
                        latency_s: float,
                        utilization: Optional[float] = None,
                        registry: Optional[MetricsRegistry] = None
                        ) -> float:
    """Set the phase-labelled achieved-TFLOPS / FLOPs-per-step gauges
    from a model-shape FLOPs estimate plus a measured step time — the
    one roofline formula the flops profiler and the bench phases share
    (satellite: replaces bench-local arithmetic where they overlap).
    Returns the achieved TFLOPS."""
    tf = flops_per_step / latency_s / 1e12 if latency_s > 0 else 0.0
    reg = registry if registry is not None else get_registry()
    if reg.enabled:
        reg.gauge("achieved_tflops", phase=phase).set(tf)
        reg.gauge("flops_per_step", phase=phase).set(flops_per_step)
        if utilization is not None:
            reg.gauge("mxu_utilization", phase=phase).set(utilization)
    return tf
