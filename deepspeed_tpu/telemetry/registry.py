"""Metrics registry — counters, gauges and streaming histograms.

The observability substrate every serving/scheduling decision in
ROADMAP's fleet item keys on (docs/observability.md): per-request SLO
numbers (TTFT/TPOT/queue-wait percentiles, goodput), cache and pool
health, and comm-schedule counters as *first-class engine outputs*
instead of ad-hoc bench arithmetic.

Design constraints (why this is not just a dict of floats):

  * **Host-only, commit-boundary cheap.** Every record call is a few
    Python arithmetic ops on host ints/floats — no device access, no
    locks on the count path. The serve engine records inside its
    existing host-side plan/commit boundaries, so the dslint DSL001
    no-host-sync discipline and the audited zero-callback programs are
    untouched (tier-1 asserts both).
  * **Percentiles without samples.** :class:`Histogram` is a log-bucketed
    streaming sketch (DDSketch-style): bucket ``i`` holds values in
    ``(gamma^(i-1), gamma^i]`` with ``gamma = (1+alpha)/(1-alpha)``, so
    any quantile is answered with relative error <= ``alpha`` (default
    5%) from O(log range) ints — p50/p99 over millions of tokens with no
    sample buffer.
  * **No-op when off.** ``DSTPU_TELEMETRY=0`` routes every caller to the
    :class:`NullRegistry`, whose metric handles are shared do-nothing
    singletons — the zero-overhead kill switch (``bench.py serve_obs``
    measures the on-path against it).

Metric names live in :data:`REGISTERED_METRICS`; the dslint DSL006 rule
keeps that table and the docs/observability.md catalog from drifting in
either direction.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, List, Optional, Tuple

#: metric-name catalog: name -> one-line meaning. The single source of
#: truth dslint DSL006 checks two-way against docs/observability.md's
#: "Metric catalog" table. Keep this a PURE literal dict — the rule
#: reads it from the AST, not by importing this module.
REGISTERED_METRICS = {
    # -- serve request lifecycle (counters) ---------------------------- #
    "serve_requests_admitted": "fresh requests admitted by put()",
    "serve_requests_completed": "requests flushed after clean completion",
    "serve_requests_shed": "requests load-shed (kv_pool_exhausted)",
    "serve_requests_deadline_expired": "requests aborted past deadline",
    "serve_requests_aborted": "requests cancelled via engine.abort()",
    "serve_requests_rejected_draining": "fresh requests refused mid-drain",
    "serve_requests_drained": "live requests manifested by drain()",
    "serve_tokens_committed": "output tokens committed (host-visible)",
    "serve_steps": "engine steps dispatched",
    "serve_steps_device_fed": "steps fed from the device token buffer",
    "serve_step_retries": "transient dispatch failures retried",
    # -- serve latency (histograms, seconds) --------------------------- #
    "serve_ttft_s": "admission -> first committed token",
    "serve_tpot_s": "per-token gap between committed tokens",
    "serve_queue_wait_s": "admission -> first scheduled chunk",
    "serve_plan_s": "per-step plan (scheduler + staging) time",
    "serve_dispatch_s": "per-step dispatch (enqueue) time",
    "serve_commit_block_s": "per-commit blocking readback time",
    # -- prefix cache (counters + gauges) ------------------------------ #
    "prefix_matched_tokens": "prompt tokens served from cached blocks",
    "prefix_prefill_tokens": "prompt tokens that ran a prefill chunk",
    "prefix_cow_copies": "partial-tail copy-on-write block copies",
    "prefix_hit_blocks": "full cached blocks matched",
    "prefix_evicted_blocks": "cached blocks reclaimed under pressure",
    "prefix_cached_blocks": "blocks currently held by the cache",
    "prefix_evictable_blocks": "refcount-0 cached blocks (reclaimable)",
    # -- KV pool (gauges) ---------------------------------------------- #
    "kv_pool_blocks_total": "KV pool capacity in blocks",
    "kv_pool_blocks_free": "allocator-free KV blocks",
    "kv_pool_bytes_total": "KV pool bytes across all chips",
    "kv_pool_bytes_per_chip": "KV pool bytes one chip holds",
    # -- comm schedule (counters, auditor-canonical kinds) ------------- #
    "comm_traced_all_reduce": "all-reduce sites traced (program builds)",
    "comm_traced_all_gather": "all-gather sites traced (incl. ring sites)",
    "comm_traced_reduce_scatter": "reduce-scatter sites traced (incl. ring sites)",
    "comm_traced_ppermute": "raw ppermute sites traced",
    "comm_traced_all_to_all": "all-to-all sites traced",
    "comm_traced_broadcast": "broadcast sites traced",
    # -- FLOPs / roofline (gauges, phase-labelled) --------------------- #
    "achieved_tflops": "achieved TFLOPS for a phase (label: phase)",
    "flops_per_step": "model FLOPs per step for a phase (label: phase)",
    "mxu_utilization": "achieved/peak FLOPs fraction (label: phase)",
}


def telemetry_enabled() -> bool:
    """The process-wide kill switch: ``DSTPU_TELEMETRY=0`` (or
    ``false``/``off``) disables every registry, recorder and bridge."""
    return os.environ.get("DSTPU_TELEMETRY", "1") \
        not in ("0", "false", "off")


class Counter:
    """Monotone float counter. ``inc`` is the hot path — one add."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, n=1.0):
        self.value += n


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v):
        self.value = v


class Histogram:
    """Log-bucketed streaming histogram (DDSketch-style).

    ``observe(v, n)`` adds ``n`` occurrences of value ``v`` to the bucket
    ``ceil(log_gamma(v))``; ``quantile(q)`` walks the (sorted) buckets
    and returns the geometric midpoint of the covering bucket, clamped
    to the observed [min, max] — relative error <= ``alpha`` by
    construction, exact-ish on single-bucket (constant) distributions.
    Non-positive values land in a dedicated zero bucket.
    """

    __slots__ = ("alpha", "gamma", "_lg", "buckets", "zero", "count",
                 "sum", "min", "max")

    def __init__(self, alpha: float = 0.05):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        self.alpha = alpha
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._lg = math.log(self.gamma)
        self.buckets: Dict[int, int] = {}
        self.zero = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v, n=1):
        self.count += n
        self.sum += v * n
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if v <= 0.0:
            self.zero += n
            return
        i = math.ceil(math.log(v) / self._lg)
        b = self.buckets
        b[i] = b.get(i, 0) + n

    def quantile(self, q: float) -> Optional[float]:
        if self.count <= 0:
            return None
        # nearest-rank (1-based ceil(q*n)) — an upper quantile over a
        # tiny count lands on the top value instead of collapsing into
        # the median bucket; converges to interpolated percentiles as
        # counts grow, within the alpha bucket error
        target = q * self.count
        if self.zero and target <= self.zero:
            return min(0.0, self.max)
        acc = self.zero
        for i in sorted(self.buckets):
            acc += self.buckets[i]
            if acc >= target:
                est = 2.0 * self.gamma ** i / (self.gamma + 1.0)
                return max(self.min, min(est, self.max))
        return self.max

    def summary(self) -> Dict[str, Any]:
        if self.count == 0:
            return {"count": 0, "sum": 0.0}
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
        }


def _key(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    """A named family of metrics with snapshot / Prometheus / JSON
    export and optional monitor bridges (telemetry.attach_monitor).

    Metric handles are get-or-create by (name, labels) and safe to cache
    — the serve observer binds its hot counters once at engine build."""

    enabled = True

    def __init__(self, name: str = "default"):
        self.name = name
        self._metrics: Dict[str, Any] = {}
        self._types: Dict[str, str] = {}
        self._bridges: List[Any] = []
        self.created_at = time.time()

    # ------------------------- metric handles ------------------------- #

    def _get(self, kind: str, cls, name: str, labels: Dict[str, Any],
             **kw):
        prev = self._types.get(name)
        if prev is not None and prev != kind:
            raise ValueError(
                f"metric {name!r} already registered as {prev}")
        key = _key(name, labels)
        m = self._metrics.get(key)
        if m is None:
            m = cls(**kw)
            self._metrics[key] = m
            self._types[name] = kind
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str, alpha: float = 0.05,
                  **labels) -> Histogram:
        return self._get("histogram", Histogram, name, labels, alpha=alpha)

    def metric_names(self) -> List[str]:
        """Base metric names (labels stripped) registered so far."""
        return sorted(self._types)

    # --------------------------- exports ------------------------------ #

    def snapshot(self) -> Dict[str, Any]:
        """{"counters": {...}, "gauges": {...}, "histograms": {...}} —
        histogram values are ``summary()`` dicts (count/sum/min/max/
        p50/p90/p99)."""
        out: Dict[str, Any] = {"counters": {}, "gauges": {},
                               "histograms": {}}
        for key, m in sorted(self._metrics.items()):
            if isinstance(m, Counter):
                out["counters"][key] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][key] = m.value
            else:
                out["histograms"][key] = m.summary()
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition: counters/gauges as-is, histograms
        as summaries (quantile label rows + _count/_sum)."""
        lines: List[str] = []
        seen_type = set()
        for key, m in sorted(self._metrics.items()):
            base = key.split("{", 1)[0]
            if isinstance(m, Counter):
                if base not in seen_type:
                    lines.append(f"# TYPE {base} counter")
                    seen_type.add(base)
                lines.append(f"{key} {m.value:g}")
            elif isinstance(m, Gauge):
                if base not in seen_type:
                    lines.append(f"# TYPE {base} gauge")
                    seen_type.add(base)
                lines.append(f"{key} {m.value:g}")
            else:
                if base not in seen_type:
                    lines.append(f"# TYPE {base} summary")
                    seen_type.add(base)
                labels = key[len(base):].strip("{}")
                for q in (0.5, 0.9, 0.99):
                    val = m.quantile(q)
                    if val is None:
                        continue
                    ql = f'quantile="{q}"'
                    full = f"{base}{{{labels + ',' if labels else ''}{ql}}}"
                    lines.append(f"{full} {val:g}")
                lines.append(f"{base}_count{{{labels}}} {m.count}"
                             if labels else f"{base}_count {m.count}")
                lines.append(f"{base}_sum{{{labels}}} {m.sum:g}"
                             if labels else f"{base}_sum {m.sum:g}")
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self, extra: Optional[Dict[str, Any]] = None) -> str:
        blob = {"time": time.time(), "registry": self.name,
                "uptime_s": time.time() - self.created_at}
        if extra:
            blob.update(extra)
        blob.update(self.snapshot())
        return json.dumps(blob)

    def export(self, path: str,
               extra: Optional[Dict[str, Any]] = None) -> None:
        """Atomic JSON snapshot publish (tmp + rename) — the file
        ``bin/dstpu_top`` tails; a reader never sees a torn snapshot."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(self.to_json(extra))
        os.replace(tmp, path)

    # ---------------------- monitor bridging -------------------------- #

    def tick(self, step: int) -> None:
        """Drive attached monitor bridges (telemetry.attach_monitor):
        each emits a snapshot to its MonitorMaster every
        ``interval_steps``. Called by the serve observer at commit
        boundaries and usable from any train loop."""
        for b in self._bridges:
            b.step(step)


class _NullMetric:
    """Shared do-nothing handle for counters/gauges/histograms when
    telemetry is off — callers keep their cached-handle code shape."""

    __slots__ = ()
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, n=1.0):
        return

    def set(self, v):
        return

    def observe(self, v, n=1):
        return

    def quantile(self, q):
        return None

    def summary(self):
        return {"count": 0, "sum": 0.0}


_NULL_METRIC = _NullMetric()


class NullRegistry(MetricsRegistry):
    """The DSTPU_TELEMETRY=0 path: every handle is the shared no-op
    metric, every export is empty. ``enabled`` lets callers skip work
    (building label dicts, timestamps) entirely."""

    enabled = False

    def counter(self, name, **labels):
        return _NULL_METRIC

    def gauge(self, name, **labels):
        return _NULL_METRIC

    def histogram(self, name, alpha=0.05, **labels):
        return _NULL_METRIC

    def snapshot(self):
        return {"counters": {}, "gauges": {}, "histograms": {}}

    def tick(self, step):
        return


_DEFAULT: Optional[MetricsRegistry] = None


def new_registry(name: str = "default") -> MetricsRegistry:
    """A fresh registry honoring the DSTPU_TELEMETRY kill switch."""
    return MetricsRegistry(name) if telemetry_enabled() else \
        NullRegistry(name)


def get_registry() -> MetricsRegistry:
    """The process-default registry (train-side metrics, comm counters).
    Serve engines carry their OWN registry (``engine.metrics``) so two
    engines in one process — e.g. a drill's dead replica and survivor —
    never mix request stats."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = new_registry("default")
    return _DEFAULT


def set_registry(reg: Optional[MetricsRegistry]) -> None:
    """Install a registry (tests), or None to re-read the env lazily."""
    global _DEFAULT
    _DEFAULT = reg


# ---------------------------------------------------------------------- #
# cross-subsystem recording helpers
# ---------------------------------------------------------------------- #

#: comm-facade op name -> the program auditor's canonical collective
#: kind (analysis/program_audit.py COLLECTIVE_PRIMS values) — the ring
#: builders record their decomposed sites as reduce_scatter/all_gather,
#: so these counters and an audited CollectiveBudget speak the same
#: vocabulary (per-hop execution counts come from the auditor's
#: trip-weighted reports, not from here).
COMM_CANONICAL_KINDS = {
    "all_reduce": "all_reduce",
    "inference_all_reduce": "all_reduce",
    "all_gather": "all_gather",
    "reduce_scatter": "reduce_scatter",
    "ppermute": "ppermute",
    "all_to_all_single": "all_to_all",
    "broadcast": "broadcast",
}


def comm_counter(op: str) -> None:
    """Count a traced collective site on the default registry, keyed by
    canonical kind. Called from ``comm._record`` — TRACE time, like the
    CommsLogger: 'sites the programs being built contain', not per-step
    executions (the auditor's trip-weighted counts cover those)."""
    kind = COMM_CANONICAL_KINDS.get(op)
    if kind is None:
        return
    reg = get_registry()
    if reg.enabled:
        reg.counter("comm_traced_" + kind).inc()


def record_phase_tflops(phase: str, flops_per_step: float,
                        latency_s: float,
                        utilization: Optional[float] = None,
                        registry: Optional[MetricsRegistry] = None
                        ) -> float:
    """Set the phase-labelled achieved-TFLOPS / FLOPs-per-step gauges
    from a model-shape FLOPs estimate plus a measured step time — the
    one roofline formula the flops profiler and the bench phases share
    (satellite: replaces bench-local arithmetic where they overlap).
    Returns the achieved TFLOPS."""
    tf = flops_per_step / latency_s / 1e12 if latency_s > 0 else 0.0
    reg = registry if registry is not None else get_registry()
    if reg.enabled:
        reg.gauge("achieved_tflops", phase=phase).set(tf)
        reg.gauge("flops_per_step", phase=phase).set(flops_per_step)
        if utilization is not None:
            reg.gauge("mxu_utilization", phase=phase).set(utilization)
    return tf
