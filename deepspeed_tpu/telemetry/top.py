"""``bin/dstpu_top`` — render serving metrics snapshots.

Reads the atomic JSON export a running engine publishes at
``DSTPU_TELEMETRY_EXPORT`` (every ``DSTPU_TELEMETRY_EXPORT_EVERY``
committed steps) and renders a compact operator view: request outcome
counts and rates, TTFT/TPOT/queue-wait percentiles, goodput, prefix
cache hit fraction and KV pool occupancy. When the snapshot carries the
registry's sampled time series (``series`` — DSTPU_SERIES_* knobs), the
render adds per-window rates and sparklines, so even a ONE-SHOT render
shows the recent rate history. ``--watch N`` refreshes every N seconds
(rates then also derive from consecutive snapshots).

Fleet mode: MULTIPLE export files (repeated ``--file``, positional
paths, or a shell-quoted glob like ``'profiles/replica_*.json'``) are
rolled up through the EXACT cross-process merge
(``telemetry.merge_snapshots`` — counters sum, gauges gain stable
``source`` labels, histogram quantiles equal a single stream over the
union) and rendered as ONE fleet view plus a per-source breakdown line
per replica (docs/observability.md "Fleet rollup").
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple


def _ms(v: Optional[float]) -> str:
    return "-" if v is None else f"{v * 1e3:8.1f}"


def _frac(n: float, d: float) -> Optional[float]:
    return n / d if d else None


def _pct(v: Optional[float]) -> str:
    return "-" if v is None else f"{v * 100:5.1f}%"


def load_snapshot(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(vals: List[float], width: int = 32) -> str:
    """Unicode block sparkline over the last ``width`` values (empty
    string for fewer than 2 points)."""
    vals = [v for v in vals if v is not None][-width:]
    if len(vals) < 2:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[3] * len(vals)
    scale = (len(_SPARK) - 1) / (hi - lo)
    return "".join(_SPARK[int((v - lo) * scale)] for v in vals)


def _series_rates(pairs: List[List[float]]) -> List[float]:
    """Per-window rates from a sampled counter series [[t, v], ...]."""
    out: List[float] = []
    for (t0, v0), (t1, v1) in zip(pairs, pairs[1:]):
        if t1 > t0:
            out.append((v1 - v0) / (t1 - t0))
    return out


def _series_ratio(num: List[List[float]],
                  den: List[List[float]]) -> List[float]:
    """Per-window Δnum/Δden over two counter series sampled on the
    same clock (the speculative accept-rate trend); windows where the
    denominator did not move are skipped."""
    out: List[float] = []
    for (n0, d0), (n1, d1) in zip(zip(num, den), zip(num[1:], den[1:])):
        dd = d1[1] - d0[1]
        if dd > 0:
            out.append((n1[1] - n0[1]) / dd)
    return out


#: attribution components in render order: (label, histogram, bar char,
#: window-dominant initial, counter-label component name)
_ATTRIB_ROWS = (
    ("plan", "serve_plan_s", "█", "p", "plan"),
    ("dispatch", "serve_dispatch_s", "▓", "d", "dispatch"),
    ("execute", "serve_commit_block_s", "▒", "x", "device_execute"),
    ("apply", "serve_commit_apply_s", "░", "c", "commit_apply"),
    ("host gap", "serve_host_gap_s", "·", "g", "host_gap"),
)


def _attrib_fracs(hists: Dict[str, Any], rows=_ATTRIB_ROWS):
    """((label, frac), ...) + dominant label from the component
    histograms' sums; None before any attributed step. ``rows`` selects
    the partition (serve default; _TRAIN_ATTRIB_ROWS for --train)."""
    sums = [(label, float(hists.get(name, {}).get("sum", 0.0)), ch)
            for label, name, ch, _, _ in rows]
    total = sum(s for _, s, _ in sums)
    if total <= 0.0:
        return None
    fracs = [(label, s / total) for label, s, _ in sums]
    dominant = max(sums, key=lambda r: r[1])[0]
    return fracs, dominant


def _attrib_bar(fracs, rows=_ATTRIB_ROWS, width: int = 44) -> str:
    """One-line proportional bar over the step-wall components, each
    component its own fill glyph (legend rides the fraction row)."""
    chars = {label: ch for label, _, ch, _, _ in rows}
    out = ""
    for label, f in fracs:
        out += chars[label] * max(1 if f > 0.005 else 0,
                                  round(f * width))
    return f"[{out[:width + len(fracs)]}]"


def _attrib_window_dominants(series: Dict[str, Any],
                             rows=_ATTRIB_ROWS,
                             counter: str = "serve_attrib_seconds_total",
                             width: int = 32) -> str:
    """Per-sample-window dominant component as a trail of initials (the
    sampled ``*_attrib_seconds_total{component=...}`` counter series):
    one glyph per window, newest right — a drifting dominant (say
    compute windows giving way to host-gap windows) reads at a
    glance."""
    per_comp = {}
    for _, _, _, init, comp in rows:
        key = f'{counter}{{component="{comp}"}}'
        pairs = series.get(key, [])
        if pairs:
            # keyed by sample TIMESTAMP: one registry sample() stamps
            # every live counter with the same clock value, so equal
            # timestamps ARE the same window — while a late-created
            # component (a fused-decode fleet plans nothing until it
            # switches paths) simply has no entry for early windows
            # instead of shifting everyone's alignment
            per_comp[init] = dict(pairs)
    if not per_comp:
        return ""
    times = sorted({t for m in per_comp.values() for t in m})
    if len(times) < 2:
        return ""
    out = []
    for t0, t1 in list(zip(times, times[1:]))[-width:]:
        deltas = {init: m[t1] - m[t0] for init, m in per_comp.items()
                  if t0 in m and t1 in m}
        if not deltas:
            out.append("-")
            continue
        best = max(deltas, key=deltas.get)
        out.append(best if deltas[best] > 0 else "-")
    return "".join(out)


def render(snap: Dict[str, Any], prev: Optional[Dict[str, Any]] = None
           ) -> str:
    """The operator table for one snapshot; ``prev`` (an earlier
    snapshot) turns counter deltas into rates."""
    c = snap.get("counters", {})
    g = snap.get("gauges", {})
    h = snap.get("histograms", {})
    series = snap.get("series", {})

    def series_rate(name: str) -> Optional[float]:
        rates = _series_rates(series.get(name, []))
        return rates[-1] if rates else None

    def rate(name: str) -> str:
        if prev is not None:
            dt = snap.get("time", 0.0) - prev.get("time", 0.0)
            if dt <= 0:
                return "      -"
            d = c.get(name, 0.0) - prev.get("counters", {}).get(name, 0.0)
            return f"{d / dt:7.1f}"
        # one-shot render: the sampled series still yields a rate
        r = series_rate(name)
        return f"{r:7.1f}" if r is not None else "      -"

    lines: List[str] = []
    when = time.strftime("%H:%M:%S",
                         time.localtime(snap.get("time", time.time())))
    lines.append(f"dstpu_top — registry '{snap.get('registry', '?')}' "
                 f"@ {when}  (uptime {snap.get('uptime_s', 0.0):.0f}s)")
    lines.append("")
    lines.append("requests            total     /s")
    for label, name in (("admitted", "serve_requests_admitted"),
                        ("completed", "serve_requests_completed"),
                        ("shed", "serve_requests_shed"),
                        ("deadline", "serve_requests_deadline_expired"),
                        ("adm-reject", "serve_requests_rejected_admission"),
                        ("aborted", "serve_requests_aborted"),
                        ("drained", "serve_requests_drained")):
        lines.append(f"  {label:<14}{c.get(name, 0):9.0f} {rate(name)}")
    good = c.get("serve_requests_completed", 0.0)
    bad = (c.get("serve_requests_shed", 0.0)
           + c.get("serve_requests_deadline_expired", 0.0)
           + c.get("serve_requests_rejected_draining", 0.0)
           + c.get("serve_requests_rejected_admission", 0.0)
           + c.get("serve_requests_aborted", 0.0))
    lines.append(f"  goodput        {_pct(_frac(good, good + bad))}")
    lines.append("")
    lines.append(f"tokens committed {c.get('serve_tokens_committed', 0):11.0f}"
                 f"  {rate('serve_tokens_committed')} tok/s   "
                 f"steps {c.get('serve_steps', 0):.0f} "
                 f"(device-fed {c.get('serve_steps_device_fed', 0):.0f})")
    prop = c.get("spec_proposed", 0.0)
    if prop:
        acc = c.get("spec_accepted", 0.0)
        lines.append(
            f"speculation    proposed {prop:.0f}   accepted {acc:.0f}   "
            f"accept rate {_pct(_frac(acc, prop))}   "
            f"rounds {c.get('spec_rounds', 0):.0f}")
    # disaggregated serving: only rendered once a prefill→decode handoff
    # has actually happened (colocated fleets never pay for the line).
    # A fleet-merged view sums both sides, so seqs counts the prefill
    # exports and adopted the decode-side restores — they diverge only
    # while migrations are in flight or falling back to replay.
    hoff = c.get("serve_handoff_seqs", 0.0)
    if hoff:
        ex = h.get("serve_handoff_exposed_s", {})
        lines.append(
            f"handoff        seqs {hoff:.0f}   "
            f"adopted {c.get('serve_handoff_seqs_in', 0):.0f}   "
            f"replayed {c.get('serve_handoff_fallback_replays', 0):.0f}   "
            f"blocks {c.get('serve_handoff_blocks', 0):.0f}   "
            f"{c.get('serve_handoff_bytes', 0.0) / 1e6:.1f} MB   "
            f"exposed p99 {_ms(ex.get('p99'))} ms")
    lines.append("")
    lines.append("latency (ms)          p50      p90      p99    count")
    for label, name in (("ttft", "serve_ttft_s"),
                        ("tpot", "serve_tpot_s"),
                        ("queue wait", "serve_queue_wait_s"),
                        ("commit block", "serve_commit_block_s")):
        s = h.get(name, {})
        lines.append(f"  {label:<14}{_ms(s.get('p50'))} {_ms(s.get('p90'))}"
                     f" {_ms(s.get('p99'))} {s.get('count', 0):8d}")
    # step-time attribution bar (docs/observability.md "Step-time
    # attribution"): where the committed steps' wall clock went, from
    # the component histograms' sums — plus the per-window dominant
    # component off the sampled serve_attrib_seconds_total series
    attrib = _attrib_fracs(h)
    if attrib is not None:
        fracs, dominant = attrib
        lines.append("")
        lines.append("step time      " + "  ".join(
            f"{name} {_pct(f)}" for name, f in fracs) +
            f"   dominant: {dominant}")
        lines.append("  " + _attrib_bar(fracs))
        doms = _attrib_window_dominants(series, _ATTRIB_ROWS)
        if doms:
            lines.append(f"  dominant/window  {doms}  "
                         f"(p=plan d=dispatch x=execute c=apply "
                         f"g=host-gap)")
    lines.append("")
    hit = c.get("prefix_matched_tokens", 0.0)
    ran = c.get("prefix_prefill_tokens", 0.0)
    def g_sum(name: str) -> float:
        # a fleet-merged snapshot carries gauges under per-replica
        # source labels; the headline row sums them (pool capacity /
        # occupancy across the fleet is the sum of the replicas')
        if name in g:
            return g[name]
        return sum(v for k, v in g.items()
                   if k.split("{", 1)[0] == name)

    lines.append(f"prefix cache   hit frac {_pct(_frac(hit, hit + ran))}"
                 f"   cached {g_sum('prefix_cached_blocks'):.0f}"
                 f" blocks (evictable {g_sum('prefix_evictable_blocks'):.0f})"
                 f"   cow {c.get('prefix_cow_copies', 0):.0f}"
                 f"   evicted {c.get('prefix_evicted_blocks', 0):.0f}")
    demoted = c.get("prefix_demoted_blocks", 0.0)
    host_now = g_sum("prefix_host_blocks")
    if demoted or host_now:
        # hierarchical KV: the host-RAM tier line — resident blocks,
        # demote/promote churn, host-served hits, true losses at the
        # tier's own cap, and the promotion dispatch wait the plan path
        # actually paid (the exposed slice of a demoted hit's cost)
        pw = h.get("prefix_promote_wait_s", {})
        lines.append(
            f"host tier      {host_now:.0f} blocks resident   "
            f"demoted {demoted:.0f}   "
            f"promoted {c.get('prefix_promoted_blocks', 0):.0f}   "
            f"host hits {c.get('prefix_host_hit_blocks', 0):.0f}   "
            f"lost {c.get('prefix_host_evicted_blocks', 0):.0f}   "
            f"promote wait p99 {_ms(pw.get('p99'))} ms")
    total = g_sum("kv_pool_blocks_total")
    free = g_sum("kv_pool_blocks_free")
    per_chip = [v for k, v in g.items()
                if k.split("{", 1)[0] == "kv_pool_bytes_per_chip"]
    lines.append(f"kv pool        occupancy "
                 f"{_pct(_frac(total - free, total))}   "
                 f"{free:.0f}/{total:.0f} blocks free   "
                 f"{max(per_chip, default=0.0) / 1e6:.1f} MB/chip")
    lvls = [v for k, v in g.items()
            if k.split("{", 1)[0] == "admission_level"]
    if lvls or "admission_window" in g:
        # overload-control status (docs/serving.md "Overload control"):
        # which brownout level the fleet is in and why. Window sums
        # across replicas (door concurrency is additive); level takes
        # the WORST replica — a fleet is as browned out as its most
        # pressured member
        from ..serving.admission import BROWNOUT_LEVELS
        lvl = int(max(lvls, default=0.0))
        lvl = min(lvl, len(BROWNOUT_LEVELS) - 1)
        trans = sum(v for k, v in c.items()
                    if k.split("{", 1)[0] == "brownout_transitions")
        lines.append(
            f"admission      window {g_sum('admission_window'):.0f}   "
            f"level {lvl} ({BROWNOUT_LEVELS[lvl]})   "
            f"door rejects {c.get('admission_rejected', 0):.0f}   "
            f"brownout moves {trans:.0f}")
    dropped = c.get("flight_spans_dropped", 0.0)
    if dropped:
        lines.append(f"flight ring    {dropped:.0f} spans dropped "
                     f"(ring wrapped — raise DSTPU_FLIGHT_CAPACITY for "
                     f"longer postmortems)")
    # sampled time series -> per-window rate sparklines (the recent
    # history a single snapshot carries; DSTPU_SERIES_* knobs)
    spark_rows = []
    for label, name in (("admitted/s", "serve_requests_admitted"),
                        ("completed/s", "serve_requests_completed"),
                        ("tokens/s", "serve_tokens_committed")):
        rates = _series_rates(series.get(name, []))
        spark = _sparkline(rates)
        if spark:
            spark_rows.append(f"  {label:<14}{rates[-1]:9.1f}  {spark}")
    # speculative acceptance trend: per-window Δaccepted/Δproposed over
    # the two sampled counter series (windows with no proposals skip)
    accs = _series_ratio(series.get("spec_accepted", []),
                         series.get("spec_proposed", []))
    spark = _sparkline(accs)
    if spark:
        spark_rows.append(f"  {'accept rate':<14}{accs[-1]:9.2f}  {spark}")
    if spark_rows:
        lines.append("")
        lines.append("rates (sampled series)   now  trend")
        lines.extend(spark_rows)
    return "\n".join(lines)


#: train attribution components in render order (label, histogram,
#: bar glyph, dominant-trail initial, counter-label component name)
_TRAIN_ATTRIB_ROWS = (
    ("data wait", "train_data_wait_s", "░", "w", "data_wait"),
    ("stage", "train_stage_s", "█", "s", "stage"),
    ("dispatch", "train_dispatch_s", "▓", "d", "dispatch"),
    ("execute", "train_device_execute_s", "▒", "x", "device_execute"),
    ("apply", "train_commit_apply_s", "·", "c", "commit_apply"),
    ("host gap", "train_host_gap_s", "-", "g", "host_gap"),
)


def render_train(snap: Dict[str, Any],
                 prev: Optional[Dict[str, Any]] = None,
                 per_source: Optional[List[Tuple[str, Dict[str, Any]]]]
                 = None) -> str:
    """The training-observatory view (``--train``): step counts/rates,
    loss + grad norm, the step-time attribution bar, roofline gauges,
    goodput, anomaly counters — and, over several per-host exports, the
    straggler table + laggard line (docs/observability.md "Training
    observatory")."""
    c = snap.get("counters", {})
    g = snap.get("gauges", {})
    h = snap.get("histograms", {})
    series = snap.get("series", {})

    def rate(name: str) -> str:
        if prev is not None:
            dt = snap.get("time", 0.0) - prev.get("time", 0.0)
            if dt > 0:
                d = c.get(name, 0.0) \
                    - prev.get("counters", {}).get(name, 0.0)
                return f"{d / dt:8.2f}"
        rates = _series_rates(series.get(name, []))
        return f"{rates[-1]:8.2f}" if rates else "       -"

    def g_vals(name: str, contains: Optional[str] = None) -> List[float]:
        # a fleet-merged snapshot carries gauges under per-replica
        # source labels — collect every variant of the base name (the
        # same scheme render()'s g_sum uses)
        out = []
        for k, v in g.items():
            if k.split("{", 1)[0] != name:
                continue
            if contains is not None and "{" in k and contains not in k:
                continue
            out.append(v)
        return out

    def g_mean(name: str) -> Optional[float]:
        vals = g_vals(name)
        return sum(vals) / len(vals) if vals else None

    lines: List[str] = []
    when = time.strftime("%H:%M:%S",
                         time.localtime(snap.get("time", time.time())))
    lines.append(f"dstpu_top --train — registry "
                 f"'{snap.get('registry', '?')}' @ {when}  "
                 f"(uptime {snap.get('uptime_s', 0.0):.0f}s)")
    lines.append("")
    wall = h.get("train_step_wall_s", {})
    lines.append(
        f"steps {c.get('train_steps', 0):10.0f}   {rate('train_steps')}"
        f" steps/s   samples {c.get('train_samples', 0):.0f}   "
        f"{rate('train_samples')} samples/s")
    lines.append(
        f"step wall (ms)   p50 {_ms(wall.get('p50'))}   "
        f"p99 {_ms(wall.get('p99'))}   max {_ms(wall.get('max'))}")
    lines.append(
        f"loss {g_mean('train_loss') or 0.0:14.4f}   grad norm "
        f"{g_mean('train_grad_norm') or 0.0:.4f}   skipped "
        f"{c.get('train_steps_skipped', 0):.0f}")
    # roofline gauges (flops profiler publishes {phase="train"} into
    # the SAME registry export, so one file carries the whole story;
    # fleet view: TFLOPS sum across hosts, utilization averaged)
    tfs = g_vals("achieved_tflops", contains='phase="train"')
    if tfs:
        mxus = g_vals("mxu_utilization", contains='phase="train"')
        lines.append(
            f"roofline       {sum(tfs):.2f} TFLOPS   mxu "
            f"{_pct(sum(mxus) / len(mxus) if mxus else None)}")
    # attribution bar + dominant-per-window trail (shared helpers,
    # train partition)
    attrib = _attrib_fracs(h, _TRAIN_ATTRIB_ROWS)
    if attrib is not None:
        fracs, dominant = attrib
        lines.append("")
        lines.append("step time      " + "  ".join(
            f"{name} {_pct(f)}" for name, f in fracs)
            + f"   dominant: {dominant}")
        lines.append("  " + _attrib_bar(fracs, _TRAIN_ATTRIB_ROWS))
        doms = _attrib_window_dominants(
            series, _TRAIN_ATTRIB_ROWS, "train_attrib_seconds_total")
        if doms:
            lines.append(f"  dominant/window  {doms}  "
                         f"(w=data-wait s=stage d=dispatch x=execute "
                         f"c=apply g=host-gap)")
    # goodput: fleet view shows the WORST host (the one to fix)
    gps = g_vals("train_goodput_frac")
    gp = min(gps) if gps else None
    lines.append("")
    lines.append(f"goodput        {_pct(gp)} of wall clock productive"
                 if gp is not None else
                 "goodput        - (no ledger events yet)")
    anomalies = c.get("train_anomalies", 0.0)
    nonfin = c.get("train_nonfinite_steps", 0.0)
    if anomalies or nonfin:
        lines.append(f"ANOMALIES      {anomalies:.0f} sentinel trips "
                     f"({nonfin:.0f} non-finite steps) — flight dumps "
                     f"under DSTPU_FLIGHT_DIR")
    # straggler table over per-host exports
    if per_source and len(per_source) > 1:
        from .train import train_skew_report
        skew = train_skew_report(per_source)
        lines.append("")
        lines.append("per-host            steps   step p50(ms)  "
                     "data-wait p50(ms)  data-wait frac")
        for src, row in sorted(skew["hosts"].items()):
            lines.append(
                f"  {src:<16}{row['steps']:9d}  "
                f"{_ms(row['step_wall_p50_s'])}       "
                f"{_ms(row['data_wait_p50_s'])}          "
                f"{_pct(row['data_wait_frac'])}")
        if skew["laggard"] is not None:
            lines.append(
                f"  straggler: {skew['laggard']} at "
                f"{_ms(skew['max_step_p50_s'])} ms p50 "
                f"({skew['step_time_skew']:.2f}x the median host)")
    # sampled series sparklines
    spark_rows = []
    for label, name in (("steps/s", "train_steps"),
                        ("samples/s", "train_samples")):
        rates = _series_rates(series.get(name, []))
        spark = _sparkline(rates)
        if spark:
            spark_rows.append(f"  {label:<14}{rates[-1]:9.2f}  {spark}")
    if spark_rows:
        lines.append("")
        lines.append("rates (sampled series)   now  trend")
        lines.extend(spark_rows)
    return "\n".join(lines)


def _resolve_paths(file_args: List[str],
                   positional: List[str]) -> List[str]:
    """Expand the --file/positional path set: each entry may be a
    literal path or a glob pattern (shells that did not expand it —
    quoted, or no match locally). Order-stable, de-duplicated."""
    out: List[str] = []
    for raw in list(file_args) + list(positional):
        hits = sorted(_glob.glob(raw)) if _glob.has_magic(raw) else [raw]
        for p in hits or [raw]:
            if p not in out:
                out.append(p)
    return out


def load_fleet(paths: List[str]
               ) -> Tuple[Dict[str, Any], List[Tuple[str, Dict[str, Any]]]]:
    """Load every snapshot and merge EXACTLY (counters sum, gauges gain
    stable source labels, histograms bucket-merge). Sources are the
    snapshots' registry names when unique (the replica-pool path names
    each registry after its replica id), else the file basenames.
    Returns (merged, [(source, snapshot), ...])."""
    from .registry import merge_snapshots
    snaps = [load_snapshot(p) for p in paths]
    names = [s.get("registry") or "" for s in snaps]
    if len(set(names)) == len(snaps) and all(names):
        sources = names
    else:
        sources = [os.path.splitext(os.path.basename(p))[0]
                   for p in paths]
    merged = merge_snapshots(snaps, sources=sources)
    # the merged view keeps the newest uptime so the header stays sane
    merged["uptime_s"] = max((s.get("uptime_s", 0.0) for s in snaps),
                             default=0.0)
    return merged, list(zip(sources, snaps))


def render_sources(per_source: List[Tuple[str, Dict[str, Any]]]) -> str:
    """The per-replica breakdown under a fleet render: one line per
    source file with its own outcome counts, token total and TTFT p99."""
    lines = ["", "per-source breakdown        admitted completed     "
                 "tokens  ttft p99(ms)"]
    for src, snap in per_source:
        c = snap.get("counters", {})
        h = snap.get("histograms", {}).get("serve_ttft_s", {})
        lines.append(
            f"  {src:<24}{c.get('serve_requests_admitted', 0):10.0f}"
            f"{c.get('serve_requests_completed', 0):10.0f}"
            f"{c.get('serve_tokens_committed', 0):11.0f}"
            f"  {_ms(h.get('p99'))}")
    return "\n".join(lines)


def merge_trace_files(paths: List[str], out_path: str) -> int:
    """``--merge-trace``: merge flight-dump Chrome traces into one
    fleet timeline and summarize the request tracks it reconstructs
    (docs/observability.md "Distributed tracing")."""
    from .flight_recorder import (atomic_json_dump, merge_chrome_traces,
                                  request_tracks)
    if len(paths) < 1:
        print("dstpu_top --merge-trace: need at least one flight dump",
              file=sys.stderr)
        return 2
    dumps = []
    for p in paths:
        try:
            dumps.append(load_snapshot(p))
        except (OSError, ValueError) as e:
            print(f"dstpu_top: unreadable flight dump {p}: {e}",
                  file=sys.stderr)
            return 2
    sources = [os.path.splitext(os.path.basename(p))[0] for p in paths]
    if len(set(sources)) != len(sources):
        # two replicas each writing flight_0.json into their own dir
        # must NOT collapse onto one source — that would re-introduce
        # the same-uid tid collision the merge exists to fix. Prefer
        # dir/basename; suffix any residual duplicates.
        sources = [os.path.join(os.path.basename(os.path.dirname(
            os.path.abspath(p))), s) for p, s in zip(paths, sources)]
        seen: Dict[str, int] = {}
        for i, s in enumerate(sources):
            n = seen.get(s, 0)
            seen[s] = n + 1
            if n:
                sources[i] = f"{s}#{n}"
    try:
        merged = merge_chrome_traces(dumps, sources)
    except ValueError as e:
        print(f"dstpu_top: {e}", file=sys.stderr)
        return 2
    atomic_json_dump(out_path, merged)
    tracks = request_tracks(merged)
    n_ev = sum(1 for e in merged["traceEvents"] if e.get("ph") != "M")
    print(f"merged {len(dumps)} flight dumps -> {out_path}: "
          f"{n_ev} spans, {len(tracks)} request tracks, "
          f"{merged['otherData']['spans_dropped']} dropped")
    cross = 0
    for name, evs in sorted(tracks.items()):
        srcs = sorted({e.get('args', {}).get('source') for e in evs})
        if len(srcs) > 1:
            cross += 1
        print(f"  {name:<32}{len(evs):4d} spans   "
              f"sources: {', '.join(s for s in srcs if s)}")
    if cross:
        print(f"  ({cross} track(s) span multiple sources — "
              f"drain/replay continuations stitched by trace context)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu_top",
        description="render one serving engine's telemetry export, or "
                    "merge several replicas' exports into one fleet "
                    "view (docs/observability.md)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="export file(s); globs accepted — more than "
                         "one renders the merged fleet view")
    ap.add_argument("--file", action="append", default=[],
                    help="export file or glob (repeatable; default: "
                         "$DSTPU_TELEMETRY_EXPORT)")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="refresh every N seconds (0 = one-shot)")
    ap.add_argument("--train", action="store_true",
                    help="render the training-observatory view (step "
                         "rates, attribution bar, goodput, anomaly "
                         "counters; several per-host exports add the "
                         "straggler table)")
    ap.add_argument("--merge-trace", metavar="OUT", default=None,
                    help="treat the paths as flight-recorder Chrome-"
                         "trace dumps, merge them onto one fleet "
                         "timeline (tracks namespaced by source, "
                         "trace-context spans stitched across "
                         "replicas) and write the merged trace to OUT")
    args = ap.parse_args(argv)
    paths = _resolve_paths(args.file, args.paths)
    if args.merge_trace:
        return merge_trace_files(paths, args.merge_trace)
    if not paths and os.environ.get("DSTPU_TELEMETRY_EXPORT"):
        paths = [os.environ["DSTPU_TELEMETRY_EXPORT"]]
    if not paths:
        print("dstpu_top: no export file (--file, paths or "
              "DSTPU_TELEMETRY_EXPORT)", file=sys.stderr)
        return 2
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"dstpu_top: export file not found: {missing[0]} — is "
              f"the engine running with DSTPU_TELEMETRY_EXPORT set?",
              file=sys.stderr)
        return 2
    prev = None
    while True:
        try:
            if len(paths) == 1:
                snap = load_snapshot(paths[0])
                out = render_train(snap, prev) if args.train \
                    else render(snap, prev)
            else:
                snap, per_source = load_fleet(paths)
                if args.train:
                    out = render_train(snap, prev,
                                       per_source=per_source)
                else:
                    out = render(snap, prev) + "\n" \
                        + render_sources(per_source)
        except (OSError, ValueError) as e:
            print(f"dstpu_top: unreadable snapshot: {e}",
                  file=sys.stderr)
            return 2
        if args.watch > 0:
            print("\x1b[2J\x1b[H" + out, flush=True)
        else:
            print(out)
            return 0
        prev = snap
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
