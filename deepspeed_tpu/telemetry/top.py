"""``bin/dstpu_top`` — render a serving engine's metrics snapshot.

Reads the atomic JSON export a running engine publishes at
``DSTPU_TELEMETRY_EXPORT`` (every ``DSTPU_TELEMETRY_EXPORT_EVERY``
committed steps) and renders a compact operator view: request outcome
counts and rates, TTFT/TPOT/queue-wait percentiles, goodput, prefix
cache hit fraction and KV pool occupancy. When the snapshot carries the
registry's sampled time series (``series`` — DSTPU_SERIES_* knobs), the
render adds per-window rates and sparklines, so even a ONE-SHOT render
shows the recent rate history. ``--watch N`` refreshes every N seconds
(rates then also derive from consecutive snapshots).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional


def _ms(v: Optional[float]) -> str:
    return "-" if v is None else f"{v * 1e3:8.1f}"


def _frac(n: float, d: float) -> Optional[float]:
    return n / d if d else None


def _pct(v: Optional[float]) -> str:
    return "-" if v is None else f"{v * 100:5.1f}%"


def load_snapshot(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(vals: List[float], width: int = 32) -> str:
    """Unicode block sparkline over the last ``width`` values (empty
    string for fewer than 2 points)."""
    vals = [v for v in vals if v is not None][-width:]
    if len(vals) < 2:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[3] * len(vals)
    scale = (len(_SPARK) - 1) / (hi - lo)
    return "".join(_SPARK[int((v - lo) * scale)] for v in vals)


def _series_rates(pairs: List[List[float]]) -> List[float]:
    """Per-window rates from a sampled counter series [[t, v], ...]."""
    out: List[float] = []
    for (t0, v0), (t1, v1) in zip(pairs, pairs[1:]):
        if t1 > t0:
            out.append((v1 - v0) / (t1 - t0))
    return out


def render(snap: Dict[str, Any], prev: Optional[Dict[str, Any]] = None
           ) -> str:
    """The operator table for one snapshot; ``prev`` (an earlier
    snapshot) turns counter deltas into rates."""
    c = snap.get("counters", {})
    g = snap.get("gauges", {})
    h = snap.get("histograms", {})
    series = snap.get("series", {})

    def series_rate(name: str) -> Optional[float]:
        rates = _series_rates(series.get(name, []))
        return rates[-1] if rates else None

    def rate(name: str) -> str:
        if prev is not None:
            dt = snap.get("time", 0.0) - prev.get("time", 0.0)
            if dt <= 0:
                return "      -"
            d = c.get(name, 0.0) - prev.get("counters", {}).get(name, 0.0)
            return f"{d / dt:7.1f}"
        # one-shot render: the sampled series still yields a rate
        r = series_rate(name)
        return f"{r:7.1f}" if r is not None else "      -"

    lines: List[str] = []
    when = time.strftime("%H:%M:%S",
                         time.localtime(snap.get("time", time.time())))
    lines.append(f"dstpu_top — registry '{snap.get('registry', '?')}' "
                 f"@ {when}  (uptime {snap.get('uptime_s', 0.0):.0f}s)")
    lines.append("")
    lines.append("requests            total     /s")
    for label, name in (("admitted", "serve_requests_admitted"),
                        ("completed", "serve_requests_completed"),
                        ("shed", "serve_requests_shed"),
                        ("deadline", "serve_requests_deadline_expired"),
                        ("aborted", "serve_requests_aborted"),
                        ("drained", "serve_requests_drained")):
        lines.append(f"  {label:<14}{c.get(name, 0):9.0f} {rate(name)}")
    good = c.get("serve_requests_completed", 0.0)
    bad = (c.get("serve_requests_shed", 0.0)
           + c.get("serve_requests_deadline_expired", 0.0)
           + c.get("serve_requests_rejected_draining", 0.0)
           + c.get("serve_requests_aborted", 0.0))
    lines.append(f"  goodput        {_pct(_frac(good, good + bad))}")
    lines.append("")
    lines.append(f"tokens committed {c.get('serve_tokens_committed', 0):11.0f}"
                 f"  {rate('serve_tokens_committed')} tok/s   "
                 f"steps {c.get('serve_steps', 0):.0f} "
                 f"(device-fed {c.get('serve_steps_device_fed', 0):.0f})")
    lines.append("")
    lines.append("latency (ms)          p50      p90      p99    count")
    for label, name in (("ttft", "serve_ttft_s"),
                        ("tpot", "serve_tpot_s"),
                        ("queue wait", "serve_queue_wait_s"),
                        ("commit block", "serve_commit_block_s")):
        s = h.get(name, {})
        lines.append(f"  {label:<14}{_ms(s.get('p50'))} {_ms(s.get('p90'))}"
                     f" {_ms(s.get('p99'))} {s.get('count', 0):8d}")
    lines.append("")
    hit = c.get("prefix_matched_tokens", 0.0)
    ran = c.get("prefix_prefill_tokens", 0.0)
    lines.append(f"prefix cache   hit frac {_pct(_frac(hit, hit + ran))}"
                 f"   cached {g.get('prefix_cached_blocks', 0):.0f}"
                 f" blocks (evictable {g.get('prefix_evictable_blocks', 0):.0f})"
                 f"   cow {c.get('prefix_cow_copies', 0):.0f}"
                 f"   evicted {c.get('prefix_evicted_blocks', 0):.0f}")
    total = g.get("kv_pool_blocks_total", 0.0)
    free = g.get("kv_pool_blocks_free", 0.0)
    lines.append(f"kv pool        occupancy "
                 f"{_pct(_frac(total - free, total))}   "
                 f"{free:.0f}/{total:.0f} blocks free   "
                 f"{g.get('kv_pool_bytes_per_chip', 0) / 1e6:.1f} MB/chip")
    dropped = c.get("flight_spans_dropped", 0.0)
    if dropped:
        lines.append(f"flight ring    {dropped:.0f} spans dropped "
                     f"(ring wrapped — raise DSTPU_FLIGHT_CAPACITY for "
                     f"longer postmortems)")
    # sampled time series -> per-window rate sparklines (the recent
    # history a single snapshot carries; DSTPU_SERIES_* knobs)
    spark_rows = []
    for label, name in (("admitted/s", "serve_requests_admitted"),
                        ("completed/s", "serve_requests_completed"),
                        ("tokens/s", "serve_tokens_committed")):
        rates = _series_rates(series.get(name, []))
        spark = _sparkline(rates)
        if spark:
            spark_rows.append(f"  {label:<14}{rates[-1]:9.1f}  {spark}")
    if spark_rows:
        lines.append("")
        lines.append("rates (sampled series)   now  trend")
        lines.extend(spark_rows)
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu_top",
        description="render a serving engine's telemetry export "
                    "(docs/observability.md)")
    ap.add_argument("--file", default=None,
                    help="export file (default: $DSTPU_TELEMETRY_EXPORT)")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SECONDS",
                    help="refresh every N seconds (0 = one-shot)")
    args = ap.parse_args(argv)
    path = args.file or os.environ.get("DSTPU_TELEMETRY_EXPORT")
    if not path:
        print("dstpu_top: no export file (--file or "
              "DSTPU_TELEMETRY_EXPORT)", file=sys.stderr)
        return 2
    if not os.path.exists(path):
        print(f"dstpu_top: export file not found: {path} — is the "
              f"engine running with DSTPU_TELEMETRY_EXPORT set?",
              file=sys.stderr)
        return 2
    prev = None
    while True:
        try:
            snap = load_snapshot(path)
        except (OSError, ValueError) as e:
            print(f"dstpu_top: unreadable snapshot: {e}",
                  file=sys.stderr)
            return 2
        out = render(snap, prev)
        if args.watch > 0:
            print("\x1b[2J\x1b[H" + out, flush=True)
        else:
            print(out)
            return 0
        prev = snap
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
