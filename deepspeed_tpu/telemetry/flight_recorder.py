"""Phase flight recorder — a bounded ring of serve-loop spans.

Postmortems of a wedged or preempted replica keep asking the same
question: *what was the engine doing right before it died?* The watchdog
names the last phase and collective; this module keeps the last N
plan/dispatch/commit/drain/replay spans (reusing the exact phase names
the watchdog brackets carry) in a fixed-size ring and dumps them as
Chrome-trace JSON (``chrome://tracing`` / Perfetto "Load trace") when
something goes wrong:

  * **watchdog fire** — ``StepWatchdog.check_once`` auto-dumps on a
    diagnosed stall, so the trace shows the seconds leading into it;
  * **fault-drill crash** — ``FaultInjector.maybe_fire`` dumps before it
    raises or ``os._exit``s, so every drill leaves a trace artifact the
    drill result asserts on;
  * **drain** — the engine dumps at cooperative preemption, pairing the
    replay manifest with the timeline that led to it.

Dumps land under ``DSTPU_FLIGHT_DIR`` (unset = auto-dump disabled; the
ring itself is always recording — append cost is a lock + tuple). The
ring is bounded (``DSTPU_FLIGHT_CAPACITY``, default 512 spans) so a
month-long serving process holds a constant-size recorder.

Span times use ``time.perf_counter`` (monotonic, sub-µs); the dump
carries a wall-clock anchor so traces can be correlated across replicas.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

#: every live recorder, for crash-path auto-dumps (weak: a flushed
#: engine's recorder must not be kept alive by the dump hook)
_RECORDERS: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()


def flight_dir() -> Optional[str]:
    return os.environ.get("DSTPU_FLIGHT_DIR") or None


def flight_capacity() -> int:
    return int(os.environ.get("DSTPU_FLIGHT_CAPACITY", "512") or "512")


def register_recorder(rec: "FlightRecorder") -> None:
    _RECORDERS.add(rec)


def auto_dump(reason: str) -> List[str]:
    """Dump every live recorder to DSTPU_FLIGHT_DIR (no-op when unset).
    Crash-path safe: never raises — a failed dump must not mask the
    fault being reported. Returns the paths written."""
    d = flight_dir()
    if not d:
        return []
    paths: List[str] = []
    for rec in list(_RECORDERS):
        name = f"flight_{reason}_{os.getpid()}_{id(rec) & 0xffff:04x}.json"
        path = os.path.join(d, name)
        try:
            rec.dump(path, reason=reason)
            paths.append(path)
        except Exception:
            # never-raises contract: a failed dump (disk, or a span arg
            # json.dump rejects) must not mask the crash/drain being
            # reported — drain() calls this with state already released
            pass
    return paths


class FlightRecorder:
    """Bounded ring of (name, t0, t1, step, args) spans.

    Two recording styles share the ring:

      * :meth:`phase` — watchdog-style transitions: starting phase B
        closes the open phase A span; ``phase("idle")`` closes without
        opening (the serve loop's step_end). This is the hot path — the
        engine calls it at its existing plan/dispatch/commit brackets.
      * :meth:`span` / :meth:`record` — explicit bracketed spans for
        long operations (drain, replay, checkpoint save).
    """

    def __init__(self, capacity: Optional[int] = None):
        cap = flight_capacity() if capacity is None else int(capacity)
        self.capacity = max(1, cap)
        self._ring: deque = deque(maxlen=self.capacity)
        self._open: Optional[Tuple[str, float, Optional[int]]] = None
        self._lock = threading.Lock()
        #: spans silently evicted by ring wrap — monotone; surfaced as
        #: the ``flight_spans_dropped`` registry counter and in every
        #: dump header, so a trace that only shows the last N spans
        #: SAYS how much history it lost
        self.dropped = 0
        # wall-clock anchor: perf_counter t=anchor_perf corresponds to
        # wall time anchor_wall (cross-replica correlation)
        self.anchor_perf = time.perf_counter()
        self.anchor_wall = time.time()

    # --------------------------- recording ---------------------------- #

    def phase(self, name, step=None):
        """Transition to ``name`` (closing any open span); "idle" only
        closes. Registered DSL001 hot path — lock + tuple append."""
        now = time.perf_counter()
        with self._lock:
            if self._open is not None:
                n0, t0, s0 = self._open
                if len(self._ring) == self.capacity:
                    self.dropped += 1
                self._ring.append((n0, t0, now, s0, None))
            self._open = None if name == "idle" else (name, now, step)

    def record(self, name, t0, t1, step=None, args=None):
        """Append a completed span. Registered DSL001 hot path."""
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append((name, t0, t1, step, args))

    def event(self, name, step=None, duration=0.0, **args):
        """Instant (or ``duration``-long, ending now) span — the
        request-lifecycle marks (admit/first-token/finish) the serve
        observer tags with ``uid`` so one request's life reads off a
        single dump. Registered DSL001 hot path."""
        t1 = time.perf_counter()
        self.record(name, t1 - duration, t1, step=step,
                    args=args or None)

    @contextmanager
    def span(self, name: str, step: Optional[int] = None, **args):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self.record(name, t0, time.perf_counter(), step=step,
                        args=args or None)

    # ---------------------------- reading ----------------------------- #

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def spans(self) -> List[Tuple]:
        """Snapshot copy of the ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def to_chrome_trace(self, reason: Optional[str] = None
                        ) -> Dict[str, Any]:
        """Chrome-trace JSON ("Trace Event Format"): complete ("X")
        events in µs relative to the oldest span, one pid per process.
        Loadable directly in chrome://tracing or Perfetto."""
        spans = self.spans
        base = spans[0][1] if spans else self.anchor_perf
        events = []
        for name, t0, t1, step, args in spans:
            ev: Dict[str, Any] = {
                "name": name,
                "ph": "X",
                "ts": round((t0 - base) * 1e6, 1),
                "dur": round((t1 - t0) * 1e6, 1),
                "pid": os.getpid(),
                # uid-tagged request spans land on a per-request track
                # (tid = uid + 1; track 0 stays the engine phase lane)
                # so one request's admit->...->finish life reads as one
                # row in chrome://tracing / Perfetto
                "tid": int(args["uid"]) + 1
                if args and "uid" in args else 0,
            }
            a = dict(args) if args else {}
            if step is not None:
                a["step"] = step
            if a:
                ev["args"] = a
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "source": "dstpu.flight_recorder",
                "reason": reason,
                "capacity": self.capacity,
                "spans_dropped": self.dropped,
                "wall_time_base": self.anchor_wall
                + (base - self.anchor_perf),
            },
        }

    def dump(self, path: str, reason: Optional[str] = None) -> None:
        """Atomic Chrome-trace publish (tmp + rename)."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.to_chrome_trace(reason=reason), f)
        os.replace(tmp, path)
