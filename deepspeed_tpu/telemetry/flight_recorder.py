"""Phase flight recorder — a bounded ring of serve-loop spans.

Postmortems of a wedged or preempted replica keep asking the same
question: *what was the engine doing right before it died?* The watchdog
names the last phase and collective; this module keeps the last N
plan/dispatch/commit/drain/replay spans (reusing the exact phase names
the watchdog brackets carry) in a fixed-size ring and dumps them as
Chrome-trace JSON (``chrome://tracing`` / Perfetto "Load trace") when
something goes wrong:

  * **watchdog fire** — ``StepWatchdog.check_once`` auto-dumps on a
    diagnosed stall, so the trace shows the seconds leading into it;
  * **fault-drill crash** — ``FaultInjector.maybe_fire`` dumps before it
    raises or ``os._exit``s, so every drill leaves a trace artifact the
    drill result asserts on;
  * **drain** — the engine dumps at cooperative preemption, pairing the
    replay manifest with the timeline that led to it.

Dumps land under ``DSTPU_FLIGHT_DIR`` (unset = auto-dump disabled; the
ring itself is always recording — append cost is a lock + tuple). The
ring is bounded (``DSTPU_FLIGHT_CAPACITY``, default 512 spans) so a
month-long serving process holds a constant-size recorder.

Span times use ``time.perf_counter`` (monotonic, sub-µs); the dump
carries a wall-clock anchor so traces can be correlated across replicas.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

#: every live recorder, for crash-path auto-dumps (weak: a flushed
#: engine's recorder must not be kept alive by the dump hook)
_RECORDERS: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()


def flight_dir() -> Optional[str]:
    return os.environ.get("DSTPU_FLIGHT_DIR") or None


def flight_capacity() -> int:
    return int(os.environ.get("DSTPU_FLIGHT_CAPACITY", "512") or "512")


def register_recorder(rec: "FlightRecorder") -> None:
    _RECORDERS.add(rec)


def auto_dump(reason: str) -> List[str]:
    """Dump every live recorder to DSTPU_FLIGHT_DIR (no-op when unset).
    Crash-path safe: never raises — a failed dump must not mask the
    fault being reported. Returns the paths written."""
    d = flight_dir()
    if not d:
        return []
    paths: List[str] = []
    for rec in list(_RECORDERS):
        name = f"flight_{reason}_{os.getpid()}_{id(rec) & 0xffff:04x}.json"
        path = os.path.join(d, name)
        try:
            rec.dump(path, reason=reason)
            paths.append(path)
        except Exception:
            # never-raises contract: a failed dump (disk, or a span arg
            # json.dump rejects) must not mask the crash/drain being
            # reported — drain() calls this with state already released
            pass
    return paths


class FlightRecorder:
    """Bounded ring of (name, t0, t1, step, args) spans.

    Two recording styles share the ring:

      * :meth:`phase` — watchdog-style transitions: starting phase B
        closes the open phase A span; ``phase("idle")`` closes without
        opening (the serve loop's step_end). This is the hot path — the
        engine calls it at its existing plan/dispatch/commit brackets.
      * :meth:`span` / :meth:`record` — explicit bracketed spans for
        long operations (drain, replay, checkpoint save).
    """

    def __init__(self, capacity: Optional[int] = None):
        cap = flight_capacity() if capacity is None else int(capacity)
        self.capacity = max(1, cap)
        self._ring: deque = deque(maxlen=self.capacity)
        self._open: Optional[Tuple[str, float, Optional[int]]] = None
        self._lock = threading.Lock()
        #: spans silently evicted by ring wrap — monotone; surfaced as
        #: the ``flight_spans_dropped`` registry counter and in every
        #: dump header, so a trace that only shows the last N spans
        #: SAYS how much history it lost
        self.dropped = 0
        # wall-clock anchor: perf_counter t=anchor_perf corresponds to
        # wall time anchor_wall (cross-replica correlation)
        self.anchor_perf = time.perf_counter()
        self.anchor_wall = time.time()

    # --------------------------- recording ---------------------------- #

    def phase(self, name, step=None):
        """Transition to ``name`` (closing any open span); "idle" only
        closes. Registered DSL001 hot path — lock + tuple append."""
        now = time.perf_counter()
        with self._lock:
            if self._open is not None:
                n0, t0, s0 = self._open
                if len(self._ring) == self.capacity:
                    self.dropped += 1
                self._ring.append((n0, t0, now, s0, None))
            self._open = None if name == "idle" else (name, now, step)

    def record(self, name, t0, t1, step=None, args=None):
        """Append a completed span. Registered DSL001 hot path."""
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append((name, t0, t1, step, args))

    def event(self, name, step=None, duration=0.0, **args):
        """Instant (or ``duration``-long, ending now) span — the
        request-lifecycle marks (admit/first-token/finish) the serve
        observer tags with ``uid`` so one request's life reads off a
        single dump. Registered DSL001 hot path."""
        t1 = time.perf_counter()
        self.record(name, t1 - duration, t1, step=step,
                    args=args or None)

    @contextmanager
    def span(self, name: str, step: Optional[int] = None, **args):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self.record(name, t0, time.perf_counter(), step=step,
                        args=args or None)

    # ---------------------------- reading ----------------------------- #

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def spans(self) -> List[Tuple]:
        """Snapshot copy of the ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def to_chrome_trace(self, reason: Optional[str] = None
                        ) -> Dict[str, Any]:
        """Chrome-trace JSON ("Trace Event Format"): complete ("X")
        events in µs relative to the oldest span, one pid per process.
        Loadable directly in chrome://tracing or Perfetto."""
        spans = self.spans
        base = spans[0][1] if spans else self.anchor_perf
        events = []
        for name, t0, t1, step, args in spans:
            ev: Dict[str, Any] = {
                "name": name,
                "ph": "X",
                "ts": round((t0 - base) * 1e6, 1),
                "dur": round((t1 - t0) * 1e6, 1),
                "pid": os.getpid(),
                # uid-tagged request spans land on a per-request track
                # (tid = uid + 1; track 0 stays the engine phase lane)
                # so one request's admit->...->finish life reads as one
                # row in chrome://tracing / Perfetto
                "tid": int(args["uid"]) + 1
                if args and "uid" in args else 0,
            }
            a = dict(args) if args else {}
            if step is not None:
                a["step"] = step
            if a:
                ev["args"] = a
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "source": "dstpu.flight_recorder",
                "reason": reason,
                "capacity": self.capacity,
                "spans_dropped": self.dropped,
                "wall_time_base": self.anchor_wall
                + (base - self.anchor_perf),
            },
        }

    def dump(self, path: str, reason: Optional[str] = None) -> None:
        """Atomic Chrome-trace publish (tmp + rename)."""
        atomic_json_dump(path, self.to_chrome_trace(reason=reason))


def atomic_json_dump(path: str, obj: Any) -> None:
    """The one copy of the atomic JSON publish (makedirs + tmp.{pid} +
    rename) the trace/snapshot writers share — a reader never sees a
    torn file."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


# ---------------------------------------------------------------------- #
# fleet trace merge (docs/observability.md "Distributed tracing")
# ---------------------------------------------------------------------- #


def merge_chrome_traces(dumps: List[Dict[str, Any]],
                        sources: List[str]) -> Dict[str, Any]:
    """Merge N replicas' Chrome-trace flight dumps into ONE fleet
    timeline, on one clock and with collision-free tracks.

    * **Clock alignment**: each dump's timestamps are µs relative to its
      own oldest span; its ``otherData.wall_time_base`` anchors that
      origin on the wall clock. The merge rebases every event onto the
      earliest dump's origin, so spans from different replicas land in
      true fleet order.
    * **Track namespacing** (the tid-collision fix): a single dump gives
      each uid the track ``tid = uid + 1`` — concatenating dumps would
      therefore fold DIFFERENT requests that happen to share a uid
      number on two replicas onto one track. Here every uid track is
      keyed by ``(source, uid)`` instead, and every engine phase lane by
      its source, each getting a fresh merged tid plus a ``thread_name``
      metadata row naming it.
    * **Trace-context stitching**: spans carrying a ``trace`` arg (the
      fleet trace context minted at ``ReplicaPool.put``) key their track
      on the TRACE ID alone — so one request's spans from the router,
      the replica that first served it, and the survivor that replayed
      it after a drain all land on ONE gapless track, while untraced
      same-uid requests stay apart.

    ``sources`` names each dump (replica ids / file basenames); a short
    list is refused rather than silently mislabelling."""
    if len(sources) != len(dumps):
        raise ValueError(
            f"{len(sources)} sources for {len(dumps)} dumps — every "
            f"dump needs its replica id (tracks are namespaced by it)")
    bases = []
    for d, src in zip(dumps, sources):
        base = d.get("otherData", {}).get("wall_time_base")
        if base is None:
            # a foreign/hand-trimmed trace without the anchor would
            # default to wall 0 and shift every REAL dump by ~50 years
            # of microseconds — refuse instead of silently producing a
            # garbage timeline
            raise ValueError(
                f"dump {src!r} has no otherData.wall_time_base — not a "
                f"FlightRecorder dump; merge needs the wall anchor to "
                f"align clocks")
        bases.append(float(base))
    base0 = min(bases) if bases else 0.0
    tids: Dict[Tuple, int] = {}
    names: Dict[int, str] = {}
    # engine phase lanes first, in source order, so lane k is replica k
    for i, src in enumerate(sources):
        tids[("engine", src)] = i
        names[i] = f"engine {src}"

    def tid_of(key: Tuple, label: str) -> int:
        t = tids.get(key)
        if t is None:
            t = len(tids)
            tids[key] = t
            names[t] = label
        return t

    events: List[Dict[str, Any]] = []
    dropped = 0
    for dump, src, wtb in zip(dumps, sources, bases):
        off_us = (wtb - base0) * 1e6
        dropped += int(dump.get("otherData", {}).get("spans_dropped", 0))
        for ev in dump.get("traceEvents", []):
            if ev.get("ph") == "M":
                continue                      # re-derived below
            args = ev.get("args") or {}
            trace = args.get("trace")
            uid = args.get("uid")
            if trace is not None:
                t = tid_of(("trace", trace), f"req {trace}")
            elif uid is not None:
                t = tid_of(("uid", src, uid), f"req {src}/uid{uid}")
            elif ev.get("tid", 0) == 0:
                t = tids[("engine", src)]
            else:
                t = tid_of(("t", src, ev["tid"]),
                           f"{src} t{ev['tid']}")
            out = dict(ev)
            out["pid"] = 0
            out["tid"] = t
            out["ts"] = round(ev.get("ts", 0.0) + off_us, 1)
            a = dict(args)
            a["source"] = src
            out["args"] = a
            events.append(out)
    events.sort(key=lambda e: (e["ts"], e["tid"]))
    meta = [{"ph": "M", "pid": 0, "tid": t, "name": "thread_name",
             "args": {"name": names[t]}} for t in sorted(names)]
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "dstpu.flight_recorder/merge",
            "sources": list(sources),
            "spans_dropped": dropped,
            "wall_time_base": base0,
        },
    }


def request_tracks(merged: Dict[str, Any]
                   ) -> Dict[str, List[Dict[str, Any]]]:
    """{track name: [events, ts-ordered]} for every request track of a
    merged trace (``req ...`` thread names) — what the fleet tests and
    the ``dstpu_top --merge-trace`` summary walk to assert a drained
    request reconstructs gapless end-to-end."""
    names: Dict[int, str] = {}
    for ev in merged.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            names[ev["tid"]] = ev["args"]["name"]
    out: Dict[str, List[Dict[str, Any]]] = {}
    for ev in merged.get("traceEvents", []):
        if ev.get("ph") == "M":
            continue
        name = names.get(ev.get("tid"))
        if name is not None and name.startswith("req "):
            out.setdefault(name, []).append(ev)
    for evs in out.values():
        evs.sort(key=lambda e: e["ts"])
    return out
