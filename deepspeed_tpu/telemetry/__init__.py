"""Telemetry — metrics registry, phase flight recorder, trace hooks.

The observability substrate (docs/observability.md): counters, gauges
and log-bucketed streaming histograms with Prometheus/JSON export
(:mod:`.registry`), a bounded ring of plan/dispatch/commit/drain/replay
spans dumped as Chrome-trace JSON on watchdog fire / fault-drill crash /
drain (:mod:`.flight_recorder`), per-request SLO instrumentation for the
v2 serve engine (:mod:`.serve`), a MonitorMaster bridge
(:mod:`.monitor_bridge`), optional ``jax.profiler`` capture
(:mod:`.trace`) and the ``bin/dstpu_top`` renderer (:mod:`.top`).

Kill switch: ``DSTPU_TELEMETRY=0`` — every registry call becomes a
shared no-op and the serve engine skips instrumentation entirely.
"""

from .attribution import (ATTRIBUTION_COMPONENTS,
                          TRAIN_ATTRIBUTION_COMPONENTS,
                          attribution_report, comm_share,
                          component_totals, train_attribution_report)
from .flight_recorder import (FlightRecorder, auto_dump, flight_dir,
                              merge_chrome_traces, register_recorder,
                              request_tracks)
from .goodput import (goodput_from_ledgers, goodput_report,
                      load_ledger_events)
from .loadgen import (LoadResult, PoissonArrivals, Request,
                      TraceArrivals, UniformArrivals, WorkloadMix,
                      build_requests, run_open_loop, sweep_capacity)
from .monitor_bridge import MonitorBridge, attach_monitor
from .registry import (COMM_CANONICAL_KINDS, REGISTERED_METRICS, Counter,
                       Gauge, Histogram, MetricsRegistry, NullRegistry,
                       comm_counter, get_registry, merge_snapshots,
                       new_registry, record_phase_tflops, set_registry,
                       telemetry_enabled)
from .serve import ServeObserver, serve_observer
from .trace import annotate, maybe_trace, trace_dir
from .train import (TrainObserver, train_comm_share, train_observer,
                    train_skew_report)

__all__ = [
    "ATTRIBUTION_COMPONENTS", "COMM_CANONICAL_KINDS", "Counter",
    "FlightRecorder", "Gauge", "Histogram", "LoadResult",
    "MetricsRegistry", "MonitorBridge", "NullRegistry",
    "PoissonArrivals", "REGISTERED_METRICS", "Request", "ServeObserver",
    "TRAIN_ATTRIBUTION_COMPONENTS", "TraceArrivals", "TrainObserver",
    "UniformArrivals", "WorkloadMix", "annotate",
    "attach_monitor", "attribution_report", "auto_dump",
    "build_requests", "comm_counter", "comm_share", "component_totals",
    "flight_dir", "get_registry", "goodput_from_ledgers",
    "goodput_report", "load_ledger_events", "maybe_trace",
    "merge_chrome_traces", "merge_snapshots", "new_registry",
    "record_phase_tflops", "register_recorder", "request_tracks",
    "run_open_loop", "serve_observer", "set_registry", "sweep_capacity",
    "telemetry_enabled", "trace_dir", "train_attribution_report",
    "train_comm_share", "train_observer", "train_skew_report",
]
