"""Goodput ledger — where a training run's WALL CLOCK went.

The restart ledger (``resilience/ledger.py``) records what happened
(launches, crashes, checkpoints); this module integrates those events
into an exact partition of the run's wall clock — the ETTR-style number
("effective training time ratio") a fleet operator actually plans
around. Every second of ``[t0, t_end]`` lands in exactly ONE bucket:

  * ``productive``      — a worker was up and doing NEW work (steps the
    run had never durably reached before);
  * ``checkpoint_save`` — inside a ``checkpoint_save`` event's
    ``[t_start, t_end]`` interval (the save tax);
  * ``restart_lost``    — downtime between worker runs PLUS the tail of
    a CRASHED run after its last durable checkpoint: that compute was
    discarded, so it buys nothing (a cooperative drain writes an urgent
    checkpoint first and loses ~nothing);
  * ``replay_catchup``  — after a restart, the time spent re-running
    steps the previous incarnation had already attempted (resume →
    the ``train_caught_up`` marker the train observer records when the
    step counter passes the prior incarnation's high-water mark);
  * ``stall``           — inside an explicit ``train_stall`` event
    interval (the observer records one when a step's wall blows past
    its rolling median by ``DSTPU_TRAIN_OBS_STALL_FACTOR``).

``buckets sum to total wall EXACTLY by construction`` — the partition is
a boundary sweep over labelled intervals with a fixed priority
(checkpoint_save > stall > replay_catchup > productive inside worker
time; everything outside worker time is restart_lost), not five
independent estimators. ``train_goodput_frac = productive / total``.

Event sources merge freely (:func:`load_ledger_events`): the elastic
agent's supervisor ledger (``DSTPU_RESTART_LEDGER`` — launch / restart /
success / drained, now carrying ``t_start``/``t_end``) and the train
observer's own ledger (``DSTPU_TRAIN_LEDGER`` — train_start /
checkpoint_save / train_resume / train_progress / train_caught_up /
train_stall). Old ledgers (pre-stamp events carrying only ``time`` and
``runtime_s``) stay readable — stamps are reconstructed from those
fields.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: events that OPEN a worker-up interval
_OPENERS = ("launch", "train_start")
#: events that CLOSE a worker-up interval (the agent records them at
#: worker exit); ``crashed`` tells the sweep whether the tail after the
#: last durable checkpoint was discarded
_TERMINALS = {
    "success": False,
    "drained": False,          # cooperative: urgent checkpoint landed
    "restart": True,           # crash OR membership change (flag below)
    "giveup": True,
}

#: the bucket names, in report order
BUCKETS = ("productive", "checkpoint_save", "restart_lost",
           "replay_catchup", "stall")


def _t_start(e: Dict[str, Any]) -> Optional[float]:
    """Interval start of an event: explicit ``t_start``, else
    reconstructed from the legacy ``time``/``runtime_s`` pair, else the
    instant ``time``."""
    if e.get("t_start") is not None:
        return float(e["t_start"])
    t = e.get("time")
    if t is None:
        return None
    if e.get("runtime_s") is not None:
        return float(t) - float(e["runtime_s"])
    return float(t)


def _t_end(e: Dict[str, Any]) -> Optional[float]:
    if e.get("t_end") is not None:
        return float(e["t_end"])
    t = e.get("time")
    return float(t) if t is not None else None


def _is_crash(e: Dict[str, Any]) -> bool:
    kind = e.get("event")
    if kind == "restart":
        # a membership-change exit checkpointed cooperatively first
        return not bool(e.get("membership_change"))
    return bool(_TERMINALS.get(kind, False))


def load_ledger_events(paths: Sequence[Optional[str]]
                       ) -> List[Dict[str, Any]]:
    """Merge the events of several restart-ledger JSON files (missing /
    unreadable paths are skipped), sorted by event time — the agent's
    supervisor ledger and the train observer's ledger combine into one
    timeline this way."""
    events: List[Dict[str, Any]] = []
    for path in paths:
        if not path or not os.path.exists(path):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                events.extend(json.load(f).get("events", []))
        except (OSError, ValueError):
            continue
    events.sort(key=lambda e: e.get("time", _t_start(e) or 0.0))
    return events


def _worker_intervals(events: Sequence[Dict[str, Any]],
                      t_end: float) -> List[Tuple[float, float, bool]]:
    """(start, end, crashed) worker-up intervals from opener/terminal
    events. An opener while another interval is still open (two
    incarnations writing one observer ledger with no supervisor in
    between) closes the previous interval at its last recorded
    activity — a process that died silently must not count its
    post-mortem gap as up-time."""
    out: List[Tuple[float, float, bool]] = []
    open_start: Optional[float] = None
    open_kind: Optional[str] = None
    last_activity: Optional[float] = None
    for e in events:
        kind = e.get("event")
        ts = _t_start(e)
        if ts is None:
            continue
        if kind in _OPENERS:
            if open_start is not None:
                if kind == "train_start" and open_kind == "launch":
                    # the supervisor's launch already covers this
                    # incarnation — the observer's own start marker is
                    # activity inside it, not a second opener (a split
                    # here would misfile the engine-build span between
                    # launch and observer attach as downtime)
                    last_activity = max(last_activity or ts, ts)
                    continue
                close = max(open_start, last_activity
                            if last_activity is not None else open_start)
                out.append((open_start, min(close, ts), True))
            open_start = ts
            open_kind = kind
            last_activity = ts
        elif kind in _TERMINALS:
            te = _t_end(e)
            start = open_start if open_start is not None else ts
            if te is not None:
                out.append((start, max(start, te), _is_crash(e)))
            open_start = None
            last_activity = None
        else:
            te = _t_end(e)
            if te is not None:
                last_activity = max(last_activity or te, te)
    if open_start is not None:        # still running at report time
        out.append((open_start, max(open_start, t_end), False))
    return out


def _clip(a0: float, a1: float, b0: float, b1: float
          ) -> Optional[Tuple[float, float]]:
    lo, hi = max(a0, b0), min(a1, b1)
    return (lo, hi) if hi > lo else None


def _coverage(segments: List[Tuple[float, float]],
              intervals: List[Tuple[float, float]]) -> List[bool]:
    """Per-segment "covered by any interval" via an active-count sweep
    — O((n+m) log(n+m)) instead of per-segment interval scans, which
    went quadratic on month-long checkpoint histories. Segments are
    sorted and non-overlapping, and every interval endpoint is also a
    segment boundary, so a segment midpoint never sits on an endpoint:
    processing boundary events ``<= mid`` reproduces the half-open
    ``s <= mid < e`` membership exactly."""
    bounds: List[Tuple[float, int]] = []
    for s, e in intervals:
        bounds.append((s, 1))
        bounds.append((e, -1))
    bounds.sort()
    out: List[bool] = []
    i = 0
    active = 0
    for a, b in segments:
        mid = (a + b) / 2.0
        while i < len(bounds) and bounds[i][0] <= mid:
            active += bounds[i][1]
            i += 1
        out.append(active > 0)
    return out


def goodput_report(events: Iterable[Dict[str, Any]],
                   t0: Optional[float] = None,
                   t_end: Optional[float] = None) -> Dict[str, Any]:
    """Integrate ledger ``events`` into the exact wall-clock partition
    described in the module docstring. ``t0``/``t_end`` default to the
    earliest event start / latest event end; pass ``t_end=time.time()``
    for a live run. Buckets sum to ``total_wall_s`` exactly."""
    evs = [e for e in events if isinstance(e, dict) and e.get("event")]
    # record time orders the opener/terminal state machine correctly
    # (a terminal's t_start is its LAUNCH time — sorting on that would
    # hoist it above the run's own checkpoint events)
    evs.sort(key=lambda e: e["time"] if e.get("time") is not None
             else (_t_start(e) or 0.0))
    starts = [t for t in (_t_start(e) for e in evs) if t is not None]
    ends = [t for t in (_t_end(e) for e in evs) if t is not None]
    if not starts:
        return {"total_wall_s": 0.0,
                "buckets": {b: 0.0 for b in BUCKETS},
                "train_goodput_frac": None, "worker_runs": 0,
                "events": 0}
    lo = min(starts) if t0 is None else float(t0)
    hi = max(ends + starts) if t_end is None else float(t_end)
    hi = max(hi, lo)
    total = hi - lo

    workers = [(max(w0, lo), min(w1, hi), crashed)
               for w0, w1, crashed in _worker_intervals(evs, hi)
               if min(w1, hi) > max(w0, lo)]

    # labelled sub-intervals, clipped per worker during the sweep
    ckpts = [(s, e) for s, e in
             ((_t_start(ev), _t_end(ev)) for ev in evs
              if ev.get("event") == "checkpoint_save")
             if s is not None and e is not None and e > s]
    stalls = [(s, e) for s, e in
              ((_t_start(ev), _t_end(ev)) for ev in evs
               if ev.get("event") in ("train_stall", "stall"))
              if s is not None and e is not None and e > s]

    buckets = {b: 0.0 for b in BUCKETS}
    worker_time = sum(w1 - w0 for w0, w1, _ in workers)
    buckets["restart_lost"] += total - worker_time

    for w0, w1, crashed in workers:
        # catchup span: worker start -> the caught_up marker (a resume
        # that never caught up spends its whole incarnation replaying)
        def _in_window(ev) -> bool:
            ts = _t_start(ev)
            # explicit None check: a legitimate stamp of exactly 0.0
            # (relative-timestamp ledgers) must not read as missing
            return ts is not None and w0 <= ts <= w1

        caught = [_t_start(ev) for ev in evs
                  if ev.get("event") == "train_caught_up"
                  and _in_window(ev)]
        resumed = any(ev.get("event") == "train_resume"
                      and int(ev.get("step") or 0) > 0
                      and _in_window(ev) for ev in evs)
        catch_hi = min(caught) if caught else (w1 if resumed else w0)
        # crashed incarnation: everything after the last durable
        # checkpoint end was discarded — label it restart_lost
        lost_lo = w1
        if crashed:
            durable = [e for s, e in ckpts if w0 <= e <= w1]
            lost_lo = max(durable) if durable else w0
        # boundary sweep with fixed priority (active-count coverage —
        # linearithmic in events, not quadratic)
        w_ckpts = [iv for iv in (_clip(s, e, w0, w1)
                                 for s, e in ckpts) if iv]
        w_stalls = [iv for iv in (_clip(s, e, w0, w1)
                                  for s, e in stalls) if iv]
        points = {w0, w1}
        for s, e in w_ckpts + w_stalls:
            points.update((s, e))
        points.update(p for p in (catch_hi, lost_lo) if w0 <= p <= w1)
        pts = sorted(points)
        segs = list(zip(pts, pts[1:]))
        in_ckpt = _coverage(segs, w_ckpts)
        in_stall = _coverage(segs, w_stalls)
        for (a, b), ck, st in zip(segs, in_ckpt, in_stall):
            mid = (a + b) / 2.0
            if ck:
                buckets["checkpoint_save"] += b - a
            elif st:
                buckets["stall"] += b - a
            elif crashed and mid >= lost_lo:
                buckets["restart_lost"] += b - a
            elif mid < catch_hi:
                buckets["replay_catchup"] += b - a
            else:
                buckets["productive"] += b - a

    return {
        "t0": lo,
        "t_end": hi,
        "total_wall_s": total,
        "buckets": buckets,
        "train_goodput_frac": (buckets["productive"] / total)
        if total > 0 else None,
        "worker_runs": len(workers),
        "events": len(evs),
    }


def goodput_from_ledgers(paths: Sequence[Optional[str]],
                         t_end: Optional[float] = None
                         ) -> Dict[str, Any]:
    """:func:`goodput_report` over the merged events of several ledger
    files — the one-call path the fault drill and ``dstpu_top --train``
    use."""
    return goodput_report(load_ledger_events(paths), t_end=t_end)
