"""Registry -> MonitorMaster bridge.

``telemetry.attach_monitor(master, interval_steps)`` makes every writer
the monitor layer already multiplexes (TensorBoard/CSV/W&B/Comet —
``monitor/monitor.py``) receive periodic registry snapshots for free:
counters and gauges as scalars, histograms as their p50/p99/count
triple. The registry's ``tick(step)`` (called by the serve observer at
commit boundaries, or by any train loop) drives the cadence; nothing is
emitted between intervals, so the monitor write amplification is
bounded regardless of request rate.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from .registry import MetricsRegistry, get_registry

Event = Tuple[str, float, int]


class MonitorBridge:
    def __init__(self, master, interval_steps: int = 100,
                 registry: Optional[MetricsRegistry] = None,
                 prefix: str = "telemetry"):
        self.master = master
        self.interval_steps = max(1, int(interval_steps))
        self.registry = registry if registry is not None else get_registry()
        self.prefix = prefix
        self._last_step: Optional[int] = None

    def step(self, step: int) -> None:
        """Emit iff ``interval_steps`` have elapsed since the last emit
        (the first call always emits)."""
        if self._last_step is not None \
                and step - self._last_step < self.interval_steps:
            return
        self._last_step = step
        self.emit(step)

    def emit(self, step: int) -> None:
        snap = self.registry.snapshot()
        events: List[Event] = []
        p = self.prefix
        for name, value in snap.get("counters", {}).items():
            events.append((f"{p}/{name}", float(value), step))
        for name, value in snap.get("gauges", {}).items():
            events.append((f"{p}/{name}", float(value), step))
        for name, summ in snap.get("histograms", {}).items():
            events.append((f"{p}/{name}/count",
                           float(summ.get("count", 0)), step))
            for q in ("p50", "p99"):
                if summ.get(q) is not None:
                    events.append((f"{p}/{name}/{q}", float(summ[q]),
                                   step))
        if events:
            self.master.write_events(events)


def attach_monitor(master, interval_steps: int = 100,
                   registry: Optional[MetricsRegistry] = None,
                   prefix: str = "telemetry") -> MonitorBridge:
    """Attach ``master`` (a MonitorMaster or any object with
    ``write_events``) to ``registry`` (default: the process registry):
    a snapshot is written every ``interval_steps`` registry ticks."""
    reg = registry if registry is not None else get_registry()
    bridge = MonitorBridge(master, interval_steps, reg, prefix)
    reg._bridges.append(bridge)
    return bridge
