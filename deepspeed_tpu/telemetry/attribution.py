"""Step-time attribution — where every millisecond of a serve step goes.

The observatory can say a step was slow; this module says WHY. The
serve observer (telemetry/serve.py) already brackets the pipeline's
host-side boundaries; with ``DSTPU_ATTRIB=1`` (default) it additionally
closes the books on every committed step, so a step's wall clock
decomposes into:

  * ``plan``            — scheduler + staged-buffer fill
    (``serve_plan_s``);
  * ``dispatch``        — compiled-step enqueue (``serve_dispatch_s``;
    fused decode/verify dispatches land here too);
  * ``device_execute``  — the exposed device wait at the commit's
    blocking readback (``serve_commit_block_s``): device time the
    pipeline failed to hide under host work;
  * ``commit_apply``    — host-side commit application after the
    readback: token bookkeeping, journal appends, rollbacks, deferred
    flushes (``serve_commit_apply_s``);
  * ``host_gap``        — the RESIDUAL: loop time inside the serve loop
    but outside every bracket (resume scans, deadline sweeps, ring
    bookkeeping, GC pauses — ``serve_host_gap_s``). This is the
    component a "mysteriously slow" step usually hides in, which is
    why it is measured as the closure of the sum rather than by
    enumerating its causes;
  * ``promote_wait``    — the hierarchical-KV promotion dispatch wait
    the ADMISSION path pays (``prefix_promote_wait_s``; put()-side, so
    it is reported as its own component, not part of the step sum).

By construction ``plan + dispatch + device_execute + commit_apply +
host_gap`` equals the serve loop's wall clock (each step's wall is the
interval between commit boundaries; the loop exit closes the tail), so
the components sum to externally measured step wall-clock within
tolerance — ``bench.py serve_attrib`` gates exactly that. Everything is
host-side ``perf_counter`` arithmetic at existing boundaries: traced
programs gain 0 host callbacks and the warm path 0 fresh compiles with
attribution on (same gates as the PR 8 observer).

The **audited-collective share** rides along without any device timer:
the program auditor's trip-weighted reports give the steady decode
program's exact per-step collective hop count (ring-decomposed
schedules included) and — new here — its trip-weighted ``dot_general``
count, so :func:`comm_share` derives an op-level comm-vs-compute split
of ``device_execute`` straight from the compiled schedule. It is a
schedule-derived share (ops, not seconds): honest about what host-side
observation can know, and exactly the per-knob evidence the autotuning
item needs (a schedule with 4x the hops at the same device_execute is
hiding its comm; one with rising device_execute AND rising hop share is
comm-bound).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

#: component -> the histogram whose SUM carries its seconds. Order is
#: the attribution bar's render order (dstpu_top); the first five are
#: the step-wall partition, promote_wait is admission-side.
ATTRIBUTION_COMPONENTS = (
    ("plan", "serve_plan_s"),
    ("dispatch", "serve_dispatch_s"),
    ("device_execute", "serve_commit_block_s"),
    ("commit_apply", "serve_commit_apply_s"),
    ("host_gap", "serve_host_gap_s"),
    ("promote_wait", "prefix_promote_wait_s"),
)

#: the components that partition one committed step's wall clock
STEP_WALL_COMPONENTS = ("plan", "dispatch", "device_execute",
                        "commit_apply", "host_gap")

#: the TRAIN-side partition (telemetry/train.py,
#: docs/observability.md "Training observatory"): one committed
#: train_batch's wall clock — the interval between step-exit
#: boundaries — decomposes into these six, host_gap again the closure
#: of the sum. data_wait is the between-step span (the caller's data
#: fetch), checkpoint saves between steps ride commit_apply.
TRAIN_ATTRIBUTION_COMPONENTS = (
    ("data_wait", "train_data_wait_s"),
    ("stage", "train_stage_s"),
    ("dispatch", "train_dispatch_s"),
    ("device_execute", "train_device_execute_s"),
    ("commit_apply", "train_commit_apply_s"),
    ("host_gap", "train_host_gap_s"),
)

TRAIN_STEP_WALL_COMPONENTS = tuple(c for c, _ in
                                   TRAIN_ATTRIBUTION_COMPONENTS)

TRAIN_WALL_HIST = "train_step_wall_s"


def _hist_sums(snap: Mapping[str, Any]) -> Dict[str, float]:
    """{histogram name: sum seconds} from a registry snapshot (the
    ``snapshot()`` dict or an exported JSON blob)."""
    hists = snap.get("histograms", {})
    out: Dict[str, float] = {}
    for key, s in hists.items():
        out[key.split("{", 1)[0]] = float(s.get("sum", 0.0))
    return out


def component_totals(snap: Mapping[str, Any],
                     prev: Optional[Mapping[str, Any]] = None,
                     components: Any = ATTRIBUTION_COMPONENTS
                     ) -> Dict[str, float]:
    """Per-component attributed seconds from a snapshot — deltas against
    ``prev`` when given (the measured-window discipline every bench
    sibling uses: warm-up must not pollute the gated numbers).
    ``components`` selects the partition (serve default;
    :data:`TRAIN_ATTRIBUTION_COMPONENTS` for the train observer)."""
    cur = _hist_sums(snap)
    old = _hist_sums(prev) if prev is not None else {}
    return {comp: max(0.0, cur.get(h, 0.0) - old.get(h, 0.0))
            for comp, h in components}


def step_wall_total(snap: Mapping[str, Any],
                    prev: Optional[Mapping[str, Any]] = None,
                    wall_hist: str = "serve_step_wall_s") -> float:
    """Total step wall-clock seconds the observer accounted
    (``serve_step_wall_s`` / ``train_step_wall_s`` sum, optionally
    delta'd)."""
    cur = _hist_sums(snap).get(wall_hist, 0.0)
    old = _hist_sums(prev).get(wall_hist, 0.0) \
        if prev is not None else 0.0
    return max(0.0, cur - old)


def attribution_report(snap: Mapping[str, Any],
                       prev: Optional[Mapping[str, Any]] = None,
                       components: Any = ATTRIBUTION_COMPONENTS,
                       wall_components: Any = STEP_WALL_COMPONENTS,
                       wall_hist: str = "serve_step_wall_s"
                       ) -> Dict[str, Any]:
    """The attribution summary over a snapshot (or a window between two
    snapshots): per-component seconds and fractions of the step wall,
    the dominant component, and the closure error
    (``|wall − Σ components| / wall`` — the quantity the serve_attrib /
    train_obs benches gate; a large residual means a new unbracketed
    code path crept into the loop). Defaults cover the serve partition;
    pass the TRAIN_* tables for the train observer."""
    comps = component_totals(snap, prev, components=components)
    wall = step_wall_total(snap, prev, wall_hist=wall_hist)
    step_sum = sum(comps[c] for c in wall_components)
    denom = wall if wall > 0 else step_sum
    out: Dict[str, Any] = {
        "components_s": {c: round(v, 6) for c, v in comps.items()},
        "step_wall_s": round(wall, 6),
        "components_sum_s": round(step_sum, 6),
        "closure_err_frac": round(abs(wall - step_sum) / denom, 6)
        if denom > 0 else None,
        "fracs": {c: round(comps[c] / denom, 4) if denom > 0 else None
                  for c in wall_components},
    }
    if denom > 0:
        out["dominant"] = max(wall_components,
                              key=lambda c: comps[c])
    else:
        out["dominant"] = None
    return out


def train_attribution_report(snap: Mapping[str, Any],
                             prev: Optional[Mapping[str, Any]] = None
                             ) -> Dict[str, Any]:
    """:func:`attribution_report` over the train observer's partition."""
    return attribution_report(
        snap, prev, components=TRAIN_ATTRIBUTION_COMPONENTS,
        wall_components=TRAIN_STEP_WALL_COMPONENTS,
        wall_hist=TRAIN_WALL_HIST)


def share_from_report(rep: Any, program: str) -> Dict[str, Any]:
    """The comm-op share dict from one trip-weighted
    :class:`~..analysis.program_audit.ProgramReport` — the ONE copy of
    the arithmetic :func:`comm_share` (serve) and
    ``telemetry.train.train_comm_share`` share."""
    coll = rep.total_collectives
    dots = rep.dot_generals
    return {
        "program": program,
        "collectives_per_step": coll,
        "by_kind": dict(sorted(rep.by_kind().items())),
        "dot_generals_per_step": dots,
        "comm_op_share": round(coll / (coll + dots), 4)
        if coll + dots else 0.0,
        "host_callbacks": rep.host_callbacks,
    }


def comm_share(engine, program: str = "step_greedy_fb"
               ) -> Optional[Dict[str, Any]]:
    """The audited-collective share of one serve program's device work,
    derived entirely from the program auditor's trip-weighted jaxpr
    counts (0 host callbacks, 0 device timers): per-step collective
    executions by kind, the trip-weighted GEMM count, and their
    op-level ratio — the schedule-derived comm-vs-compute split of the
    ``device_execute`` component. Report-time only (lowers the program;
    never call on the hot path). None when the program is unavailable
    on this runner."""
    from ..analysis.program_audit import audit_serve_programs
    try:
        reports = audit_serve_programs(engine, programs=(program,))
    except (AttributeError, NotImplementedError):
        return None
    rep = reports.get(program)
    if rep is None:
        return None
    return share_from_report(rep, program)
