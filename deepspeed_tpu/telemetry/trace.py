"""Optional ``jax.profiler`` hooks, gated on ``DSTPU_TRACE_DIR``.

The flight recorder answers "what was the HOST doing"; a real device
timeline needs the XLA profiler. These helpers make that a zero-code
knob: set ``DSTPU_TRACE_DIR`` and the bench phases (and any caller of
:func:`maybe_trace`) capture a TensorBoard-loadable trace of their
measured window; unset, both helpers are inert nullcontexts — no jax
import, no overhead.
"""

from __future__ import annotations

import os
from contextlib import contextmanager, nullcontext
from typing import Optional


def trace_dir() -> Optional[str]:
    return os.environ.get("DSTPU_TRACE_DIR") or None


@contextmanager
def maybe_trace(label: str = "dstpu"):
    """``jax.profiler.trace`` around the body when DSTPU_TRACE_DIR is
    set (trace lands in ``<dir>/<label>``); yields whether tracing is
    active."""
    d = trace_dir()
    if not d:
        yield False
        return
    import jax
    jax.profiler.start_trace(os.path.join(d, label))
    try:
        yield True
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """A ``jax.profiler.TraceAnnotation`` context when tracing is
    enabled (names host spans inside the captured device timeline),
    else a free nullcontext."""
    if not trace_dir():
        return nullcontext()
    import jax
    return jax.profiler.TraceAnnotation(name)
