"""Train-side telemetry observer — step-time attribution, goodput,
straggler and anomaly instrumentation for the training engine.

The serve engine got the full observability stack in PRs 8/9/14; this
module gives ``runtime/engine.py`` the same discipline (ISSUE 15,
docs/observability.md "Training observatory"). One object the engine
owns (``engine._train_obs``; None when ``DSTPU_TRAIN_OBS=0`` or
``DSTPU_TELEMETRY=0`` — the kill switch restores the exact pre-observer
``train_batch`` path), recording ONLY at the train loop's existing
host-side boundaries:

  * **step-time attribution** — every committed ``train_batch``
    decomposes into ``data_wait`` (the between-step span: the caller's
    data fetch) / ``stage`` (validation, watchdog/profiler arming,
    offload swap-in) / ``dispatch`` (the compiled-step call) /
    ``device_execute`` (the one sanctioned blocking readback) /
    ``commit_apply`` (metrics readback, loss-scale + monitor +
    checkpoint bookkeeping) / ``host_gap`` (the CLOSURE of the sum:
    wall between step-exit boundaries minus every bracket), so the six
    components ≡ measured wall by construction — the same closure
    discipline ``serve_attrib`` gates, gated here by
    ``bench.py train_obs``;
  * **goodput** — checkpoint saves, resumes and step progress land as
    stamped events in a :class:`~..resilience.ledger.RestartLedger`
    (``DSTPU_TRAIN_LEDGER``); at export boundaries the observer
    integrates them (merged with the elastic agent's supervisor ledger,
    ``DSTPU_RESTART_LEDGER``) through :mod:`.goodput` into the
    ``train_goodput_frac`` gauge;
  * **straggler evidence** — the per-host registry is named
    ``train@<host>`` (``DSTPU_TRAIN_OBS_HOST``, default the jax process
    index), so N hosts' exports roll up through the existing
    ``MetricsRegistry.merge`` source scheme and
    :func:`train_skew_report` names the laggard;
  * **anomaly sentinel** — the compiled step reduces a non-finite
    loss/grad-norm flag into ``StepMetrics.nonfinite`` IN-PROGRAM (no
    new callbacks — audited), the observer reads it after the
    sanctioned block plus keeps a windowed z-score on the loss series;
    either tripping increments a counter, records a ``train_anomaly``
    flight event and auto-dumps the ring — a NaN'd or spiking run
    leaves forensics behind.

Everything on the record path is pre-bound counter/histogram arithmetic
over host floats (dslint DSL001-registered); the ONE device sync the
observer adds is the explicit ``block_until_ready`` that defines the
``device_execute`` bracket — it subsumes the sync ``_maybe_log`` /
the watchdog pay anyway, and ``bench.py train_obs`` gates the whole
record path at ≤3% overhead with 0 fresh warm-path compiles.
"""

from __future__ import annotations

import math
import os
import time
from collections import deque
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from .attribution import (TRAIN_ATTRIBUTION_COMPONENTS,
                          share_from_report, train_attribution_report)
from .flight_recorder import FlightRecorder, auto_dump, register_recorder
from .registry import MetricsRegistry, new_registry, telemetry_enabled


def train_obs_enabled() -> bool:
    """DSTPU_TRAIN_OBS (default on) gates the whole observer; 0 is the
    exact pre-observer train_batch path."""
    return os.environ.get("DSTPU_TRAIN_OBS", "1") \
        not in ("0", "false", "off")


def train_observer(engine) -> Optional["TrainObserver"]:
    """The engine's attach point: a TrainObserver, or None when either
    kill switch (DSTPU_TELEMETRY / DSTPU_TRAIN_OBS) is off — the engine
    then never calls into this module again."""
    if not telemetry_enabled() or not train_obs_enabled():
        return None
    return TrainObserver(engine)


def _host_id() -> str:
    hid = os.environ.get("DSTPU_TRAIN_OBS_HOST")
    if hid:
        return hid
    try:
        import jax
        return str(jax.process_index())
    except Exception:
        return "0"


class TrainObserver:
    def __init__(self, engine):
        self.engine = engine
        self.host = _host_id()
        self.registry: MetricsRegistry = new_registry(f"train@{self.host}")
        self.flight = FlightRecorder()
        register_recorder(self.flight)
        # env knobs read with LITERAL names (dslint DSL004/5 scan)
        self.export_path = os.environ.get("DSTPU_TELEMETRY_EXPORT") or None
        self.export_every = int(
            os.environ.get("DSTPU_TELEMETRY_EXPORT_EVERY", "50") or "50")
        self.window = int(
            os.environ.get("DSTPU_TRAIN_OBS_WINDOW", "32") or "32")
        self.zmax = float(
            os.environ.get("DSTPU_TRAIN_OBS_ZMAX", "6.0") or "6.0")
        self.stall_factor = float(
            os.environ.get("DSTPU_TRAIN_OBS_STALL_FACTOR", "10.0")
            or "10.0")
        self.progress_every = int(
            os.environ.get("DSTPU_TRAIN_OBS_PROGRESS_EVERY", "25")
            or "25")
        # DSTPU_TRAIN_OBS_SYNC=0: drop the per-step block_until_ready.
        # The device_execute bracket then reads ~0 (device time hides
        # under later host work or queue back-pressure — the closure
        # still holds, wall is wall) and the sentinel reads the
        # PREVIOUS step's metrics, which are ready by then without
        # forcing a sync — the knob for TPU loops that rely on
        # dispatch-ahead overlap between steps (the default keeps the
        # exact attribution; the bench gates run with it on).
        self.sync = os.environ.get("DSTPU_TRAIN_OBS_SYNC", "1") \
            not in ("0", "false", "off")
        self._pending_sentinel: Optional[Tuple[int, Any]] = None
        self._last_progress: Optional[Dict[str, Any]] = None
        # the observer's own event ledger (goodput source); in-memory
        # when DSTPU_TRAIN_LEDGER is unset. The agent's supervisor
        # ledger is a DIFFERENT file (two processes must not rewrite
        # one JSON document); goodput merges both at report time.
        from ..resilience.ledger import RestartLedger
        self.ledger_path = os.environ.get("DSTPU_TRAIN_LEDGER") or None
        self.agent_ledger_path = \
            os.environ.get("DSTPU_RESTART_LEDGER") or None
        self.ledger = RestartLedger(self.ledger_path)
        #: the prior incarnation's step high-water mark, read from the
        #: ledger BEFORE this run appends anything — the caught-up
        #: marker (goodput's replay_catchup boundary) compares against
        #: the highest step any earlier incarnation ATTEMPTED
        #: (train_progress) or durably saved (checkpoint_save)
        self.prior_max_step = max(
            (int(e.get("step") or 0) for e in self.ledger.events
             if e.get("event") in ("checkpoint_save", "train_progress",
                                   "train_caught_up", "train_resume")),
            default=0)
        self._caught_up = self.prior_max_step == 0
        self.ledger.record("train_start", t_start=time.time(),
                           host=self.host)

        # attribution state (pure perf_counter arithmetic)
        self._t_enter = 0.0
        self._t_mark = 0.0
        self._acc: Dict[str, float] = {}
        self._last_exit: Optional[float] = None
        self._between_apply = 0.0    # checkpoint/eval work between steps
        self._between_this = 0.0     # its share of the CURRENT step
        self._wall_anchor: Optional[float] = None
        self._attrib_prev: Dict[str, float] = {}
        self._last_export_step = 0
        self._loss_window: deque = deque(maxlen=max(4, self.window))
        self._wall_window: deque = deque(maxlen=max(4, self.window))

        r = self.registry
        # hot handles bound once — the record paths below are pre-bound
        # attribute ops, no registry lookups per step
        self.c_steps = r.counter("train_steps")
        self.c_samples = r.counter("train_samples")
        self.c_skipped = r.counter("train_steps_skipped")
        self.c_nonfinite = r.counter("train_nonfinite_steps")
        self.c_anomalies = r.counter("train_anomalies")
        self.h_data = r.histogram("train_data_wait_s")
        self.h_stage = r.histogram("train_stage_s")
        self.h_dispatch = r.histogram("train_dispatch_s")
        self.h_device = r.histogram("train_device_execute_s")
        self.h_apply = r.histogram("train_commit_apply_s")
        self.h_gap = r.histogram("train_host_gap_s")
        self.h_wall = r.histogram("train_step_wall_s")
        self.g_loss = r.gauge("train_loss")
        self.g_gnorm = r.gauge("train_grad_norm")
        self.g_goodput = r.gauge("train_goodput_frac")

    # ------------------- step brackets (hot paths) -------------------- #
    # Registered DSL001 hot paths: pure perf_counter reads, attribute
    # stores and pre-bound histogram observes.

    def on_step_enter(self):
        """train_batch entry: close the between-step span. The gap since
        the previous step's exit minus any bracketed between-step work
        (checkpoint saves ride commit_apply) is ``data_wait`` — for a
        train loop, the data fetch."""
        now = time.perf_counter()
        self._t_enter = now
        self._t_mark = now
        if self._wall_anchor is None:
            # first observed step: the wall ledger opens here, so the
            # closure covers [first enter -> last exit] exactly
            self._wall_anchor = now
        acc = {"data_wait": 0.0, "stage": 0.0, "dispatch": 0.0,
               "device_execute": 0.0, "commit_apply": 0.0}
        if self._last_exit is not None:
            # the between-step bracket work (checkpoint save, resume
            # load) is INSIDE the measured gap — re-file it under
            # commit_apply. With no exit anchor the work happened
            # before this step's measured wall: dropping it keeps the
            # components ≤ wall (a resumed run's 2 s checkpoint load
            # must not blow the first step's closure)
            gap = now - self._last_exit
            between = min(self._between_apply, gap)
            acc["data_wait"] = max(0.0, gap - between)
            acc["commit_apply"] = between
            # remembered so the stall detector can exclude EXPECTED
            # bracketed work (a checkpoint save / validation sweep is
            # not a stall) from its wall comparison
            self._between_this = between
        else:
            self._between_this = 0.0
        self._between_apply = 0.0
        self._acc = acc
        self.flight.phase("stage")

    def on_staged(self):
        """Stage done (validation, watchdog/profiler arming, offload
        swap-in): the compiled step dispatches next."""
        now = time.perf_counter()
        self._acc["stage"] += now - self._t_mark
        self._t_mark = now
        self.flight.phase("dispatch")

    def on_dispatched(self):
        """The compiled step call returned (enqueue on TPU; on the CPU
        harness eager dispatch executes synchronously — the same
        measurement caveat serve_attrib documents)."""
        now = time.perf_counter()
        self._acc["dispatch"] += now - self._t_mark
        self._t_mark = now
        self.flight.phase("device_execute")

    def on_device_done(self):
        """The sanctioned blocking readback finished: the exposed device
        wait is the bracket between on_dispatched and here."""
        now = time.perf_counter()
        self._acc["device_execute"] += now - self._t_mark
        self._t_mark = now
        self.flight.phase("commit_apply")

    def on_step_abort(self):
        """A dead step must not leak its anchors into the next window:
        drop the open accumulators; the next enter re-anchors (the
        serve observer's self-healing rule). A deferred sentinel entry
        is dropped too — after a runtime error even prior steps'
        buffers may be poisoned, and the sentinel must never block on
        a dead computation."""
        self._acc = {}
        self._last_exit = None
        self._wall_anchor = None
        self._between_apply = 0.0
        self._pending_sentinel = None
        self.flight.phase("idle")

    def flush(self):
        """Process the deferred (DSTPU_TRAIN_OBS_SYNC=0) sentinel entry
        — the final step of a run would otherwise end the process with
        its metrics stashed and never examined, leaving no forensics
        for a last-step NaN. Called at every checkpoint save (the
        normal and urgent-preemption end-of-run paths) and public for
        explicit teardown; blocks on the metrics if still in flight
        (teardown semantics, not the hot path)."""
        prev = self._pending_sentinel
        self._pending_sentinel = None
        if prev is not None:
            self._sentinel(*prev)

    def on_between(self, dt: float):
        """Bracketed between-step engine work (checkpoint save, eval):
        accounted into the NEXT step's commit_apply instead of reading
        as data_wait."""
        self._between_apply += dt

    # --------------------- step close (hot-ish) ----------------------- #

    def on_step_exit(self, step: int, metrics: Any, samples: int = 0):
        """Close the books on one committed step: the closure residual
        is host_gap, per-component histograms observe, the sentinel
        reads the in-program non-finite flag (ready — the device bracket
        already blocked on this step's outputs) and the windowed loss
        z-score, then periodic sampling/export. The scalar readbacks
        here are transfers of READY values, not device syncs.
        """
        now = time.perf_counter()
        acc = self._acc
        if not acc or self._wall_anchor is None:
            return
        acc["commit_apply"] += now - self._t_mark
        wall = now - (self._last_exit if self._last_exit is not None
                      else self._t_enter)
        gap = wall - sum(acc.values())
        self._last_exit = now
        self._acc = {}
        self.flight.phase("idle")

        self.c_steps.inc()
        if samples:
            self.c_samples.inc(samples)
        self.h_data.observe(acc["data_wait"])
        self.h_stage.observe(acc["stage"])
        self.h_dispatch.observe(acc["dispatch"])
        self.h_device.observe(acc["device_execute"])
        self.h_apply.observe(acc["commit_apply"])
        self.h_gap.observe(gap if gap > 0.0 else 0.0)
        self.h_wall.observe(wall)

        if self.sync:
            # values ready: the device_execute bracket blocked on them
            self._sentinel(step, metrics)
        else:
            # overlap-preserving mode: process the PREVIOUS step's
            # metrics (at most one step behind the device, so the
            # transfer is ready or nearly so) and stash this step's
            prev = self._pending_sentinel
            self._pending_sentinel = (step, metrics)
            if prev is not None:
                self._sentinel(*prev)
        self._finish_step(step, wall)

    def _sentinel(self, step: int, metrics: Any):
        """The anomaly sentinel's readbacks for ONE step's metrics —
        ready values when called (sync mode blocks in the device
        bracket; deferred mode lags one step). Registered DSL001 hot
        path — scalar transfers + pre-bound counter arithmetic."""
        # dslint: allow(DSL001): scalar transfers of READY values — the
        # device_execute bracket (or the one-step lag) proved them
        loss = float(metrics.loss)
        # dslint: allow(DSL001): ready-value transfer (see above)
        gnorm = float(metrics.grad_norm)
        if bool(metrics.skipped):
            # fp16 overflow skip: routine self-healing (the loss-scale
            # search), already counted and state-protected by the
            # overflow gate — NOT an anomaly, and its garbage inf/NaN
            # must reach neither the loss/grad-norm gauges (an exported
            # snapshot carrying Infinity breaks strict-JSON readers)
            # nor the z-score window
            self.c_skipped.inc()
            return
        # gauges only ever carry finite values (a NaN'd step is visible
        # through train_nonfinite_steps + the anomaly dump instead)
        if math.isfinite(loss):
            self.g_loss.set(loss)
        if math.isfinite(gnorm):
            self.g_gnorm.set(gnorm)
        nonfinite = metrics.nonfinite
        bad = bool(nonfinite) if nonfinite is not None else \
            not (math.isfinite(loss) and math.isfinite(gnorm))
        if bad:
            self.c_nonfinite.inc()
            self._trip("nonfinite", step, loss=loss, grad_norm=gnorm)
        else:
            win = self._loss_window
            if len(win) >= max(4, self.window // 4):
                mean = sum(win) / len(win)
                var = sum((v - mean) ** 2 for v in win) / len(win)
                std = math.sqrt(var)
                if std > 0.0 and abs(loss - mean) / std > self.zmax:
                    self._trip("loss_zscore", step, loss=loss,
                               mean=round(mean, 6),
                               z=round((loss - mean) / std, 2))
            win.append(loss)

    def _finish_step(self, step: int, wall: float):
        """The step close's tail — stall detection, progress/caught-up
        ledger markers, sampling + periodic export — shared by normal
        and overflow-skipped steps. Registered DSL001 hot path."""
        # ---- stall detection -> ledger interval (goodput's bucket).
        # Engine-bracketed between-step work (checkpoint save, eval
        # sweep) is EXPECTED time — excluded from both the comparison
        # and the rolling median so it can never read as a stall.
        stall_wall = max(0.0, wall - self._between_this)
        ww = self._wall_window
        if len(ww) >= max(4, self.window // 4) and self.stall_factor > 0:
            med = sorted(ww)[len(ww) // 2]
            if med > 0 and stall_wall > self.stall_factor * med:
                self.ledger.record(
                    "train_stall",
                    t_start=time.time() - stall_wall,
                    t_end=time.time(), step=step,
                    wall_s=round(stall_wall, 4),
                    median_s=round(med, 4))
        ww.append(stall_wall)

        # ---- progress + caught-up markers (goodput's catchup boundary)
        # >=: reaching the prior high-water mark means every previously
        # attempted step has been redone — the NEXT step is new work
        if not self._caught_up and step >= self.prior_max_step:
            self._caught_up = True
            self.ledger.record("train_caught_up", t_start=time.time(),
                               step=step)
        if self.progress_every > 0 and step % self.progress_every == 0:
            # this incarnation's progress events collapse to ONE (the
            # high-water mark only needs the latest) — replaced by
            # IDENTITY so interleaved checkpoint/stall events cannot
            # defeat the collapse and grow the ledger per N steps
            self._last_progress = self.ledger.replace(
                self._last_progress, "train_progress",
                t_start=time.time(), t_end=time.time(), step=step)

        self.registry.maybe_sample()
        if step - self._last_export_step >= self.export_every:
            self._last_export_step = step
            self.sync_gauges()
            if self.export_path:
                self.registry.export(self.export_path,
                                     extra={"engine": "train",
                                            "host": self.host})
            self.registry.tick(step)

    def _trip(self, kind: str, step: int, **args):
        """One anomaly: counter + trace-worthy flight event + ring
        auto-dump (no-op without DSTPU_FLIGHT_DIR) — the forensics a
        NaN'd run leaves behind. Non-finite floats are stringified
        first: json.dump would emit a literal ``NaN`` token that
        strict-JSON readers (Perfetto — the dump's target tool) refuse
        to load."""
        args = {k: (repr(v) if isinstance(v, float)
                    and not math.isfinite(v) else v)
                for k, v in args.items()}
        self.c_anomalies.inc()
        self.flight.event("train_anomaly", step=step, kind=kind, **args)
        auto_dump("train_anomaly")

    # --------------------- checkpoint / resume ------------------------ #

    def on_checkpoint(self, t0: float, t1: float, step: int,
                      save_dir: str):
        """One checkpoint save published: a stamped ledger interval (the
        goodput ledger's checkpoint_save bucket) + between-step
        accounting so the save rides commit_apply, not data_wait. Also
        flushes a deferred sentinel entry — a run that ends (or is
        preempted) right after its final save leaves complete
        forensics even in SYNC=0 mode."""
        self.flush()
        self.ledger.record("checkpoint_save", t_start=t0, t_end=t1,
                           step=step, dir=save_dir)
        self.on_between(t1 - t0)
        self.flight.record("checkpoint_save",
                           time.perf_counter() - (t1 - t0),
                           time.perf_counter(), step=step)

    def on_resume(self, t0: float, t1: float, step: int, load_dir: str):
        """A checkpoint load: the goodput ledger's resume marker — with
        step > 0 it opens the replay_catchup span that
        ``train_caught_up`` closes."""
        self.ledger.record("train_resume", t_start=t0, t_end=t1,
                           step=step, dir=load_dir)
        self.on_between(t1 - t0)
        # resumed below the prior high-water mark: catch-up runs until
        # the counter gets back there; at (or past) it, nothing is owed
        self._caught_up = step >= self.prior_max_step
        if self._caught_up and step > 0:
            # a CLEAN resume (urgent checkpoint landed at the exact
            # high-water mark — the cooperative-preemption path) owes
            # no redo: record the marker NOW, or goodput_report would
            # see a step>0 resume with no caught marker and misfile
            # the whole healthy incarnation as replay_catchup
            self.ledger.record("train_caught_up", t_start=time.time(),
                               step=step)

    def reset_anchor(self):
        """Drop the between-step anchor (bench windows toggling the
        observer call this on re-attach so the off-window gap never
        reads as one giant data_wait)."""
        self._last_exit = None
        self._wall_anchor = None
        self._between_apply = 0.0

    # --------------------- reports / exports -------------------------- #

    def sync_gauges(self):
        """Export-boundary work (never the hot path): mirror component
        histogram sums into the labelled
        ``train_attrib_seconds_total{component=...}`` counter
        (delta-sync keeps it monotone) and refresh the goodput gauge
        from the merged ledgers."""
        r = self.registry
        for comp, hist in (("data_wait", self.h_data),
                           ("stage", self.h_stage),
                           ("dispatch", self.h_dispatch),
                           ("device_execute", self.h_device),
                           ("commit_apply", self.h_apply),
                           ("host_gap", self.h_gap)):
            cur = hist.sum
            prev = self._attrib_prev.get(comp, 0.0)
            if cur > prev:
                r.counter("train_attrib_seconds_total",
                          component=comp).inc(cur - prev)
                self._attrib_prev[comp] = cur
        rep = self.goodput_report()
        if rep["train_goodput_frac"] is not None:
            self.g_goodput.set(rep["train_goodput_frac"])

    def goodput_report(self) -> Dict[str, Any]:
        """The wall-clock partition over this run's merged event
        timeline: the observer's own ledger (in memory + file) plus the
        elastic agent's supervisor ledger when present."""
        from .goodput import goodput_report, load_ledger_events
        events = list(self.ledger.events)
        if self.agent_ledger_path:
            events = load_ledger_events([self.agent_ledger_path]) + events
        return goodput_report(events, t_end=time.time())

    def attribution_report(self,
                           prev: Optional[Mapping[str, Any]] = None
                           ) -> Dict[str, Any]:
        return train_attribution_report(self.registry.snapshot(), prev)


# ---------------------------------------------------------------------- #
# report-time helpers (never the hot path)
# ---------------------------------------------------------------------- #


def train_comm_share(engine, batch: Any, program: str = "train_step",
                     rng: Any = None) -> Optional[Dict[str, Any]]:
    """The audited-collective share of the compiled train (or eval)
    step, straight from the program auditor's trip-weighted jaxpr
    counts — collective hops (the grad-accum ``lax.scan`` body
    trip-weighted, ring decompositions included) vs trip-weighted
    ``dot_general``s, with 0 host callbacks and 0 device timers. The
    op-level comm-vs-compute split of ``device_execute`` the autotuning
    item needs on the training side. Report-time only (lowers the
    program)."""
    from ..analysis.program_audit import audit_fn
    try:
        if program == "train_step":
            rep = audit_fn(engine._train_step, engine.state, batch,
                           name=program)
        elif program == "eval_step":
            if engine._eval_step is None:
                return None
            import jax
            rng = rng if rng is not None else jax.random.PRNGKey(0)
            rep = audit_fn(engine._eval_step, engine.state.params, batch,
                           rng, engine.state.step, name=program)
        else:
            raise ValueError(f"unknown program {program!r} "
                             f"(train_step | eval_step)")
    except (AttributeError, NotImplementedError, TypeError):
        return None
    return share_from_report(rep, program)


def train_skew_report(per_source: Sequence[Tuple[str, Mapping[str, Any]]]
                      ) -> Dict[str, Any]:
    """The straggler view over per-host train snapshots ([(source,
    snapshot), ...] — the shape ``dstpu_top`` loads): per-host step-time
    and data-wait medians, the max/median step-time skew, and the
    laggard host. Sources are the stable ``train@<host>`` registry
    names the merge scheme keys on."""
    hosts: Dict[str, Dict[str, Any]] = {}
    for src, snap in per_source:
        h = snap.get("histograms", {})
        wall = h.get("train_step_wall_s", {})
        data = h.get("train_data_wait_s", {})
        hosts[src] = {
            "steps": int(wall.get("count", 0)),
            "step_wall_p50_s": wall.get("p50"),
            "step_wall_max_s": wall.get("max"),
            "data_wait_p50_s": data.get("p50"),
            "data_wait_frac": (data.get("sum", 0.0) / wall["sum"])
            if wall.get("sum") else None,
        }
    p50s = [(src, row["step_wall_p50_s"]) for src, row in hosts.items()
            if row["step_wall_p50_s"] is not None]
    out: Dict[str, Any] = {"hosts": hosts, "laggard": None,
                           "step_time_skew": None,
                           "max_step_p50_s": None,
                           "median_step_p50_s": None}
    if p50s:
        vals = sorted(v for _, v in p50s)
        # LOWER median: with an even host count the upper median IS
        # (or neighbors) the laggard, which would read a 3x-slower
        # host on a 2-host fleet as skew 1.0
        med = vals[(len(vals) - 1) // 2]
        laggard, worst = max(p50s, key=lambda kv: kv[1])
        out.update(laggard=laggard,
                   max_step_p50_s=worst, median_step_p50_s=med,
                   step_time_skew=(worst / med) if med > 0 else None)
    return out
