"""Serve-side telemetry observer — per-request SLO instrumentation.

One object the v2 ragged engine owns (``engine._obs``; None when
``DSTPU_TELEMETRY=0`` so every call site is a single ``is not None``
guard): it binds the hot metric handles once at engine build and turns
the engine's EXISTING host-side boundaries into SLO numbers —

  * admission (``put``)          -> ``serve_requests_admitted`` +
    ``seq.admitted_at`` stamp;
  * first schedule (plan)        -> ``serve_queue_wait_s``;
  * token commit (commit/fused)  -> ``serve_ttft_s`` on the first
    committed token, ``serve_tpot_s`` on every later one,
    ``serve_tokens_committed``;
  * rejection / abort / flush    -> the outcome counters goodput is
    computed from;
  * plan/dispatch/commit phases  -> flight-recorder spans (the same
    phase names the watchdog brackets carry).

Everything is pure host work (floats, dict lookups on pre-bound
handles) on paths that already run at those boundaries — no device
access, no callbacks into traced programs; the audited serve programs
are bit-identical with telemetry on or off (tier-1 asserts 0 host
callbacks and 0 fresh compiles on the warm path either way). The
per-request timestamps additionally live on the SequenceDescriptor
(``admitted_at``/``first_sched_at``/``first_token_at``/
``last_token_at``), so TTFT >= queue-wait is checkable per request, not
just in aggregate.

Export: every ``DSTPU_TELEMETRY_EXPORT_EVERY`` committed steps the
registry snapshot is atomically published to ``DSTPU_TELEMETRY_EXPORT``
(the file ``bin/dstpu_top`` renders) and attached monitor bridges tick.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from .flight_recorder import FlightRecorder, auto_dump, register_recorder
from .registry import MetricsRegistry, new_registry, telemetry_enabled

#: rejection reason (engine._reject) -> outcome counter name
_REJECT_COUNTERS = {
    "kv_pool_exhausted": "serve_requests_shed",
    "deadline_exceeded": "serve_requests_deadline_expired",
    "draining": "serve_requests_rejected_draining",
    "admission_overload": "serve_requests_rejected_admission",
}


def serve_observer(engine) -> Optional["ServeObserver"]:
    """The engine's telemetry attach point: a ServeObserver, or None
    when DSTPU_TELEMETRY=0 (the zero-overhead path — the engine then
    never calls into this module again)."""
    if not telemetry_enabled():
        return None
    return ServeObserver(engine)


class ServeObserver:
    def __init__(self, engine):
        self.engine = engine
        self.registry: MetricsRegistry = new_registry("serve")
        self.flight = FlightRecorder()
        register_recorder(self.flight)
        # env knobs read with LITERAL names (dslint DSL004/5 scan)
        self.export_path = os.environ.get("DSTPU_TELEMETRY_EXPORT") or None
        self.export_every = int(
            os.environ.get("DSTPU_TELEMETRY_EXPORT_EVERY", "50") or "50")
        # request-scoped flight spans: uid-tagged admit/queue/prefill/
        # first-token/decode/finish marks so ONE request's life is
        # reconstructable from a single Chrome-trace dump (each request
        # renders as its own track). A handful of ring entries per
        # request; DSTPU_FLIGHT_REQUESTS=0 keeps the ring phases-only.
        self.req_spans = os.environ.get("DSTPU_FLIGHT_REQUESTS", "1") \
            not in ("0", "false", "off")
        # step-time attribution (telemetry/attribution.py,
        # docs/observability.md "Step-time attribution"): when armed, the
        # observer closes the books on every committed step — wall clock
        # since the previous commit boundary, minus the bracketed
        # plan/dispatch/readback/apply components, is the HOST GAP. All
        # pure perf_counter arithmetic at the same host-side boundaries
        # the SLO metrics already own; DSTPU_ATTRIB=0 restores the exact
        # pre-attribution record path (the bench's parity control).
        self.attrib = os.environ.get("DSTPU_ATTRIB", "1") \
            not in ("0", "false", "off")
        self._in_loop = False
        self._anchor = 0.0
        self._acc = 0.0
        self._attrib_prev: Dict[str, float] = {}
        self._last_export_step = 0
        self._prefix_prev: Dict[str, float] = {}
        self._flight_dropped_prev = 0
        r = self.registry
        # hot handles bound once — the record paths below are pre-bound
        # attribute ops, no registry lookups per token
        self.c_admitted = r.counter("serve_requests_admitted")
        self.c_completed = r.counter("serve_requests_completed")
        self.c_aborted = r.counter("serve_requests_aborted")
        self.c_drained = r.counter("serve_requests_drained")
        self.c_tokens = r.counter("serve_tokens_committed")
        self.c_steps = r.counter("serve_steps")
        self.c_fed = r.counter("serve_steps_device_fed")
        self.c_retries = r.counter("serve_step_retries")
        self.c_spec_proposed = r.counter("spec_proposed")
        self.c_spec_accepted = r.counter("spec_accepted")
        self.c_spec_rounds = r.counter("spec_rounds")
        self.h_ttft = r.histogram("serve_ttft_s")
        self.h_tpot = r.histogram("serve_tpot_s")
        self.h_queue = r.histogram("serve_queue_wait_s")
        self.h_plan = r.histogram("serve_plan_s")
        self.h_dispatch = r.histogram("serve_dispatch_s")
        self.h_commit = r.histogram("serve_commit_block_s")
        self.h_apply = r.histogram("serve_commit_apply_s")
        self.h_gap = r.histogram("serve_host_gap_s")
        self.h_wall = r.histogram("serve_step_wall_s")
        self.h_promote = r.histogram("prefix_promote_wait_s")
        self.c_promoted = r.counter("prefix_promoted_blocks")
        self.c_flight_dropped = r.counter("flight_spans_dropped")
        # disaggregated serving (docs/serving.md "Disaggregated
        # serving"): handoff volume counted at the source replica,
        # adoption + exposed transfer wall at the destination
        self.c_handoff_seqs = r.counter("serve_handoff_seqs")
        self.c_handoff_blocks = r.counter("serve_handoff_blocks")
        self.c_handoff_bytes = r.counter("serve_handoff_bytes")
        self.c_handoff_in = r.counter("serve_handoff_seqs_in")
        self.c_handoff_replays = r.counter("serve_handoff_fallback_replays")
        self.h_handoff_exposed = r.histogram("serve_handoff_exposed_s")
        self._reject_counters = {
            reason: r.counter(name)
            for reason, name in _REJECT_COUNTERS.items()}

    def _req_span(self, name, t0_m, t1_m, uid, trace=None, **args):
        """Record a request-lifecycle span from MONOTONIC endpoints
        (the per-seq SLO stamps) onto the flight ring's perf_counter
        axis — the clock offset is measured at record time, so the span
        lands exactly where it happened. ``trace`` is the fleet-wide
        trace context (minted at ReplicaPool.put, carried on the
        sequence descriptor) — merged multi-replica dumps key one
        request's track on it. DSL001-registered hot path: two clock
        reads + a ring append."""
        off = time.perf_counter() - time.monotonic()
        if trace is not None:
            args["trace"] = trace
        self.flight.record(name, t0_m + off, t1_m + off,
                           args={"uid": uid, **args})

    def _req_event(self, name, uid, trace, **args):
        """Instant request-lifecycle mark, trace-tagged when the request
        carries a fleet trace context. DSL001-registered hot path — one
        ring append."""
        if trace is not None:
            args["trace"] = trace
        self.flight.event(name, uid=uid, **args)

    # ------------------- request lifecycle (hot) ---------------------- #
    # Registered DSL001 hot paths: these run inside the pipeline's
    # plan-ahead/commit window — pure host arithmetic only.

    def on_admit(self, seq, now):
        """``now`` is the request's admission stamp — the open-loop
        loadgen passes the request's scheduled ARRIVAL time here (via
        ``put(..., arrivals=...)``), so queue-wait/TTFT include any time
        the request waited outside the engine; the default is the
        put() call time."""
        seq.admitted_at = now
        self.c_admitted.inc()
        if self.req_spans:
            # anchored at the (possibly past) admission stamp so the
            # uid track reads admit -> queue -> ttft in order even when
            # admission lagged the arrival (the loadgen's regime)
            self._req_span("req_admit", now, now, seq.uid,
                           trace=seq.trace_id)

    def on_sched(self, sched, now):
        """First-schedule stamps for this plan's sequences -> queue
        wait. Continuations keep their original stamp (queue wait is an
        admission-time property)."""
        req = self.req_spans
        for item in sched:
            seq = item.seq
            if seq.first_sched_at is None:
                seq.first_sched_at = now
                if seq.admitted_at is not None:
                    self.h_queue.observe(now - seq.admitted_at)
                    if req:
                        self._req_span("req_queue_wait",
                                       seq.admitted_at, now, seq.uid,
                                       trace=seq.trace_id)
            if req and len(item.tokens) > 1:
                self._req_event("req_prefill_chunk", seq.uid,
                                seq.trace_id, ntok=len(item.tokens))

    def on_token_commit(self, seq, now, n=1):
        """``n`` output tokens of ``seq`` became host-visible at ``now``
        (one per pipelined commit; ``n`` per fused decode_batch chunk).
        First commit -> TTFT; later commits -> per-token TPOT. A fused
        chunk's follow-on tokens share one wall interval, so TPOT is the
        interval split evenly (weight n) — the same quantity the bench's
        per-chunk arithmetic reported."""
        self.c_tokens.inc(n)
        if seq.first_token_at is None:
            seq.first_token_at = now
            if seq.admitted_at is not None:
                self.h_ttft.observe(now - seq.admitted_at)
                if self.req_spans:
                    self._req_span("req_ttft", seq.admitted_at, now,
                                   seq.uid, trace=seq.trace_id)
        else:
            last = seq.last_token_at
            if last is not None and now > last:
                self.h_tpot.observe((now - last) / n, n=n)
        seq.last_token_at = now

    def on_plan(self, dt):
        self.h_plan.observe(dt)
        self._acc += dt

    def on_dispatch(self, dt, fed):
        self.c_steps.inc()
        if fed:
            self.c_fed.inc()
        self.h_dispatch.observe(dt)
        self._acc += dt

    def on_fused_dispatch(self, dt):
        """One fused decode_batch / speculative-verify enqueue (n steps
        in one dispatch): same dispatch histogram, no per-step counter
        (``serve_steps`` counts pipelined dispatches; fused rounds are
        already visible as spec_rounds / token commits). Registered
        DSL001 hot path — one observe + one add."""
        self.h_dispatch.observe(dt)
        self._acc += dt

    def on_commit_block(self, dt):
        self.h_commit.observe(dt)
        self._acc += dt

    def on_commit_apply(self, dt):
        """Host-side commit application — token bookkeeping, journal
        appends, rollbacks and deferred flushes between the blocking
        readback and the commit boundary. Registered DSL001 hot path."""
        self.h_apply.observe(dt)
        self._acc += dt

    # ---------------- step-time attribution boundaries ----------------- #

    def on_loop_enter(self):
        """Serve-loop entry (the pipeline ring driver, the fused decode
        loop, a speculative round loop): anchor the attribution clock.
        Loops never genuinely nest (decode_spec exits its window BEFORE
        falling back into the pipelined impl), so entry always
        RE-ANCHORS unconditionally — a loop that unwound on an
        exception without reaching its exit therefore cannot poison
        later windows with a stale anchor (self-healing beats a leaked
        flag). Registered DSL001 hot path — attribute stores only."""
        self._in_loop = True
        if self.attrib:
            self._anchor = time.perf_counter()
            self._acc = 0.0

    def on_loop_exit(self):
        """Serve-loop exit: close the residual tail since the last
        commit boundary (loop-condition checks, ring teardown) so a
        window's component sum equals its wall clock. Registered DSL001
        hot path."""
        self._in_loop = False
        if self.attrib:
            self._close_step(time.perf_counter())

    def _close_step(self, now):
        """The ONE copy of the attribution closure arithmetic (both the
        per-commit boundary and the loop-exit tail call it): wall since
        the anchor, the unbracketed residual into host_gap, re-anchor.
        Registered DSL001 hot path — pure host arithmetic."""
        wall = now - self._anchor
        if wall > 0.0:
            gap = wall - self._acc
            self.h_wall.observe(wall)
            self.h_gap.observe(gap if gap > 0.0 else 0.0)
        self._anchor = now
        self._acc = 0.0

    def on_retry(self):
        self.c_retries.inc()

    def on_spec(self, proposed, accepted):
        """One speculative verify round committed: ``proposed`` draft
        tokens offered across the round's slots, ``accepted`` of them
        survived greedy verification (the committed corrections/bonus
        tokens ride serve_tokens_committed). Registered DSL001 hot
        path — three pre-bound counter adds."""
        self.c_spec_rounds.inc()
        if proposed:
            self.c_spec_proposed.inc(proposed)
        if accepted:
            self.c_spec_accepted.inc(accepted)

    def on_spec_commit(self, seq, accepted, drafted):
        """One TRACED request's share of a speculative verify round —
        the spec-round mark on its fleet trace track (untraced requests
        skip the ring append entirely; the aggregate counters above
        cover them). Registered DSL001 hot path."""
        if self.req_spans and seq.trace_id is not None:
            self._req_event("req_spec_round", seq.uid, seq.trace_id,
                            accepted=accepted, drafted=drafted)

    def on_promote(self, blocks, wait_s):
        """One request's hierarchical-KV promotion dispatched:
        ``blocks`` host-tier blocks scattered back on device, paying
        ``wait_s`` of host-side dispatch time on the plan path (the
        transfers themselves overlap under subsequent compute — this
        histogram IS the exposed cost the serve_hier bench gates on).
        Registered DSL001 hot path: a counter add + one observe."""
        self.c_promoted.inc(blocks)
        self.h_promote.observe(wait_s)

    def on_handoff_out(self, seqs, blocks, nbytes):
        """This replica handed ``seqs`` freshly prefilled sequences to a
        decode specialist (``blocks`` KV blocks, ``nbytes`` payload —
        int8 rows + scale planes for quantized pools). Counted at the
        SOURCE so per-role registries attribute handoff traffic to the
        prefill side. Registered DSL001 hot path — three counter adds."""
        self.c_handoff_seqs.inc(seqs)
        self.c_handoff_blocks.inc(blocks)
        self.c_handoff_bytes.inc(nbytes)

    def on_handoff_in(self, seqs, blocks, exposed_s):
        """This replica adopted ``seqs`` migrated sequences
        (``blocks`` KV blocks scattered in). ``exposed_s`` is the
        caller-measured NON-overlapped transfer wall — the part of the
        gather→materialize→scatter chain that did not hide under
        neighboring compute; the serve_disagg bench gates on its share
        of prefill time. Registered DSL001 hot path."""
        self.c_handoff_in.inc(seqs)
        self.h_handoff_exposed.observe(exposed_s)
        del blocks  # volume counted once, at the source

    def on_handoff_replay(self, seqs):
        """Handoffs that fell back to manifest replay (destination
        could not adopt, or the transfer died mid-flight): the request
        re-prefills its chain token-identically instead. Registered
        DSL001 hot path — one counter add."""
        self.c_handoff_replays.inc(seqs)

    def on_reject(self, reason, uid=None, trace=None):
        c = self._reject_counters.get(reason)
        if c is not None:
            c.inc()
        if self.req_spans and uid is not None:
            self._req_event("req_reject", uid, trace, reason=reason)

    def on_abort(self, rejected):
        """engine.abort() on a live uid; shed/deadline aborts arrive
        with their rejection already counted."""
        if not rejected:
            self.c_aborted.inc()

    def on_flush(self, seq, rejected, draining):
        """Outcome classification at the one release path: drained
        sequences ride the manifest (neither good nor bad), rejected/
        aborted ones were counted at their failure site, everything
        else completed cleanly — the goodput numerator."""
        if seq is None:
            return
        if draining:
            self.c_drained.inc()
            outcome = "drained"
        elif rejected or seq.status.value == "finished":
            # FINISHED is only ever set by abort() — counted there (the
            # value comparison avoids importing the enum: telemetry must
            # stay import-cycle-free below the engine)
            outcome = "rejected" if rejected else "aborted"
        else:
            self.c_completed.inc()
            outcome = "completed"
        if self.req_spans:
            ft, lt = seq.first_token_at, seq.last_token_at
            if ft is not None and lt is not None and lt > ft:
                self._req_span("req_decode", ft, lt, seq.uid,
                               trace=seq.trace_id)
            self._req_event("req_finish", seq.uid, seq.trace_id,
                            outcome=outcome)

    def phase(self, name, step=None):
        self.flight.phase(name, step)

    # --------------------- boundaries / exports ----------------------- #

    def after_commit(self, step: int) -> None:
        """Periodic work at the commit boundary: close the attribution
        step (wall since the previous boundary; the unbracketed residual
        is the HOST GAP), then time-series sampling (throttled to
        DSTPU_SERIES_EVERY_S), then gauge refresh, export publish,
        monitor-bridge tick — every ``export_every`` steps."""
        if self.attrib and self._in_loop:
            self._close_step(time.perf_counter())
        self.registry.maybe_sample()
        if step - self._last_export_step < self.export_every:
            return
        self._last_export_step = step
        self.sync_gauges()
        if self.export_path:
            self.registry.export(self.export_path,
                                 extra={"engine": "serve"})
        self.registry.tick(step)

    def sync_gauges(self) -> None:
        """Refresh pool/prefix gauges and mirror the host-side prefix
        dict counters into registry counters (delta-sync keeps them
        monotone). Cheap host metadata reads only."""
        eng = self.engine
        r = self.registry
        r.gauge("kv_pool_blocks_total").set(eng.config.num_blocks)
        r.gauge("kv_pool_blocks_free").set(eng.kv_cache.free_blocks)
        rep = eng.state.kv_memory_report()
        r.gauge("kv_pool_bytes_total").set(rep["kv_pool_bytes_total"])
        r.gauge("kv_pool_bytes_per_chip").set(
            rep["kv_pool_bytes_per_chip"])
        st = eng.prefix_stats if eng._prefix is not None \
            else dict(eng.state.prefix_stats)
        # delta-synced host-dict counters (monotone); prefix_promoted_
        # blocks is NOT here — on_promote counts it live so the
        # promote-wait histogram and the counter move together
        for key, metric in (("matched_tokens", "prefix_matched_tokens"),
                            ("prefill_tokens", "prefix_prefill_tokens"),
                            ("cow_copies", "prefix_cow_copies"),
                            ("hit_blocks", "prefix_hit_blocks"),
                            ("evicted", "prefix_evicted_blocks"),
                            ("evicted_cap", "prefix_evicted_cap"),
                            ("evicted_pressure", "prefix_evicted_pressure"),
                            ("demoted", "prefix_demoted_blocks"),
                            ("host_hit_blocks", "prefix_host_hit_blocks"),
                            ("host_evicted",
                             "prefix_host_evicted_blocks")):
            cur = st.get(key, 0)
            prev = self._prefix_prev.get(key, 0)
            if cur > prev:
                r.counter(metric).inc(cur - prev)
                self._prefix_prev[key] = cur
        if eng._prefix is not None:
            r.gauge("prefix_cached_blocks").set(st["cached_blocks"])
            r.gauge("prefix_evictable_blocks").set(st["evictable_blocks"])
            r.gauge("prefix_host_blocks").set(st["host_cached_blocks"])
        # step-time attribution: mirror the component histograms' running
        # SUMS into one labelled counter (delta-sync keeps it monotone) —
        # the sampled counter series then yields per-window component
        # deltas, which is what dstpu_top's "dominant component" line and
        # the regression sentinel's phase rows read. Off the hot path by
        # construction (export boundaries only).
        for comp, hist in (("plan", self.h_plan),
                           ("dispatch", self.h_dispatch),
                           ("device_execute", self.h_commit),
                           ("commit_apply", self.h_apply),
                           ("host_gap", self.h_gap),
                           ("promote_wait", self.h_promote)):
            cur = hist.sum
            prev = self._attrib_prev.get(comp, 0.0)
            if cur > prev:
                r.counter("serve_attrib_seconds_total",
                          component=comp).inc(cur - prev)
                self._attrib_prev[comp] = cur
        dropped = self.flight.dropped
        if dropped > self._flight_dropped_prev:
            self.c_flight_dropped.inc(dropped - self._flight_dropped_prev)
            self._flight_dropped_prev = dropped

    def on_drain(self, manifest: Dict[str, Any]) -> None:
        """Drain published: attach the SLO report to the manifest (the
        registry-fed consumer) and auto-dump the flight ring next to the
        replay state."""
        manifest["telemetry"] = self.slo_report()
        auto_dump("drain")

    # ---------------------------- reports ----------------------------- #

    def slo_report(self) -> Dict[str, Any]:
        """The serving-layer summary: TTFT/TPOT/queue-wait percentiles,
        outcome counts and the goodput fraction (completed / terminal
        outcomes; drained requests are in flight to a survivor, not an
        outcome)."""
        self.sync_gauges()
        return slo_report_from_registry(self.registry)


def slo_report_from_registry(registry) -> Dict[str, Any]:
    """The one copy of the SLO-report arithmetic, over any registry
    holding the serve_* metrics: a live engine's own registry
    (:meth:`ServeObserver.slo_report`) or a merged fleet rollup
    (`serving.ReplicaPool.slo_report`) — per-engine and fleet goodput
    can never disagree on the formula."""
    r = registry

    def c(name: str) -> float:
        return r.counter(name).value

    bad = (c("serve_requests_shed")
           + c("serve_requests_deadline_expired")
           + c("serve_requests_rejected_draining")
           + c("serve_requests_rejected_admission")
           + c("serve_requests_aborted"))
    good = c("serve_requests_completed")
    done = good + bad
    spec_prop = c("spec_proposed")
    spec_acc = c("spec_accepted")
    return {
        "spec": {
            "proposed": spec_prop,
            "accepted": spec_acc,
            "rounds": c("spec_rounds"),
        },
        "spec_accept_rate": spec_acc / spec_prop if spec_prop else None,
        "ttft_s": r.histogram("serve_ttft_s").summary(),
        "tpot_s": r.histogram("serve_tpot_s").summary(),
        "queue_wait_s": r.histogram("serve_queue_wait_s").summary(),
        "tokens_committed": c("serve_tokens_committed"),
        "requests": {
            "admitted": c("serve_requests_admitted"),
            "completed": good,
            "shed": c("serve_requests_shed"),
            "deadline_expired": c("serve_requests_deadline_expired"),
            "rejected_draining": c("serve_requests_rejected_draining"),
            "rejected_admission": c("serve_requests_rejected_admission"),
            "aborted": c("serve_requests_aborted"),
            "drained": c("serve_requests_drained"),
        },
        "goodput_frac": good / done if done else None,
    }
