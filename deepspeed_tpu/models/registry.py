"""Model-architecture registry + HF config mapping.

Analogue of the reference's arch→policy map in ``build_hf_engine``
(``inference/v2/engine_factory.py:69``) and the container registry
(``module_inject/replace_policy.py``): maps an architecture name (or a raw
HuggingFace config dict's ``model_type``) to this framework's model config /
module classes, so checkpoints and serving configs can be resolved by name.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple

from .bert import Bert, BertConfig
from .bert import make_model as make_bert
from .diffusion import UNet2DCondition, UNetConfig, VAE, VAEConfig
from .bloom import Bloom, BloomConfig
from .bloom import make_model as make_bloom
from .gpt_neo import GPTNeo, GPTNeoConfig
from .gpt_neo import make_model as make_gpt_neo
from .gpt_neox import (GPTJ, GPTJConfig, GPTNeoX, GPTNeoXConfig,
                       make_model_gptj, make_model_neox)
from .falcon import Falcon, FalconConfig
from .falcon import make_model as make_falcon
from .gpt2 import GPT2, GPT2Config
from .gpt2 import make_model as make_gpt2
from .llama import Llama, LlamaConfig
from .llama import make_model as make_llama
from .mixtral import Mixtral, MixtralConfig
from .mixtral import make_model as make_mixtral
from .opt import OPT, OPTConfig
from .opt import make_model as make_opt
from .phi import Phi, PhiConfig
from .phi import make_model as make_phi


class ArchEntry(NamedTuple):
    config_cls: type
    model_cls: type
    make_model: Callable
    from_hf: Callable[[Dict[str, Any]], Any]


def _hf_llama(d: Dict[str, Any], **extra) -> LlamaConfig:
    base = dict(
        vocab_size=d.get("vocab_size", 32000),
        max_seq_len=d.get("max_position_embeddings", 4096),
        num_layers=d.get("num_hidden_layers", 32),
        num_heads=d.get("num_attention_heads", 32),
        num_kv_heads=d.get("num_key_value_heads",
                           d.get("num_attention_heads", 32)),
        hidden_size=d.get("hidden_size", 4096),
        intermediate_size=d.get("intermediate_size", 11008),
        rope_theta=d.get("rope_theta", 10000.0),
        rms_eps=d.get("rms_norm_eps", 1e-5),
        tie_embeddings=d.get("tie_word_embeddings", False),
    )
    base.update(extra)
    return base


def _entry_llama(d):
    return LlamaConfig(**_hf_llama(d))


def _entry_mistral(d):
    return LlamaConfig(**_hf_llama(d, sliding_window=d.get("sliding_window")))


def _entry_qwen2(d):
    return LlamaConfig(**_hf_llama(d, qkv_bias=True))


def _entry_qwen(d):
    """Qwen v1 (original Qwen-7B; reference
    inference/v2/model_implementations/qwen/): llama-shaped with biased
    fused qkv, RMSNorm, SwiGLU whose config ``intermediate_size`` counts
    BOTH branches (per-branch width is half), and its own config key names
    (seq_length / rotary_emb_base / layer_norm_epsilon)."""
    return LlamaConfig(
        vocab_size=d.get("vocab_size", 151936),
        max_seq_len=d.get("seq_length", 8192),
        num_layers=d.get("num_hidden_layers", 32),
        num_heads=d.get("num_attention_heads", 32),
        num_kv_heads=d.get("num_attention_heads", 32),
        hidden_size=d.get("hidden_size", 4096),
        intermediate_size=d.get("intermediate_size", 22016) // 2,
        rope_theta=d.get("rotary_emb_base", 10000.0),
        rms_eps=d.get("layer_norm_epsilon", 1e-6),
        tie_embeddings=d.get("tie_word_embeddings", False),
        qkv_bias=True)


def _entry_mixtral(d):
    return MixtralConfig(**_hf_llama(
        d,
        num_experts=d.get("num_local_experts", 8),
        experts_top_k=d.get("num_experts_per_tok", 2),
        router_aux_loss_coef=d.get("router_aux_loss_coef", 0.02)))


def _entry_gpt2(d):
    return GPT2Config(
        vocab_size=d.get("vocab_size", 50257),
        max_seq_len=d.get("n_positions", 1024),
        num_layers=d.get("n_layer", 12),
        num_heads=d.get("n_head", 12),
        hidden_size=d.get("n_embd", 768),
        layer_norm_eps=d.get("layer_norm_epsilon", 1e-5))


def _entry_bert(d):
    return BertConfig(
        vocab_size=d.get("vocab_size", 30522),
        max_seq_len=d.get("max_position_embeddings", 512),
        type_vocab_size=d.get("type_vocab_size", 2),
        num_layers=d.get("num_hidden_layers", 12),
        num_heads=d.get("num_attention_heads", 12),
        hidden_size=d.get("hidden_size", 768),
        intermediate_size=d.get("intermediate_size", 3072),
        layer_norm_eps=d.get("layer_norm_eps", 1e-12))


def _entry_distilbert(d):
    # DistilBERT = BERT encoder, no token-type embeddings, gelu, sinusoidal
    # optional (sinusoidal_pos_embds default False -> learned, as here)
    if d.get("sinusoidal_pos_embds", False):
        raise ValueError("distilbert sinusoidal_pos_embds=True is not "
                         "supported (learned positions only)")
    act = d.get("activation", "gelu")
    if act != "gelu":
        raise ValueError(f"distilbert activation={act!r} is not supported "
                         f"(exact gelu only)")
    return BertConfig(
        vocab_size=d.get("vocab_size", 30522),
        max_seq_len=d.get("max_position_embeddings", 512),
        type_vocab_size=0,
        num_layers=d.get("n_layers", 6),
        num_heads=d.get("n_heads", 12),
        hidden_size=d.get("dim", 768),
        intermediate_size=d.get("hidden_dim", 3072),
        layer_norm_eps=1e-12)


def _entry_opt(d):
    proj = d.get("word_embed_proj_dim")
    return OPTConfig(
        vocab_size=d.get("vocab_size", 50272),
        max_seq_len=d.get("max_position_embeddings", 2048),
        num_layers=d.get("num_hidden_layers", 12),
        num_heads=d.get("num_attention_heads", 12),
        hidden_size=d.get("hidden_size", 768),
        ffn_dim=d.get("ffn_dim", 3072),
        do_layer_norm_before=d.get("do_layer_norm_before", True),
        word_embed_proj_dim=(proj if proj and
                             proj != d.get("hidden_size", 768) else None),
        tie_embeddings=d.get("tie_word_embeddings", True))


def _entry_bloom(d):
    return BloomConfig(
        vocab_size=d.get("vocab_size", 250880),
        num_layers=d.get("n_layer", d.get("num_hidden_layers", 30)),
        num_heads=d.get("n_head", d.get("num_attention_heads", 32)),
        hidden_size=d.get("hidden_size", d.get("n_embed", 4096)),
        layer_norm_eps=d.get("layer_norm_epsilon", 1e-5),
        tie_embeddings=d.get("tie_word_embeddings", True))


def _entry_gpt_neo(d):
    # attention_types: [[["global","local"], N], ...] expands to per-layer
    kinds = None
    at = d.get("attention_types")
    if at:
        kinds = []
        for pattern, n in at:
            kinds.extend(list(pattern) * int(n))   # pattern repeated n times
        if len(kinds) != d.get("num_layers", 24):
            raise ValueError(
                f"attention_types expands to {len(kinds)} layers but "
                f"num_layers={d.get('num_layers', 24)}")
        kinds = tuple(kinds)
    act = d.get("activation_function", "gelu_new")
    if act != "gelu_new":
        raise ValueError(
            f"gpt_neo activation_function={act!r} is not supported (only "
            f"gelu_new, the shipped GPT-Neo default)")
    return GPTNeoConfig(
        vocab_size=d.get("vocab_size", 50257),
        max_seq_len=d.get("max_position_embeddings", 2048),
        num_layers=d.get("num_layers", 24),
        num_heads=d.get("num_heads", 16),
        hidden_size=d.get("hidden_size", 2048),
        intermediate_size=d.get("intermediate_size"),
        window_size=d.get("window_size", 256),
        attention_layers=kinds,
        tie_embeddings=d.get("tie_word_embeddings", True),
        layer_norm_eps=d.get("layer_norm_epsilon", 1e-5))


def _entry_gpt_neox(d):
    return GPTNeoXConfig(
        vocab_size=d.get("vocab_size", 50432),
        max_seq_len=d.get("max_position_embeddings", 2048),
        num_layers=d.get("num_hidden_layers", 44),
        num_heads=d.get("num_attention_heads", 64),
        hidden_size=d.get("hidden_size", 6144),
        intermediate_size=d.get("intermediate_size", 24576),
        rotary_pct=d.get("rotary_pct", 0.25),
        rope_theta=d.get("rope_theta", d.get("rotary_emb_base", 10000.0)),
        layer_norm_eps=d.get("layer_norm_eps", 1e-5),
        use_parallel_residual=d.get("use_parallel_residual", True),
        tie_embeddings=d.get("tie_word_embeddings", False))


def _entry_gptj(d):
    return GPTJConfig(
        vocab_size=d.get("vocab_size", 50400),
        max_seq_len=d.get("n_positions", 2048),
        num_layers=d.get("n_layer", 28),
        num_heads=d.get("n_head", 16),
        hidden_size=d.get("n_embd", 4096),
        intermediate_size=d.get("n_inner") or 4 * d.get("n_embd", 4096),
        rotary_dim=d.get("rotary_dim", 64),
        layer_norm_eps=d.get("layer_norm_epsilon", 1e-5),
        tie_embeddings=d.get("tie_word_embeddings", False))


def _entry_falcon(d):
    new_arch = d.get("new_decoder_architecture", False)
    return FalconConfig(
        vocab_size=d.get("vocab_size", 65024),
        max_seq_len=d.get("max_position_embeddings", 2048),
        num_layers=d.get("num_hidden_layers", 32),
        num_heads=d.get("num_attention_heads", 71),
        num_kv_heads=(d.get("num_kv_heads", 8) if new_arch
                      else (d.get("num_attention_heads", 71)
                            if not d.get("multi_query", True) else 1)),
        hidden_size=d.get("hidden_size", 4544),
        alibi=d.get("alibi", False),
        parallel_attn=d.get("parallel_attn", True),
        new_decoder_architecture=new_arch,
        tie_embeddings=d.get("tie_word_embeddings", True))


def _entry_phi(d):
    return PhiConfig(
        vocab_size=d.get("vocab_size", 51200),
        max_seq_len=d.get("max_position_embeddings", 2048),
        num_layers=d.get("num_hidden_layers", 24),
        num_heads=d.get("num_attention_heads", 32),
        hidden_size=d.get("hidden_size", 2048),
        intermediate_size=d.get("intermediate_size", 8192),
        rotary_fraction=d.get("partial_rotary_factor", 0.5),
        rope_theta=d.get("rope_theta", 10000.0))


def _entry_phi3(d):
    # phi-3 is llama-architecture (fused qkv/gate_up in the HF checkpoint,
    # unfused here — same math)
    return LlamaConfig(**_hf_llama(d))


def _entry_internlm(d):
    """InternLM v1/v2 are llama-architecture (reference
    module_inject/containers/internlm.py). v1's optional attention bias
    covers q/k/v here; configs with bias=True also put a bias on o_proj,
    which this model family does not carry — flagged loudly."""
    if d.get("bias", False):
        raise ValueError(
            "internlm configs with bias=True (o_proj bias) are not "
            "supported; bias=False checkpoints load as llama")
    return LlamaConfig(**_hf_llama(d))


def _entry_unet(d):
    from .diffusion import UNetConfig
    ahd = d.get("attention_head_dim", 8)
    if isinstance(ahd, (list, tuple)):
        if len(set(ahd)) != 1:
            raise ValueError(
                f"per-block attention_head_dim {ahd} (SD 2.x style) is not "
                f"supported — this UNet uses one head dim for all blocks")
        ahd = ahd[0]
    return UNetConfig(
        in_channels=d.get("in_channels", 4),
        out_channels=d.get("out_channels", 4),
        block_channels=tuple(d.get("block_out_channels",
                                   (320, 640, 1280, 1280))),
        layers_per_block=d.get("layers_per_block", 2),
        cross_attn_dim=d.get("cross_attention_dim", 768),
        attn_head_dim=ahd,
        norm_groups=d.get("norm_num_groups", 32))


def _entry_vae(d):
    from .diffusion import VAEConfig
    return VAEConfig(
        in_channels=d.get("in_channels", 3),
        latent_channels=d.get("latent_channels", 4),
        block_channels=tuple(d.get("block_out_channels",
                                   (128, 256, 512, 512))),
        norm_groups=d.get("norm_num_groups", 32),
        scaling_factor=d.get("scaling_factor", 0.18215))


def _entry_qwen2_moe(d):
    # qwen2-moe = mixtral block + an always-on sigmoid-gated shared expert
    if int(d.get("decoder_sparse_step", 1)) != 1 or d.get("mlp_only_layers"):
        raise ValueError(
            "qwen2_moe configs with dense layers interleaved "
            "(decoder_sparse_step != 1 or mlp_only_layers) are not "
            "supported — every layer is treated as sparse MoE here")
    return MixtralConfig(**_hf_llama(
        d,
        qkv_bias=True,                  # qwen2 family uses biased q/k/v
        intermediate_size=d.get("moe_intermediate_size",
                                d.get("intermediate_size", 11008)),
        num_experts=d.get("num_experts", 8),
        experts_top_k=d.get("num_experts_per_tok", 2),
        shared_expert_size=d.get("shared_expert_intermediate_size", 0),
        norm_topk_prob=d.get("norm_topk_prob", False),
        router_aux_loss_coef=d.get("router_aux_loss_coef", 0.001)))


ARCHITECTURES: Dict[str, ArchEntry] = {
    "gpt2": ArchEntry(GPT2Config, GPT2, make_gpt2, _entry_gpt2),
    "llama": ArchEntry(LlamaConfig, Llama, make_llama, _entry_llama),
    "mistral": ArchEntry(LlamaConfig, Llama, make_llama, _entry_mistral),
    "qwen": ArchEntry(LlamaConfig, Llama, make_llama, _entry_qwen),
    "qwen2": ArchEntry(LlamaConfig, Llama, make_llama, _entry_qwen2),
    "mixtral": ArchEntry(MixtralConfig, Mixtral, make_mixtral, _entry_mixtral),
    "bert": ArchEntry(BertConfig, Bert, make_bert, _entry_bert),
    "distilbert": ArchEntry(BertConfig, Bert, make_bert,
                            _entry_distilbert),
    "opt": ArchEntry(OPTConfig, OPT, make_opt, _entry_opt),
    "falcon": ArchEntry(FalconConfig, Falcon, make_falcon, _entry_falcon),
    "bloom": ArchEntry(BloomConfig, Bloom, make_bloom, _entry_bloom),
    "gpt_neox": ArchEntry(GPTNeoXConfig, GPTNeoX, make_model_neox,
                          _entry_gpt_neox),
    "gptj": ArchEntry(GPTJConfig, GPTJ, make_model_gptj, _entry_gptj),
    "phi": ArchEntry(PhiConfig, Phi, make_phi, _entry_phi),
    "phi3": ArchEntry(LlamaConfig, Llama, make_llama, _entry_phi3),
    "qwen2_moe": ArchEntry(MixtralConfig, Mixtral, make_mixtral,
                           _entry_qwen2_moe),
    "gpt_neo": ArchEntry(GPTNeoConfig, GPTNeo, make_gpt_neo,
                         _entry_gpt_neo),
    "internlm": ArchEntry(LlamaConfig, Llama, make_llama, _entry_internlm),
    "internlm2": ArchEntry(LlamaConfig, Llama, make_llama, _entry_llama),
}


# diffusers model_index components (reference
# module_inject/containers/unet.py, vae.py +
# model_implementations/diffusers/)
ARCHITECTURES.update({
    "unet2dconditionmodel": ArchEntry(UNetConfig, UNet2DCondition,
                                      None, _entry_unet),
    "autoencoderkl": ArchEntry(VAEConfig, VAE, None, _entry_vae),
})


def get_arch(name: str) -> ArchEntry:
    try:
        return ARCHITECTURES[name.lower()]
    except KeyError:
        raise ValueError(f"unknown architecture {name!r}; known: "
                         f"{sorted(ARCHITECTURES)}")


def config_from_hf(hf_config: Dict[str, Any]):
    """Build this framework's model config from a HuggingFace config dict
    (e.g. json.load of config.json). Returns (arch_name, config)."""
    mt = hf_config.get("model_type")
    if mt is None:
        raise ValueError("hf config missing 'model_type'")
    entry = get_arch(mt)
    return mt.lower(), entry.from_hf(hf_config)
