"""GPT-NeoX and GPT-J causal transformers (flax.linen).

Parity targets: the reference's v1-injection containers
``module_inject/containers/gptneox.py`` and ``gptj.py``:

  GPT-NeoX — partial rotary (``rotary_pct`` of head_dim, rotate-half
    convention), fused per-head-interleaved query_key_value, PARALLEL
    attn+mlp residual (``use_parallel_residual``) with separate
    input/post_attention layernorms, biased GELU MLP, untied ``embed_out``.
  GPT-J — partial rotary with the INTERLEAVED (even/odd pair) rotation
    convention, separate bias-free q/k/v/out projections, parallel residual
    sharing ONE layernorm, biased fc_in/fc_out MLP, untied biased lm_head.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from .llama import apply_rope, rope_frequencies
from .phi import apply_partial_rope


def apply_rope_interleaved(x: jnp.ndarray, positions: jnp.ndarray,
                           theta: float) -> jnp.ndarray:
    """GPT-J rotary convention: each (even, odd) lane PAIR rotates together
    (vs the rotate-half split llama/neox use)."""
    d = x.shape[-1]
    freqs = rope_frequencies(d, theta)                       # [D/2]
    ang = positions[..., None].astype(jnp.float32) * freqs   # [..., T, D/2]
    cos = jnp.cos(ang)[..., None, :]
    sin = jnp.sin(ang)[..., None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., 0::2], xf[..., 1::2]
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def apply_partial_rope_interleaved(x, positions, theta, rotary_dim):
    rot, keep = x[..., :rotary_dim], x[..., rotary_dim:]
    return jnp.concatenate(
        [apply_rope_interleaved(rot, positions, theta), keep], axis=-1)


@dataclasses.dataclass(frozen=True)
class GPTNeoXConfig:
    vocab_size: int = 50432
    max_seq_len: int = 2048
    num_layers: int = 44
    num_heads: int = 64
    hidden_size: int = 6144
    intermediate_size: int = 24576
    rotary_pct: float = 0.25
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-5
    use_parallel_residual: bool = True
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def rotary_dim(self) -> int:
        return int(self.head_dim * self.rotary_pct)

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        return GPTNeoXConfig(**kw)


class GPTNeoXBlock(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.num_heads, cfg.head_dim
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name)
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            use_bias=True, name=name)

        attn_in = ln("input_layernorm")(x)
        q = dense(H * D, "q_proj")(attn_in).reshape(B, T, H, D)
        k = dense(H * D, "k_proj")(attn_in).reshape(B, T, H, D)
        v = dense(H * D, "v_proj")(attn_in).reshape(B, T, H, D)
        pos = jnp.arange(T)[None, :]
        q = apply_partial_rope(q, pos, cfg.rope_theta, cfg.rotary_dim)
        k = apply_partial_rope(k, pos, cfg.rope_theta, cfg.rotary_dim)
        y = jax.nn.dot_product_attention(q, k, v, is_causal=True)
        attn_out = dense(C, "dense")(y.reshape(B, T, C))

        def mlp(h):
            h = dense(cfg.intermediate_size, "dense_h_to_4h")(h)
            return dense(C, "dense_4h_to_h")(nn.gelu(h))

        if cfg.use_parallel_residual:
            return x + attn_out + mlp(ln("post_attention_layernorm")(x))
        x = x + attn_out
        return x + mlp(ln("post_attention_layernorm")(x))


class GPTNeoX(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="embed_in")
        from ._lm_utils import constrain_activations
        x = constrain_activations(embed(tokens))
        block_cls = nn.remat(GPTNeoXBlock) if cfg.remat else GPTNeoXBlock
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layer_{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype,
                         name="final_layer_norm")(x)
        if cfg.tie_embeddings:
            return embed.attend(x.astype(jnp.float32))
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, use_bias=False,
                        name="embed_out")(x.astype(jnp.float32))


@dataclasses.dataclass(frozen=True)
class GPTJConfig:
    vocab_size: int = 50400
    max_seq_len: int = 2048
    num_layers: int = 28
    num_heads: int = 16
    hidden_size: int = 4096
    intermediate_size: int = 16384
    rotary_dim: int = 64
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("rotary_dim", 8)
        return GPTJConfig(**kw)


class GPTJBlock(nn.Module):
    cfg: GPTJConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.num_heads, cfg.head_dim
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="ln_1")(x)
        dense = lambda feats, name, bias: nn.Dense(  # noqa: E731
            feats, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            use_bias=bias, name=name)
        q = dense(H * D, "q_proj", False)(h).reshape(B, T, H, D)
        k = dense(H * D, "k_proj", False)(h).reshape(B, T, H, D)
        v = dense(H * D, "v_proj", False)(h).reshape(B, T, H, D)
        pos = jnp.arange(T)[None, :]
        q = apply_partial_rope_interleaved(q, pos, cfg.rope_theta,
                                           cfg.rotary_dim)
        k = apply_partial_rope_interleaved(k, pos, cfg.rope_theta,
                                           cfg.rotary_dim)
        y = jax.nn.dot_product_attention(q, k, v, is_causal=True)
        attn_out = dense(C, "out_proj", False)(y.reshape(B, T, C))
        # parallel residual sharing ln_1's output
        m = dense(cfg.intermediate_size, "fc_in", True)(h)
        m = dense(C, "fc_out", True)(nn.gelu(m))
        return x + attn_out + m


class GPTJ(nn.Module):
    cfg: GPTJConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="wte")
        x = embed(tokens)
        block_cls = nn.remat(GPTJBlock) if cfg.remat else GPTJBlock
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layer_{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="ln_f")(x)
        if cfg.tie_embeddings:
            return embed.attend(x.astype(jnp.float32))
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, use_bias=True,
                        name="lm_head")(x.astype(jnp.float32))


def make_model_neox(cfg: GPTNeoXConfig):
    from ._lm_utils import make_causal_lm
    return make_causal_lm(GPTNeoX(cfg), cfg)


def make_model_gptj(cfg: GPTJConfig):
    from ._lm_utils import make_causal_lm
    return make_causal_lm(GPTJ(cfg), cfg)
