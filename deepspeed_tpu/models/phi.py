"""Phi (phi-1.5/phi-2) causal transformer (flax.linen).

Parity target: the reference's v2 inference Phi containers
(``inference/v2/model_implementations/phi/``): parallel attention+MLP over
one shared LayerNorm, PARTIAL rotary embedding (``rotary_dim`` < head_dim —
only the leading slice rotates), biased projections, GELU MLP, untied LM
head with bias. Phi-3 is llama-architecture and maps to
:mod:`deepspeed_tpu.models.llama` via the registry.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from .llama import apply_rope


@dataclasses.dataclass(frozen=True)
class PhiConfig:
    vocab_size: int = 51200
    max_seq_len: int = 2048
    num_layers: int = 24
    num_heads: int = 32
    hidden_size: int = 2048
    intermediate_size: int = 8192
    rotary_fraction: float = 0.5        # partial_rotary_factor
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def rotary_dim(self) -> int:
        d = int(self.head_dim * self.rotary_fraction)
        return d - d % 2

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        return PhiConfig(**kw)


def apply_partial_rope(x, positions, theta, rotary_dim):
    rot, keep = x[..., :rotary_dim], x[..., rotary_dim:]
    return jnp.concatenate([apply_rope(rot, positions, theta), keep], axis=-1)


class PhiAttention(nn.Module):
    cfg: PhiConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.num_heads, cfg.head_dim
        dense = lambda feats, name: nn.Dense(
            feats, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            use_bias=True, name=name)
        q = dense(C, "q_proj")(x).reshape(B, T, H, D)
        k = dense(C, "k_proj")(x).reshape(B, T, H, D)
        v = dense(C, "v_proj")(x).reshape(B, T, H, D)
        pos = jnp.arange(T)[None, :]
        q = apply_partial_rope(q, pos, cfg.rope_theta, cfg.rotary_dim)
        k = apply_partial_rope(k, pos, cfg.rope_theta, cfg.rotary_dim)
        y = jax.nn.dot_product_attention(q, k, v, is_causal=True)
        return dense(C, "dense")(y.reshape(B, T, C))


class PhiBlock(nn.Module):
    cfg: PhiConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype,
                         name="input_layernorm")(x)
        attn = PhiAttention(cfg, name="self_attn")(h)
        mlp = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="fc1")(h)
        mlp = nn.gelu(mlp)
        mlp = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="fc2")(mlp)
        return x + attn + mlp                     # parallel residual


class Phi(nn.Module):
    cfg: PhiConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="embed_tokens")(tokens)
        from ._lm_utils import constrain_activations
        x = constrain_activations(x)
        block_cls = nn.remat(PhiBlock) if cfg.remat else PhiBlock
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layer_{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype,
                         name="final_layernorm")(x)
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, use_bias=True,
                        name="lm_head")(x.astype(jnp.float32))


def make_model(cfg: PhiConfig):
    from ._lm_utils import make_causal_lm
    return make_causal_lm(Phi(cfg), cfg)
