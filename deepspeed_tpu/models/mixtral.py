"""Mixtral-style MoE transformer: Llama block with the dense MLP swapped for
the framework's expert-parallel ``MoE`` layer.

Parity target: the reference's mixtral / qwen_v2_moe containers
(``inference/v2/model_implementations/mixtral/``) and the training-side MoE
integration (``deepspeed/moe/layer.py:17``). The MoE block here is the same
``deepspeed_tpu.moe.MoE`` used standalone, so EP sharding, capacity gating,
and the aux-loss plumbing behave identically in both places.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..moe.layer import MoE
from .llama import LlamaAttention, LlamaConfig, RMSNorm


@dataclasses.dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    experts_top_k: int = 2
    capacity_factor: float = 2.0
    drop_tokens: bool = False          # mixtral routes all tokens
    router_aux_loss_coef: float = 0.02
    shared_expert_size: int = 0        # qwen2-moe always-on expert width
    gated_experts: bool = True         # SwiGLU experts (HF mixtral layout)
    # True (mixtral): softmax over the selected top-k (renormalized).
    # False (qwen2-moe default): softmax over ALL experts, top-k taken
    # without renormalization.
    norm_topk_prob: bool = True

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("num_kv_heads", 2)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_experts", 4)
        return MixtralConfig(**kw)

    @staticmethod
    def mixtral_8x7b(**kw):
        kw.setdefault("vocab_size", 32000)
        kw.setdefault("max_seq_len", 32768)
        kw.setdefault("num_kv_heads", 8)
        kw.setdefault("intermediate_size", 14336)
        kw.setdefault("rope_theta", 1e6)
        return MixtralConfig(**kw)


class MixtralBlock(nn.Module):
    cfg: MixtralConfig
    ep_mesh: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        x = x + LlamaAttention(cfg, name="attn")(
            RMSNorm(cfg.rms_eps, cfg.dtype, name="input_norm")(x))
        h = RMSNorm(cfg.rms_eps, cfg.dtype, name="post_attn_norm")(x)
        y, l_aux = MoE(
            d_model=cfg.hidden_size, num_experts=cfg.num_experts,
            k=cfg.experts_top_k, hidden=cfg.intermediate_size,
            capacity_factor=cfg.capacity_factor,
            eval_capacity_factor=cfg.capacity_factor,
            drop_tokens=cfg.drop_tokens, ep_mesh=self.ep_mesh,
            dtype=cfg.dtype, activation=nn.silu,
            gated=cfg.gated_experts,
            normalize_weights=cfg.norm_topk_prob, name="moe")(x=h, train=train)
        self.sow("losses", "moe_aux", l_aux)
        if cfg.shared_expert_size:
            # qwen2-moe: an always-on SwiGLU expert gated by a sigmoid
            # (HF Qwen2MoeSparseMoeBlock shared_expert + shared_expert_gate)
            dense = lambda feats, name: nn.Dense(  # noqa: E731
                feats, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                use_bias=False, name=name)
            gate = dense(cfg.shared_expert_size, "shared_gate_proj")(h)
            up = dense(cfg.shared_expert_size, "shared_up_proj")(h)
            shared = dense(cfg.hidden_size, "shared_down_proj")(
                nn.silu(gate) * up)
            sgate = jax.nn.sigmoid(
                dense(1, "shared_expert_gate")(h).astype(jnp.float32))
            y = y + shared * sgate.astype(cfg.dtype)
        return x + y


class Mixtral(nn.Module):
    cfg: MixtralConfig
    ep_mesh: Any = None

    @nn.compact
    def __call__(self, tokens, train: bool = True,
                 return_hidden: bool = False):
        cfg = self.cfg
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="embed")
        x = embed(tokens)
        from ._lm_utils import constrain_activations
        x = constrain_activations(x)
        block_cls = (nn.remat(MixtralBlock, static_argnums=(2,))
                     if cfg.remat else MixtralBlock)
        for i in range(cfg.num_layers):
            x = block_cls(cfg, self.ep_mesh, name=f"layer_{i}")(x, train)
        x = RMSNorm(cfg.rms_eps, jnp.float32, name="final_norm")(x)
        head = nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, use_bias=False,
                        name="lm_head")
        if return_hidden:
            # training loss path: the caller fuses the head into the
            # chunked/streaming cross-entropy (lm_head params exist from
            # init, which traces the logits path)
            return x
        return head(x.astype(jnp.float32))


def make_model(cfg: MixtralConfig, ep_mesh=None):
    """(model, init_fn, loss_fn); the LM loss adds the router aux loss scaled
    by ``router_aux_loss_coef`` (the reference folds l_aux the same way)."""
    model = Mixtral(cfg, ep_mesh)

    def init_fn(rng, batch_size: int = 2, seq_len: Optional[int] = None):
        T = seq_len or min(cfg.max_seq_len, 64)
        variables = model.init({"params": rng, "gating": rng},
                               jnp.zeros((batch_size, T), jnp.int32))
        return variables["params"]

    def loss_fn(params, batch, rng):
        from ._lm_utils import lm_head_xent
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        hidden, aux = model.apply(
            {"params": params}, inputs, rngs={"gating": rng},
            mutable=["losses"], return_hidden=True)
        moe_aux = sum(jnp.sum(v) for v in
                      jax.tree_util.tree_leaves(aux.get("losses", {})))
        # head fused into the chunked/streaming xent — [B, T, V] fp32
        # logits never materialize (the MoE flagship's vocab is 32k)
        nll = lm_head_xent(hidden.astype(cfg.dtype),
                           params["lm_head"]["kernel"], targets, cfg,
                           head_layout="cv")
        return nll + cfg.router_aux_loss_coef * moe_aux

    return model, init_fn, loss_fn
