"""Falcon causal transformer (flax.linen).

Parity target: the reference's v2 inference Falcon containers
(``inference/v2/model_implementations/falcon/``): rotary attention with
multi-query (7B) or grouped-query + separate attn/mlp norms (40B
``new_decoder_architecture``), PARALLEL attention+MLP residual blocks,
bias-free projections, 4x GELU MLP, tied embeddings.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from .llama import apply_rope


@dataclasses.dataclass(frozen=True)
class FalconConfig:
    vocab_size: int = 65024
    max_seq_len: int = 2048
    num_layers: int = 32
    num_heads: int = 71
    num_kv_heads: int = 1              # MQA (falcon-7b); 8 on 40b
    hidden_size: int = 4544
    rope_theta: float = 10000.0
    alibi: bool = False                # falcon-rw family: ALiBi, no rotary
    layer_norm_eps: float = 1e-5
    parallel_attn: bool = True
    new_decoder_architecture: bool = False   # 40b: separate attn/mlp norms
    tie_embeddings: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("num_kv_heads", 1)
        kw.setdefault("hidden_size", 64)
        return FalconConfig(**kw)


class FalconAttention(nn.Module):
    cfg: FalconConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, C = x.shape
        H, KV, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        dense = lambda feats, name: nn.Dense(
            feats, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            use_bias=False, name=name)
        q = dense(H * D, "q_proj")(x).reshape(B, T, H, D)
        k = dense(KV * D, "k_proj")(x).reshape(B, T, KV, D)
        v = dense(KV * D, "v_proj")(x).reshape(B, T, KV, D)
        bias = None
        if cfg.alibi:
            from ._lm_utils import alibi_bias
            bias = alibi_bias(H, T, T).astype(x.dtype)
        else:
            pos = jnp.arange(T)[None, :]
            q = apply_rope(q, pos, cfg.rope_theta)
            k = apply_rope(k, pos, cfg.rope_theta)
        if KV != H:
            k = jnp.repeat(k, H // KV, axis=2)
            v = jnp.repeat(v, H // KV, axis=2)
        y = jax.nn.dot_product_attention(q, k, v, bias=bias, is_causal=True)
        return dense(C, "dense")(y.reshape(B, T, H * D))


class FalconMLP(nn.Module):
    cfg: FalconConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.Dense(4 * cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, use_bias=False,
                     name="dense_h_to_4h")(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, use_bias=False,
                        name="dense_4h_to_h")(h)


class FalconBlock(nn.Module):
    cfg: FalconConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name)
        if cfg.new_decoder_architecture:
            attn_in = ln("ln_attn")(x)
            mlp_in = ln("ln_mlp")(x)
        else:
            attn_in = ln("input_layernorm")(x)
            mlp_in = attn_in if cfg.parallel_attn else None
        attn_out = FalconAttention(cfg, name="self_attention")(attn_in)
        if cfg.parallel_attn or cfg.new_decoder_architecture:
            return x + attn_out + FalconMLP(cfg, name="mlp")(mlp_in)
        x = x + attn_out
        return x + FalconMLP(cfg, name="mlp")(
            ln("post_attention_layernorm")(x))


class Falcon(nn.Module):
    cfg: FalconConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="word_embeddings")
        from ._lm_utils import constrain_activations
        x = constrain_activations(embed(tokens))
        block_cls = nn.remat(FalconBlock) if cfg.remat else FalconBlock
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layer_{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="ln_f")(x)
        if cfg.tie_embeddings:
            return embed.attend(x.astype(jnp.float32))
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, use_bias=False,
                        name="lm_head")(x.astype(jnp.float32))


def make_model(cfg: FalconConfig):
    from ._lm_utils import make_causal_lm
    return make_causal_lm(Falcon(cfg), cfg)
