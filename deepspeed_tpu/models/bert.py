"""BERT-style bidirectional encoder (flax.linen) with an MLM head.

Parity target: the reference's BERT pretraining headline workload
(BASELINE.md rows 1-2: BERT-large seq128/seq512 throughput) and its
BERT/DistilBERT inference containers (``module_inject/containers/bert.py``).
Post-LN encoder (original BERT), learned position + type embeddings, GELU
MLP, tied MLM decoder.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_seq_len: int = 512
    type_vocab_size: int = 2
    num_layers: int = 12
    num_heads: int = 12
    hidden_size: int = 768
    intermediate_size: int = 3072
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        return BertConfig(**kw)

    @staticmethod
    def bert_large(**kw):
        kw.setdefault("num_layers", 24)
        kw.setdefault("num_heads", 16)
        kw.setdefault("hidden_size", 1024)
        kw.setdefault("intermediate_size", 4096)
        return BertConfig(**kw)


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask=None):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        dense = lambda feats, name: nn.Dense(
            feats, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name=name)
        q = dense(C, "query")(x).reshape(B, T, H, D)
        k = dense(C, "key")(x).reshape(B, T, H, D)
        v = dense(C, "value")(x).reshape(B, T, H, D)
        mask = None
        if attention_mask is not None:        # [B, T] 1=keep
            mask = attention_mask[:, None, None, :].astype(bool)
        y = jax.nn.dot_product_attention(q, k, v, mask=mask)
        y = dense(C, "attn_out")(y.reshape(B, T, C))
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="attn_norm")(x + y)
        h = nn.gelu(dense(cfg.intermediate_size, "intermediate")(x),
                    approximate=False)
        h = dense(C, "output")(h)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                            name="out_norm")(x + h)


class Bert(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, token_type_ids=None, attention_mask=None):
        cfg = self.cfg
        B, T = tokens.shape
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="word_embeddings")
        wpe = nn.Embed(cfg.max_seq_len, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="position_embeddings")
        x = wte(tokens) + wpe(jnp.arange(T)[None, :])
        if cfg.type_vocab_size > 0:       # distilbert has no token types
            wtt = nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                           dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                           name="token_type_embeddings")
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(tokens)
            x = x + wtt(token_type_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="embed_norm")(x)
        layer_cls = nn.remat(BertLayer) if cfg.remat else BertLayer
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, name=f"layer_{i}")(x, attention_mask)
        # MLM head: transform + tied decoder
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="mlm_transform")(x)
        x = nn.gelu(x, approximate=False)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="mlm_norm")(x)
        logits = wte.attend(x.astype(jnp.float32))
        bias = self.param("mlm_bias", nn.initializers.zeros,
                          (cfg.vocab_size,), jnp.float32)
        return logits + bias


def make_model(cfg: BertConfig, mask_token_id: int = 103,
               mask_prob: float = 0.15):
    """(model, init_fn, loss_fn): MLM loss over randomly masked positions
    (batch = {"tokens": [B, T] int32}; masking drawn from the step rng)."""
    model = Bert(cfg)

    def init_fn(rng, batch_size: int = 2, seq_len: Optional[int] = None):
        T = seq_len or min(cfg.max_seq_len, 64)
        return model.init(rng, jnp.zeros((batch_size, T), jnp.int32))["params"]

    def loss_fn(params, batch, rng):
        tokens = batch["tokens"]
        mask = jax.random.bernoulli(rng, mask_prob, tokens.shape)
        inputs = jnp.where(mask, mask_token_id, tokens)
        logits = model.apply({"params": params}, inputs)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, tokens[..., None], axis=-1)[..., 0]
        denom = jnp.maximum(mask.sum(), 1)
        return jnp.where(mask, nll, 0.0).sum() / denom

    return model, init_fn, loss_fn
