"""GPT-Neo (EleutherAI) causal transformer (flax).

The last of the reference's v1 injection containers
(``module_inject/containers/gptneo.py`` — distinct from GPT-NeoX): GPT-2's
macro-structure with three deviations the container encodes — unfused
UNSCALED attention (no 1/sqrt(d) on the scores), separate bias-free q/k/v
projections with a biased out_proj, and alternating global / local
(windowed, 256) attention layers per ``config.attention_types``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class GPTNeoConfig:
    vocab_size: int = 50257
    max_seq_len: int = 2048
    num_layers: int = 24
    num_heads: int = 16
    hidden_size: int = 2048
    intermediate_size: Optional[int] = None       # default 4*hidden
    window_size: int = 256
    # per-layer "global"/"local"; None = alternating starting global
    attention_layers: Optional[Sequence[str]] = None
    layer_norm_eps: float = 1e-5
    tie_embeddings: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    def layer_kinds(self):
        if self.attention_layers is not None:
            return list(self.attention_layers)
        return ["global" if i % 2 == 0 else "local"
                for i in range(self.num_layers)]

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_seq_len", 64)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("hidden_size", 32)
        kw.setdefault("window_size", 8)
        return GPTNeoConfig(**kw)


class GPTNeoBlock(nn.Module):
    cfg: GPTNeoConfig
    kind: str               # "global" | "local"

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ln_1")(x)
        dense = lambda n, b: nn.Dense(   # noqa: E731
            C, use_bias=b, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name=n)
        q = dense("q_proj", False)(h).reshape(B, T, H, D)
        k = dense("k_proj", False)(h).reshape(B, T, H, D)
        v = dense("v_proj", False)(h).reshape(B, T, H, D)
        # GPT-Neo attends UNSCALED (no 1/sqrt(D)) — container-encoded
        # quirk; q/k go through the matmul in fp32 (HF does the same):
        # unscaled scores reach O(100s) where bf16 has lost the mantissa
        scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32))
        iq = jnp.arange(T)[:, None]
        ik = jnp.arange(T)[None, :]
        mask = ik <= iq
        if self.kind == "local":
            mask = jnp.logical_and(mask, ik > iq - cfg.window_size)
        scores = jnp.where(mask[None, None], scores, -1e9)
        p = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        y = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, T, C)
        y = dense("out_proj", True)(y)
        x = x + y
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ln_2")(x)
        inter = cfg.intermediate_size or 4 * C
        m = nn.Dense(inter, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="c_fc")(h)
        m = nn.Dense(C, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="c_proj")(nn.gelu(m))
        return x + m


class GPTNeo(nn.Module):
    cfg: GPTNeoConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        B, T = tokens.shape
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="wte")
        wpe = nn.Embed(cfg.max_seq_len, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="wpe")
        x = wte(tokens) + wpe(jnp.arange(T)[None, :])
        from ._lm_utils import constrain_activations
        x = constrain_activations(x)
        for i, kind in enumerate(cfg.layer_kinds()):
            x = GPTNeoBlock(cfg, kind, name=f"h_{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ln_f")(x)
        if cfg.tie_embeddings:
            return x.astype(jnp.float32) @ \
                wte.embedding.astype(jnp.float32).T
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, name="lm_head")(
            x.astype(jnp.float32))


def make_model(cfg: GPTNeoConfig):
    """(model, init_fn, loss_fn) with the engine's loss signature."""
    model = GPTNeo(cfg)

    def init_fn(rng, batch_size: int = 2, seq_len: Optional[int] = None):
        T = seq_len or min(cfg.max_seq_len, 64)
        return model.init(rng, jnp.zeros((batch_size, T), jnp.int32))["params"]

    def loss_fn(params, batch, rng):
        del rng
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = model.apply({"params": params}, inputs)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return nll.mean()

    return model, init_fn, loss_fn
