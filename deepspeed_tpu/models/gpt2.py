"""GPT-2-style causal transformer (flax.linen).

The in-repo flagship model for tests and benchmarks — the analogue of the
reference's toy/test models (``tests/unit/simple_model.py``) and the GPT-2
configurations used for its ZeRO headline numbers (BASELINE.md: GPT-2-1.3B
ZeRO-3 bf16 is the north-star metric).

TPU-first choices: bf16 compute with fp32 params; all matmuls shaped for the
MXU (head_dim multiples of 128 at real sizes); optional ``jax.checkpoint``
remat per block; param names stable so tensor-parallel rules
(``deepspeed_tpu/parallel/tp_rules.py``) can target qkv/mlp projections.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    hidden_size: int = 768
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16          # compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = False
    # remat granularity: "full" recomputes the whole block; "dots" saves
    # matmul outputs and recomputes only elementwise ops (usually the best
    # memory/FLOPs trade on TPU — the MXU work is never repeated)
    remat_policy: str = "full"
    use_bias: bool = True
    layer_norm_eps: float = 1e-5   # HF GPT-2 epsilon
    # "auto": Pallas flash attention on TPU, XLA fused attention elsewhere;
    # "flash" / "xla" force one path.
    attention_impl: str = "auto"
    # flash kernel tile geometry (ops/kernels/flash_attention.py):
    # 512/512 measured best at seq 512; 1024/1024 measured +3.3 TFLOPS at
    # seq 2048 (profiles/r04_results.jsonl big_bqk1024) — the bench sets
    # it per shape
    flash_block_q: int = 512
    flash_block_k: int = 512
    # fused LM-head xent chunking (models/_lm_utils.chunked_lm_xent):
    # xent_remat=False keeps chunk logits for backward (no unembed
    # recompute) — faster when the fp32 chunks fit HBM.
    # xent_impl "chunked" | "fused": "fused" routes through the streaming
    # Pallas kernel (ops/kernels/fused_xent.py) — logits never touch HBM
    # in either direction, at +1 N*V*C recompute matmul in backward
    xent_chunks: int = 8
    xent_remat: bool = True
    xent_impl: str = "chunked"
    # torch cross_entropy ignore_index semantics (e.g. -100 for padded
    # labels): dropped from the loss, the divisor, and both gradients
    xent_ignore_index: Optional[int] = None

    @staticmethod
    def tiny(**kw):
        return GPT2Config(vocab_size=512, max_seq_len=128, num_layers=2,
                          num_heads=4, hidden_size=64, **kw)

    @staticmethod
    def small(**kw):   # GPT-2 124M
        return GPT2Config(**kw)

    @staticmethod
    def xl_1p3b(**kw):  # GPT-2 1.3B class (the BASELINE.md metric model)
        return GPT2Config(num_layers=24, num_heads=32, hidden_size=2048,
                          max_seq_len=2048, **kw)


class CausalSelfAttention(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        qkv = nn.Dense(3 * C, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       use_bias=cfg.use_bias, name="c_attn")(x)
        qkv = checkpoint_name(qkv, "qkv")
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        impl = cfg.attention_impl
        if impl == "auto":
            # Pallas custom calls carry no GSPMD partitioning rules, so a
            # multi-device jit would replicate q/k/v around the kernel. Auto
            # picks: single-device TPU -> plain flash; multi-device TPU with
            # a registered topology -> flash inside shard_map (batch over
            # data, heads over model); anything else -> XLA fused attention.
            from deepspeed_tpu.parallel import topology as _topo
            if jax.default_backend() != "tpu":
                impl = "xla"
            elif jax.device_count() == 1:
                impl = "flash"
            elif _topo.has_topology() and \
                    _topo.get_topology().mesh.shape.get("seq", 1) == 1:
                impl = "flash_sharded"
            else:
                # sequence-parallel meshes must NOT take flash_sharded: its
                # in_specs keep the sequence dim unsharded, so GSPMD would
                # all-gather seq-sharded activations around the kernel,
                # silently defeating SP — those meshes go through
                # ulysses/ring attention (parallel/) or plain XLA here
                impl = "xla"
        if impl == "flash":
            from deepspeed_tpu.ops.kernels import flash_attention
            y = flash_attention(q, k, v, causal=True, layout="BTHD",
                                block_q=cfg.flash_block_q,
                                block_k=cfg.flash_block_k)
        elif impl == "flash_sharded":
            from deepspeed_tpu.ops.kernels import sharded_flash_attention
            from deepspeed_tpu.parallel.topology import get_topology
            y = sharded_flash_attention(q, k, v, get_topology().mesh,
                                        causal=True, layout="BTHD",
                                        block_q=cfg.flash_block_q,
                                        block_k=cfg.flash_block_k)
        elif impl == "xla":
            # jax.nn.dot_product_attention lowers to a fused attention on TPU
            y = jax.nn.dot_product_attention(q, k, v, is_causal=True)
        else:
            raise ValueError(
                f"attention_impl must be 'auto', 'flash', 'flash_sharded' "
                f"or 'xla', got {cfg.attention_impl!r}")
        y = y.reshape(B, T, C)
        y = nn.Dense(C, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     use_bias=cfg.use_bias, name="c_proj")(y)
        y = checkpoint_name(y, "attn_out")
        if cfg.dropout > 0:
            y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        return y


class MLP(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        h = nn.Dense(cfg.mlp_ratio * cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, use_bias=cfg.use_bias,
                     name="c_fc")(x)
        # tagged for the "no_mlp" remat policy: the two mlp_ratio-wide
        # intermediates dominate per-layer activation memory
        h = checkpoint_name(h, "mlp_pre_act")
        h = nn.gelu(h)
        h = checkpoint_name(h, "mlp_act")
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, use_bias=cfg.use_bias,
                     name="c_proj")(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="ln_1")(x), deterministic)
        x = x + MLP(cfg, name="mlp")(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="ln_2")(x), deterministic)
        return x


class GPT2(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, tokens, deterministic: bool = True,
                 return_hidden: bool = False):
        cfg = self.cfg
        B, T = tokens.shape
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="wte")
        wpe = nn.Embed(cfg.max_seq_len, cfg.hidden_size,
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="wpe")
        x = wte(tokens) + wpe(jnp.arange(T)[None, :])
        # pin the embedding output to the natural activation layout
        # (shared helper — see _lm_utils.constrain_activations for why)
        from ._lm_utils import constrain_activations
        x = constrain_activations(x)
        block_cls = Block
        if cfg.remat:
            policy = None
            if cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies.checkpoint_dots
            elif cfg.remat_policy == "no_mlp":
                # save every residual/attention activation, recompute only
                # the two mlp_ratio-wide MLP intermediates in the backward
                # pass — one fc1 matmul recomputed vs "full"'s entire
                # forward (which costs 33% extra step FLOPs)
                policy = jax.checkpoint_policies.save_anything_except_these_names(
                    "mlp_pre_act", "mlp_act")
            elif cfg.remat_policy == "no_gelu":
                # drop only the post-gelu intermediate: recompute is a free
                # elementwise op, memory still sheds one mlp_ratio-wide
                # tensor per layer
                policy = jax.checkpoint_policies.save_anything_except_these_names(
                    "mlp_act")
            elif cfg.remat_policy == "qkv_out":
                # save ONLY the fused qkv and the attention output (4*C per
                # layer): backward recomputes the cheap LNs, the MLP fc1 +
                # gelu, and the flash forward (for its lse), but never the
                # qkv/attn-proj matmuls — a middle point between "full"
                # (+33% step FLOPs) and no remat (OOM at useful batch)
                policy = jax.checkpoint_policies.save_only_these_names(
                    "qkv", "attn_out")
            elif cfg.remat_policy.startswith("save:"):
                # explicit checkpoint_name list, e.g.
                # "save:qkv,attn_out,mlp_pre_act" — saves qkv + attention
                # output + the fc1 pre-activation (8*C per layer), so the
                # backward recomputes only LNs, gelu and the flash forward:
                # near-zero repeated MXU work at ~2x the qkv_out residency
                names = [n for n in cfg.remat_policy[5:].split(",") if n]
                policy = jax.checkpoint_policies.save_only_these_names(*names)
            block_cls = nn.remat(Block, static_argnums=(2,), policy=policy)
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"h_{i}")(x, deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="ln_f")(x)
        if return_hidden:
            # post-ln_f activations in the compute dtype: the training loss
            # consumes these via the chunked fused cross-entropy
            # (models/_lm_utils.chunked_lm_xent) instead of full logits
            return x
        # tied embedding unembed (GPT-2 ties wte)
        logits = wte.attend(x.astype(jnp.float32))
        return logits


def make_model(cfg: GPT2Config):
    """Returns (init_fn, loss_fn) — loss_fn matches the engine signature
    ``(params, batch, rng) -> loss`` where batch = {"tokens": [B, T+1] int32}
    (next-token LM loss)."""
    model = GPT2(cfg)

    def init_fn(rng, batch_size: int = 2, seq_len: Optional[int] = None):
        T = seq_len or min(cfg.max_seq_len, 64)
        tokens = jnp.zeros((batch_size, T), jnp.int32)
        return model.init(rng, tokens)["params"]

    def loss_fn(params, batch, rng):
        from ._lm_utils import lm_head_xent
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        hidden = model.apply({"params": params}, inputs,
                             deterministic=cfg.dropout == 0,
                             return_hidden=True,
                             rngs={"dropout": rng} if cfg.dropout > 0 else None)
        return lm_head_xent(hidden, params["wte"]["embedding"], targets,
                            cfg)

    return model, init_fn, loss_fn
