"""GPT-2-style causal transformer (flax.linen).

The in-repo flagship model for tests and benchmarks — the analogue of the
reference's toy/test models (``tests/unit/simple_model.py``) and the GPT-2
configurations used for its ZeRO headline numbers (BASELINE.md: GPT-2-1.3B
ZeRO-3 bf16 is the north-star metric).

TPU-first choices: bf16 compute with fp32 params; all matmuls shaped for the
MXU (head_dim multiples of 128 at real sizes); optional ``jax.checkpoint``
remat per block; param names stable so tensor-parallel rules
(``deepspeed_tpu/parallel/tp_rules.py``) can target qkv/mlp projections.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    hidden_size: int = 768
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16          # compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = False
    use_bias: bool = True
    layer_norm_eps: float = 1e-5   # HF GPT-2 epsilon
    # "auto": Pallas flash attention on TPU, XLA fused attention elsewhere;
    # "flash" / "xla" force one path.
    attention_impl: str = "auto"

    @staticmethod
    def tiny(**kw):
        return GPT2Config(vocab_size=512, max_seq_len=128, num_layers=2,
                          num_heads=4, hidden_size=64, **kw)

    @staticmethod
    def small(**kw):   # GPT-2 124M
        return GPT2Config(**kw)

    @staticmethod
    def xl_1p3b(**kw):  # GPT-2 1.3B class (the BASELINE.md metric model)
        return GPT2Config(num_layers=24, num_heads=32, hidden_size=2048,
                          max_seq_len=2048, **kw)


class CausalSelfAttention(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        qkv = nn.Dense(3 * C, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       use_bias=cfg.use_bias, name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        impl = cfg.attention_impl
        if impl == "auto":
            # Pallas custom calls carry no GSPMD partitioning rules: under a
            # multi-device jit, XLA would replicate q/k/v around the kernel.
            # Auto therefore picks flash only for single-device TPU; sharded
            # meshes keep the XLA fused attention (which GSPMD partitions).
            # (The SP paths in parallel/{ulysses,ring_attention}.py currently
            # use XLA attention too; moving their local attention onto this
            # kernel inside shard_map is a planned perf step.)
            single_dev = jax.device_count() == 1
            impl = "flash" if (jax.default_backend() == "tpu"
                               and single_dev) else "xla"
        if impl == "flash":
            from deepspeed_tpu.ops.kernels import flash_attention
            y = flash_attention(q, k, v, causal=True, layout="BTHD")
        elif impl == "xla":
            # jax.nn.dot_product_attention lowers to a fused attention on TPU
            y = jax.nn.dot_product_attention(q, k, v, is_causal=True)
        else:
            raise ValueError(
                f"attention_impl must be 'auto', 'flash' or 'xla', "
                f"got {cfg.attention_impl!r}")
        y = y.reshape(B, T, C)
        y = nn.Dense(C, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     use_bias=cfg.use_bias, name="c_proj")(y)
        if cfg.dropout > 0:
            y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        return y


class MLP(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        h = nn.Dense(cfg.mlp_ratio * cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, use_bias=cfg.use_bias,
                     name="c_fc")(x)
        h = nn.gelu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, use_bias=cfg.use_bias,
                     name="c_proj")(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="ln_1")(x), deterministic)
        x = x + MLP(cfg, name="mlp")(
            nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="ln_2")(x), deterministic)
        return x


class GPT2(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, tokens, deterministic: bool = True):
        cfg = self.cfg
        B, T = tokens.shape
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="wte")
        wpe = nn.Embed(cfg.max_seq_len, cfg.hidden_size,
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="wpe")
        x = wte(tokens) + wpe(jnp.arange(T)[None, :])
        block_cls = Block
        if cfg.remat:
            block_cls = nn.remat(Block, static_argnums=(2,))
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"h_{i}")(x, deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="ln_f")(x)
        # tied embedding unembed (GPT-2 ties wte)
        logits = wte.attend(x.astype(jnp.float32))
        return logits


def make_model(cfg: GPT2Config):
    """Returns (init_fn, loss_fn) — loss_fn matches the engine signature
    ``(params, batch, rng) -> loss`` where batch = {"tokens": [B, T+1] int32}
    (next-token LM loss)."""
    model = GPT2(cfg)

    def init_fn(rng, batch_size: int = 2, seq_len: Optional[int] = None):
        T = seq_len or min(cfg.max_seq_len, 64)
        tokens = jnp.zeros((batch_size, T), jnp.int32)
        return model.init(rng, tokens)["params"]

    def loss_fn(params, batch, rng):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = model.apply({"params": params}, inputs,
                             deterministic=cfg.dropout == 0,
                             rngs={"dropout": rng} if cfg.dropout > 0 else None)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return nll.mean()

    return model, init_fn, loss_fn
