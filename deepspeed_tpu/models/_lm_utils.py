"""Shared causal-LM plumbing for the model zoo."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def constrain_activations(x: jnp.ndarray) -> jnp.ndarray:
    """Pin [B, T, C] activations to the framework's natural layout (batch
    over data, sequence over seq, hidden over model when TP divides it).
    Applied at the embedding output: without it, GSPMD can resolve the
    token gather by fully rematerializing the embedding table per device
    ("involuntary full rematerialization", spmd_partitioner.cc:652) when
    params carry ZeRO/TP shardings, and seq-axis meshes silently
    replicate activations instead of sharding the sequence."""
    from ..parallel import topology as _topo
    if not _topo.has_topology():
        return x
    mesh = _topo.get_topology().mesh
    B, T, C = x.shape
    # batch over ALL data axes (hpZ/MiCS's data_inner included — the
    # engine's batch_sharding uses the same tuple; pinning batch to
    # "data" alone would force replication across the inner group)
    bat = tuple(a for a in ("data", "data_inner")
                if mesh.shape.get(a, 1) > 1)
    bsz = 1
    for a in bat:
        bsz *= mesh.shape[a]
    dims = [bat if bat and B % bsz == 0 else None]
    dims += [a if mesh.shape.get(a, 1) > 1 and d % mesh.shape[a] == 0
             else None
             for a, d in (("seq", T), ("model", C))]
    if not any(dims):
        return x
    from jax.sharding import NamedSharding, PartitionSpec
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*dims)))


def make_causal_lm(model, cfg):
    """(model, init_fn, loss_fn) with the engine's ``(params, batch, rng)``
    contract — batch = {"tokens": [B, T+1] int32}, next-token NLL loss."""

    def init_fn(rng, batch_size: int = 2, seq_len: Optional[int] = None):
        T = seq_len or min(cfg.max_seq_len, 64)
        return model.init(rng, jnp.zeros((batch_size, T), jnp.int32))["params"]

    def loss_fn(params, batch, rng):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = model.apply({"params": params}, inputs)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return nll.mean()

    return model, init_fn, loss_fn


def lm_head_xent(hidden: jnp.ndarray, head: jnp.ndarray,
                 targets: jnp.ndarray, cfg, *,
                 head_layout: str = "vc") -> jnp.ndarray:
    """Shared LM-head loss dispatch for the model zoo (gpt2/llama/...):
    reads the ``xent_*`` knobs off ``cfg`` (with defaults, so configs may
    omit them) and routes to the chunked scan, the streaming fused Pallas
    kernel, or its shard_map wrapper — with the manual-seam and
    seq-parallel guards applied once, here, instead of per model.

    ``head_layout``: "vc" for a [V, C] head (tied embedding), "cv" for
    the natural [C, V] Dense kernel — the chunked path contracts either
    orientation directly (no transpose ever materializes); the fused
    Pallas kernel wants [V, C] rows, so "cv" there pays ONE transposed
    copy per step (XLA CSEs it across the fwd/bwd tile passes).
    """
    if head_layout not in ("vc", "cv"):
        raise ValueError(f"head_layout must be 'vc' or 'cv', "
                         f"got {head_layout!r}")

    impl = getattr(cfg, "xent_impl", "chunked")
    if impl not in ("chunked", "fused"):
        raise ValueError(
            f"xent_impl must be 'chunked' or 'fused', got {impl!r}")
    chunks = getattr(cfg, "xent_chunks", 8)
    remat = getattr(cfg, "xent_remat", True)
    ignore = getattr(cfg, "xent_ignore_index", None)

    def _chunked():
        return chunked_lm_xent(hidden, head, targets, num_chunks=chunks,
                               remat=remat, ignore_index=ignore,
                               head_layout=head_layout)

    if impl == "fused":
        from ..ops.kernels import fused_lm_xent
        from ..ops.kernels.fused_xent import sharded_fused_lm_xent
        from ..parallel import topology as _topo
        if head_layout == "cv":
            head = head.T
        from ..utils.jax_compat import manual_axes
        manual = manual_axes()
        if manual:
            # already inside an engine manual seam (ZeRO++/1-bit
            # shard_map): hidden is per-rank local and the seam pmeans
            # the loss — run the kernel plainly on the shard
            return fused_lm_xent(hidden, head, targets,
                                 ignore_index=ignore)
        if jax.device_count() > 1:
            if not _topo.has_topology():
                # plain GSPMD data-parallel jit with no framework mesh:
                # the Pallas custom call carries no sharding rules, so XLA
                # would silently all-gather the full [B, T, C] hidden
                # states around it — the exact traffic the shard_map
                # wrapper exists to avoid. The chunked einsum shards
                # naturally under GSPMD instead.
                import warnings
                warnings.warn(
                    "xent_impl='fused' with multiple devices but no "
                    "deepspeed_tpu topology registered: falling back to "
                    "the chunked path (the fused kernel would all-gather "
                    "hidden states). Build a mesh via dstpu.initialize / "
                    "parallel.topology to use the fused kernel here.")
                return _chunked()
            mesh = _topo.get_topology().mesh
            if mesh.shape.get("seq", 1) > 1:
                # SP meshes: hidden arrives seq-sharded; the row-sharding
                # wrapper would all-gather T (the chunked einsum shards
                # naturally under GSPMD instead)
                return _chunked()
            # Pallas custom calls carry no GSPMD rules — without the
            # shard_map wrapping a multi-device jit would all-gather the
            # [B, T, C] hidden states around the kernel
            return sharded_fused_lm_xent(hidden, head, targets, mesh,
                                         ignore_index=ignore)
        return fused_lm_xent(hidden, head, targets, ignore_index=ignore)
    return _chunked()


def chunked_lm_xent(hidden: jnp.ndarray, embedding: jnp.ndarray,
                    targets: jnp.ndarray, num_chunks: int = 8,
                    remat: bool = True,
                    ignore_index: Optional[int] = None,
                    head_layout: str = "vc") -> jnp.ndarray:
    """Mean next-token NLL without ever materializing the full logits.

    ``hidden`` [B, T, C] (compute dtype, e.g. bf16), ``embedding`` [V, C]
    (the tied LM head), ``targets`` [B, T] int32. The logits for each
    sequence chunk are computed on the MXU in the compute dtype with fp32
    accumulation, reduced to (logsumexp - target logit), and DISCARDED —
    with ``remat=True`` ``jax.checkpoint`` recomputes them in the backward
    pass (peak memory O(B * T/num_chunks * V) instead of O(B * T * V)).
    ``remat=False`` keeps each chunk's fp32 logits for backward: +O(B*T*V)
    bytes resident, but the backward skips the whole unembed recompute —
    measured worth ~2 TFLOPS/chip at the 710M/seq-2k bench shape where the
    memory fits. The reference always pays the full-logits cost (training
    goes through torch xent). ``ignore_index`` (torch cross_entropy
    semantics, e.g. -100) drops those positions from the loss AND the
    mean divisor.
    """
    B, T, C = hidden.shape
    nc = num_chunks
    while T % nc:           # degrade gracefully for odd T
        nc -= 1
    emb = embedding.astype(hidden.dtype)
    # "cv" = the natural [C, V] Dense kernel: contract dim 0 directly —
    # no transpose ever materializes for either orientation
    e_dim = 1 if head_layout == "vc" else 0
    V = emb.shape[0] if head_layout == "vc" else emb.shape[1]

    def chunk_nll(h, t):
        # [B, Tc, C] @ head -> [B, Tc, V] fp32 (bf16 MXU, f32 accum)
        tc = jnp.clip(t, 0, V - 1)                  # ignore ids may be -100
        logits = jax.lax.dot_general(
            h, emb, (((2,), (e_dim,)), ((), ())),
            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        nll = lse - tgt
        # out-of-range ids (t < 0 or t >= V, e.g. a corrupt label) train
        # against NOTHING: zero their nll here and drop them from the
        # divisor below — torch cross_entropy raises for them; silently
        # training against the clamped id V-1 is the one behavior that is
        # never right. (ignore_index ids are a subset of this mask when
        # negative, which is the torch default -100.)
        valid = (t >= 0) & (t < V)
        if ignore_index is not None:
            valid &= t != ignore_index
        nll = jnp.where(valid, nll, 0.0)
        return nll.sum()

    if remat:
        chunk_nll = jax.checkpoint(chunk_nll)

    hs = hidden.reshape(B, nc, T // nc, C).swapaxes(0, 1)    # [nc, B, Tc, C]
    ts = targets.reshape(B, nc, T // nc).swapaxes(0, 1)      # [nc, B, Tc]

    def body(acc, xs):
        h, t = xs
        return acc + chunk_nll(h, t), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (hs, ts))
    valid = (targets >= 0) & (targets < V)
    if ignore_index is not None:
        valid &= targets != ignore_index
    return total / jnp.maximum(valid.sum(), 1)


def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """ALiBi per-head slopes (Press et al.): geometric schedule over the
    nearest power of two, with ODD multiples from the 2p schedule filling
    the remainder (so extra slopes interleave, never duplicate)."""
    import math
    p = 2 ** math.floor(math.log2(num_heads))
    base = [2 ** (-8.0 * (i + 1) / p) for i in range(p)]
    if p < num_heads:
        extra = [2 ** (-4.0 * (2 * i + 1) / p)
                 for i in range(num_heads - p)]
        base = base + extra
    return jnp.asarray(base[:num_heads], jnp.float32)


def alibi_bias(num_heads: int, q_len: int, k_len: int) -> jnp.ndarray:
    """[1, H, Tq, Tk] additive attention bias: -slope * distance."""
    slopes = alibi_slopes(num_heads)                       # [H]
    pos_q = jnp.arange(q_len)[:, None]
    pos_k = jnp.arange(k_len)[None, :]
    dist = (pos_q - pos_k).astype(jnp.float32)             # >=0 on causal side
    return (-slopes[None, :, None, None] * dist[None, None]).astype(jnp.float32)
