"""Shared causal-LM plumbing for the model zoo."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def make_causal_lm(model, cfg):
    """(model, init_fn, loss_fn) with the engine's ``(params, batch, rng)``
    contract — batch = {"tokens": [B, T+1] int32}, next-token NLL loss."""

    def init_fn(rng, batch_size: int = 2, seq_len: Optional[int] = None):
        T = seq_len or min(cfg.max_seq_len, 64)
        return model.init(rng, jnp.zeros((batch_size, T), jnp.int32))["params"]

    def loss_fn(params, batch, rng):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = model.apply({"params": params}, inputs)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return nll.mean()

    return model, init_fn, loss_fn


def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """ALiBi per-head slopes (Press et al.): geometric schedule over the
    nearest power of two, with ODD multiples from the 2p schedule filling
    the remainder (so extra slopes interleave, never duplicate)."""
    import math
    p = 2 ** math.floor(math.log2(num_heads))
    base = [2 ** (-8.0 * (i + 1) / p) for i in range(p)]
    if p < num_heads:
        extra = [2 ** (-4.0 * (2 * i + 1) / p)
                 for i in range(num_heads - p)]
        base = base + extra
    return jnp.asarray(base[:num_heads], jnp.float32)


def alibi_bias(num_heads: int, q_len: int, k_len: int) -> jnp.ndarray:
    """[1, H, Tq, Tk] additive attention bias: -slope * distance."""
    slopes = alibi_slopes(num_heads)                       # [H]
    pos_q = jnp.arange(q_len)[:, None]
    pos_k = jnp.arange(k_len)[None, :]
    dist = (pos_q - pos_k).astype(jnp.float32)             # >=0 on causal side
    return (-slopes[None, :, None, None] * dist[None, None]).astype(jnp.float32)
