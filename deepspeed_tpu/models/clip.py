"""CLIP text encoder (flax.linen).

Parity target: the reference's CLIP v1-injection container
(``module_inject/containers/clip.py``, serving the text encoder of stable
diffusion pipelines): causal-masked pre-LN transformer with quick-GELU MLP,
token + learned-position embeddings, final LayerNorm. The UNet/VAE half of
the diffusers surface is convolutional and out of scope (documented in
PARITY.md — XLA handles conv fusion natively; there is no injection win to
port).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    max_seq_len: int = 77
    num_layers: int = 12
    num_heads: int = 8
    hidden_size: int = 512
    intermediate_size: int = 2048
    layer_norm_eps: float = 1e-5
    hidden_act: str = "quick_gelu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 128)
        kw.setdefault("max_seq_len", 32)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 2)
        kw.setdefault("hidden_size", 32)
        kw.setdefault("intermediate_size", 64)
        return CLIPTextConfig(**kw)


def _act(name: str):
    if name == "quick_gelu":
        return lambda x: x * jax.nn.sigmoid(1.702 * x)
    if name in ("gelu", "gelu_new"):
        return nn.gelu
    raise ValueError(f"unknown activation {name!r}")


class CLIPTextLayer(nn.Module):
    cfg: CLIPTextConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.num_heads, cfg.head_dim
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name)
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name=name)
        h = ln("layer_norm1")(x)
        q = dense(C, "q_proj")(h).reshape(B, T, H, D)
        k = dense(C, "k_proj")(h).reshape(B, T, H, D)
        v = dense(C, "v_proj")(h).reshape(B, T, H, D)
        y = jax.nn.dot_product_attention(q, k, v, is_causal=True)
        x = x + dense(C, "out_proj")(y.reshape(B, T, C))
        h = ln("layer_norm2")(x)
        h = dense(cfg.intermediate_size, "fc1")(h)
        h = _act(cfg.hidden_act)(h)
        return x + dense(C, "fc2")(h)


class CLIPTextEncoder(nn.Module):
    """Returns the final-LN hidden states [B, T, C] (the tensor stable
    diffusion consumes as conditioning)."""
    cfg: CLIPTextConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        B, T = tokens.shape
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="token_embedding")(tokens)
        wpe = nn.Embed(cfg.max_seq_len, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="position_embedding")
        x = x + wpe(jnp.arange(T)[None, :])
        for i in range(cfg.num_layers):
            x = CLIPTextLayer(cfg, name=f"layer_{i}")(x)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                            param_dtype=cfg.param_dtype,
                            name="final_layer_norm")(x)
