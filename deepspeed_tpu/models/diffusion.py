"""Stable-Diffusion-class UNet + VAE (flax) and a minimal pipeline.

Analogue of the reference's diffusers support: the injected UNet/VAE
containers (``module_inject/containers/unet.py``, ``vae.py``), the fused
spatial ops (``csrc/spatial/``), and the diffusers model wrappers
(``model_implementations/diffusers/unet.py``, ``vae.py`` — cuda-graph
wrapped callables). The TPU inversion: one jitted denoise step (UNet +
scheduler update fused into a single XLA program — the role cuda-graphs play
in the reference) and XLA-fused GroupNorm/SiLU/conv epilogues instead of
hand-written spatial kernels.

Architecture follows the SD UNet2DConditionModel macro-structure —
timestep sinusoidal embedding + MLP, down/mid/up resnet blocks with
self+cross attention transformer blocks at each resolution, skip
connections, and a KL-VAE (encoder → diagonal gaussian, decoder) — sized by
config so tests run tiny while the real geometry (block multipliers 320/640/
1280..., latent 4 channels, x8 spatial factor) is a config choice.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_channels: Sequence[int] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attn_dim: int = 768          # CLIP text hidden size
    attn_head_dim: int = 8
    norm_groups: int = 32
    dtype: Any = jnp.float32

    @staticmethod
    def tiny(**kw):
        kw.setdefault("block_channels", (32, 64))
        kw.setdefault("layers_per_block", 1)
        kw.setdefault("cross_attn_dim", 32)
        kw.setdefault("attn_head_dim", 8)
        kw.setdefault("norm_groups", 8)
        return UNetConfig(**kw)


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    block_channels: Sequence[int] = (128, 256, 512, 512)
    norm_groups: int = 32
    scaling_factor: float = 0.18215
    dtype: Any = jnp.float32

    @staticmethod
    def tiny(**kw):
        kw.setdefault("block_channels", (16, 32))
        kw.setdefault("norm_groups", 8)
        return VAEConfig(**kw)


def timestep_embedding(t: jnp.ndarray, dim: int) -> jnp.ndarray:
    """Sinusoidal timestep embedding (SD convention: half log-spaced freqs)."""
    half = dim // 2
    freqs = jnp.exp(-np.log(10000.0) * jnp.arange(half) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class ResnetBlock(nn.Module):
    out_ch: int
    groups: int
    dtype: Any

    @nn.compact
    def __call__(self, x, temb=None):
        h = nn.GroupNorm(num_groups=self.groups, dtype=self.dtype)(x)
        h = nn.Conv(self.out_ch, (3, 3), padding=1, dtype=self.dtype)(
            nn.silu(h))
        if temb is not None:
            h = h + nn.Dense(self.out_ch, dtype=self.dtype)(
                nn.silu(temb))[:, None, None, :]
        h = nn.GroupNorm(num_groups=self.groups, dtype=self.dtype)(h)
        h = nn.Conv(self.out_ch, (3, 3), padding=1, dtype=self.dtype)(
            nn.silu(h))
        if x.shape[-1] != self.out_ch:
            x = nn.Conv(self.out_ch, (1, 1), dtype=self.dtype)(x)
        return x + h


class SpatialTransformer(nn.Module):
    """Self-attention + cross-attention (text) + geglu MLP over HxW tokens —
    the block the reference injects fused kernels into."""
    channels: int
    head_dim: int
    groups: int
    dtype: Any

    @nn.compact
    def __call__(self, x, context):
        B, H, W, C = x.shape
        heads = max(1, C // self.head_dim)
        resid = x
        h = nn.GroupNorm(num_groups=self.groups, dtype=self.dtype)(x)
        h = h.reshape(B, H * W, C)

        def attn(q_src, kv_src, name):
            q = nn.Dense(C, use_bias=False, dtype=self.dtype,
                         name=f"{name}_q")(q_src)
            k = nn.Dense(C, use_bias=False, dtype=self.dtype,
                         name=f"{name}_k")(kv_src)
            v = nn.Dense(C, use_bias=False, dtype=self.dtype,
                         name=f"{name}_v")(kv_src)
            q = q.reshape(B, -1, heads, C // heads)
            k = k.reshape(B, -1, heads, C // heads)
            v = v.reshape(B, -1, heads, C // heads)
            o = jax.nn.dot_product_attention(q, k, v)
            return nn.Dense(C, dtype=self.dtype, name=f"{name}_o")(
                o.reshape(B, -1, C))

        h = h + attn(nn.LayerNorm(dtype=self.dtype)(h), h, "self")
        ctx = nn.Dense(C, use_bias=False, dtype=self.dtype,
                       name="ctx_proj")(context)
        h = h + attn(nn.LayerNorm(dtype=self.dtype)(h), ctx, "cross")
        n = nn.LayerNorm(dtype=self.dtype)(h)
        gate = nn.Dense(4 * C, dtype=self.dtype)(n)
        up = nn.Dense(4 * C, dtype=self.dtype)(n)
        h = h + nn.Dense(C, dtype=self.dtype)(nn.gelu(gate) * up)
        return resid + h.reshape(B, H, W, C)


class UNet2DCondition(nn.Module):
    """SD-class conditional UNet: x [B, H, W, Cin] (NHWC), t [B],
    context [B, T, cross_attn_dim] -> eps [B, H, W, Cout]."""
    cfg: UNetConfig

    @nn.compact
    def __call__(self, x, t, context):
        cfg = self.cfg
        ch0 = cfg.block_channels[0]
        temb = timestep_embedding(t, ch0)
        temb = nn.Dense(ch0 * 4, dtype=cfg.dtype)(temb)
        temb = nn.Dense(ch0 * 4, dtype=cfg.dtype)(nn.silu(temb))

        h = nn.Conv(ch0, (3, 3), padding=1, dtype=cfg.dtype)(x)
        skips = [h]
        # down
        for i, ch in enumerate(cfg.block_channels):
            for _ in range(cfg.layers_per_block):
                h = ResnetBlock(ch, cfg.norm_groups, cfg.dtype)(h, temb)
                if i > 0:          # attention below full resolution (SD)
                    h = SpatialTransformer(ch, cfg.attn_head_dim,
                                           cfg.norm_groups, cfg.dtype)(
                        h, context)
                skips.append(h)
            if i < len(cfg.block_channels) - 1:
                h = nn.Conv(ch, (3, 3), strides=2, padding=1,
                            dtype=cfg.dtype)(h)
                skips.append(h)
        # mid
        mid_ch = cfg.block_channels[-1]
        h = ResnetBlock(mid_ch, cfg.norm_groups, cfg.dtype)(h, temb)
        h = SpatialTransformer(mid_ch, cfg.attn_head_dim, cfg.norm_groups,
                               cfg.dtype)(h, context)
        h = ResnetBlock(mid_ch, cfg.norm_groups, cfg.dtype)(h, temb)
        # up
        for i, ch in reversed(list(enumerate(cfg.block_channels))):
            for _ in range(cfg.layers_per_block + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = ResnetBlock(ch, cfg.norm_groups, cfg.dtype)(h, temb)
                if i > 0:
                    h = SpatialTransformer(ch, cfg.attn_head_dim,
                                           cfg.norm_groups, cfg.dtype)(
                        h, context)
            if i > 0:
                B, H, W, C = h.shape
                h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
                h = nn.Conv(C, (3, 3), padding=1, dtype=cfg.dtype)(h)
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype)(h)
        return nn.Conv(self.cfg.out_channels, (3, 3), padding=1,
                       dtype=cfg.dtype)(nn.silu(h))


class VAEEncoder(nn.Module):
    cfg: VAEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.Conv(cfg.block_channels[0], (3, 3), padding=1,
                    dtype=cfg.dtype, name="enc_in")(x)
        for i, ch in enumerate(cfg.block_channels):
            h = ResnetBlock(ch, cfg.norm_groups, cfg.dtype,
                            name=f"enc_res{i}")(h)
            if i < len(cfg.block_channels) - 1:
                h = nn.Conv(ch, (3, 3), strides=2, padding=1,
                            dtype=cfg.dtype, name=f"enc_down{i}")(h)
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype,
                         name="enc_norm")(h)
        moments = nn.Conv(2 * cfg.latent_channels, (1, 1), dtype=cfg.dtype,
                          name="enc_out")(nn.silu(h))
        mean, logvar = jnp.split(moments, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)


class VAEDecoder(nn.Module):
    cfg: VAEConfig

    @nn.compact
    def __call__(self, z):
        cfg = self.cfg
        h = nn.Conv(cfg.block_channels[-1], (3, 3), padding=1,
                    dtype=cfg.dtype, name="dec_in")(z)
        for i, ch in reversed(list(enumerate(cfg.block_channels))):
            h = ResnetBlock(ch, cfg.norm_groups, cfg.dtype,
                            name=f"dec_res{i}")(h)
            if i > 0:
                B, H, W, C = h.shape
                h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
                h = nn.Conv(C, (3, 3), padding=1, dtype=cfg.dtype,
                            name=f"dec_up{i}")(h)
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype,
                         name="dec_norm")(h)
        return nn.Conv(cfg.in_channels, (3, 3), padding=1, dtype=cfg.dtype,
                       name="dec_out")(nn.silu(h))


class VAE(nn.Module):
    """KL autoencoder: encode -> (mean, logvar) over latents; decode back.
    NHWC; spatial factor 2^(len(block_channels)-1)."""
    cfg: VAEConfig

    def setup(self):
        self.encoder = VAEEncoder(self.cfg)
        self.decoder = VAEDecoder(self.cfg)

    def __call__(self, x, rng=None, sample: bool = False):
        mean, logvar = self.encoder(x)
        z = mean
        if sample and rng is not None:
            z = mean + jnp.exp(0.5 * logvar) * jax.random.normal(
                rng, mean.shape)
        return self.decoder(z), mean, logvar

    def encode(self, x):
        return self.encoder(x)

    def decode(self, z):
        return self.decoder(z)


class StableDiffusionPipeline:
    """Text-to-image sampling loop: CLIP text encoder -> UNet denoise loop
    (DDIM) -> VAE decode. The whole per-step denoise (classifier-free
    guidance pair + scheduler update) is ONE jitted program — the role the
    reference's cuda-graph wrap plays (``model_implementations/diffusers/``)
    — and the loop runs ``lax.fori``-free host-side so schedulers stay
    swappable.
    """

    def __init__(self, unet: UNet2DCondition, unet_params,
                 vae: VAE, vae_params,
                 text_encoder=None, text_params=None,
                 num_train_timesteps: int = 1000):
        self.unet, self.unet_params = unet, unet_params
        self.vae, self.vae_params = vae, vae_params
        self.text_encoder, self.text_params = text_encoder, text_params
        self.T = num_train_timesteps
        # DDIM alphas (SD linear beta schedule)
        betas = jnp.linspace(0.00085 ** 0.5, 0.012 ** 0.5,
                             num_train_timesteps) ** 2
        self.alphas_cum = jnp.cumprod(1.0 - betas)

        def denoise_step(unet_params, latents, t, t_prev, context, uncond,
                         guidance):
            lat2 = jnp.concatenate([latents, latents], 0)
            ctx2 = jnp.concatenate([context, uncond], 0)
            tt = jnp.full((lat2.shape[0],), t, jnp.int32)
            eps = self.unet.apply({"params": unet_params}, lat2, tt, ctx2)
            e_cond, e_uncond = jnp.split(eps, 2, 0)
            eps = e_uncond + guidance * (e_cond - e_uncond)
            a_t = self.alphas_cum[t]
            a_prev = jnp.where(t_prev >= 0, self.alphas_cum[t_prev], 1.0)
            x0 = (latents - jnp.sqrt(1 - a_t) * eps) / jnp.sqrt(a_t)
            return jnp.sqrt(a_prev) * x0 + jnp.sqrt(1 - a_prev) * eps

        self._denoise = jax.jit(denoise_step)

    def encode_text(self, tokens):
        if self.text_encoder is None:
            raise ValueError("pipeline built without a text encoder")
        return self.text_encoder.apply({"params": self.text_params}, tokens)

    def __call__(self, context, uncond_context, latent_shape,
                 num_inference_steps: int = 20, guidance_scale: float = 7.5,
                 seed: int = 0):
        """context/uncond_context: [B, T, D] text states; returns decoded
        images [B, H*8-ish, W*8-ish, 3] in [-1, 1]."""
        rng = jax.random.PRNGKey(seed)
        latents = jax.random.normal(rng, latent_shape)
        ts = np.linspace(self.T - 1, 0, num_inference_steps).astype(np.int32)
        for i, t in enumerate(ts):
            t_prev = ts[i + 1] if i + 1 < len(ts) else -1
            latents = self._denoise(self.unet_params, latents, int(t),
                                    int(t_prev), context, uncond_context,
                                    guidance_scale)
        scale = getattr(self.vae.cfg, "scaling_factor", 1.0)
        return self.vae.apply({"params": self.vae_params}, latents / scale,
                              method=VAE.decode)
