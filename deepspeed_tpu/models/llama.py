"""Llama-family causal transformer (flax.linen).

Covers the reference's v2 inference model zoo members that share this block
structure — llama_v2, llama_v3, mistral, qwen2 (``inference/v2/
model_implementations/{llama_v2,mistral,qwen_v2}/``) — via config:
RMSNorm, RoPE, GQA attention, SwiGLU MLP, optional sliding-window mask
(mistral), optional qkv bias (qwen2), untied LM head.

TPU-first: bf16 compute / f32 params, MXU-shaped projections, optional remat
per block; stable param names so TP rules and the ragged runner can address
q/k/v/o and gate/up/down projections.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32            # < num_heads => GQA
    hidden_size: int = 4096
    intermediate_size: int = 11008
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    sliding_window: Optional[int] = None   # mistral local attention
    qkv_bias: bool = False                 # qwen2
    tie_embeddings: bool = False
    # LM-head cross-entropy knobs (models/_lm_utils.lm_head_xent):
    # "chunked" scan or the streaming "fused" Pallas kernel
    xent_impl: str = "chunked"
    xent_chunks: int = 8
    xent_remat: bool = True
    xent_ignore_index: Optional[int] = None
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("num_kv_heads", 2)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        return LlamaConfig(**kw)

    @staticmethod
    def llama2_7b(**kw):
        return LlamaConfig(**kw)

    @staticmethod
    def llama3_8b(**kw):
        kw.setdefault("vocab_size", 128256)
        kw.setdefault("max_seq_len", 8192)
        kw.setdefault("num_kv_heads", 8)
        kw.setdefault("intermediate_size", 14336)
        kw.setdefault("rope_theta", 500000.0)
        return LlamaConfig(**kw)

    @staticmethod
    def mistral_7b(**kw):
        kw.setdefault("vocab_size", 32000)
        kw.setdefault("max_seq_len", 8192)
        kw.setdefault("num_kv_heads", 8)
        kw.setdefault("intermediate_size", 14336)
        kw.setdefault("sliding_window", 4096)
        return LlamaConfig(**kw)


def rope_frequencies(head_dim: int, theta: float) -> jnp.ndarray:
    """Inverse frequencies for rotary embedding, shape [head_dim // 2]."""
    return 1.0 / (theta ** (jnp.arange(0, head_dim, 2, jnp.float32) / head_dim))


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray,
               theta: float) -> jnp.ndarray:
    """Rotary position embedding. x: [..., T, H, D]; positions: [..., T]."""
    d = x.shape[-1]
    freqs = rope_frequencies(d, theta)                     # [D/2]
    ang = positions[..., None].astype(jnp.float32) * freqs  # [..., T, D/2]
    cos = jnp.cos(ang)[..., None, :]                       # [..., T, 1, D/2]
    sin = jnp.sin(ang)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                       jnp.float32)
        xf = x.astype(jnp.float32)
        y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + self.eps)
        return (y * w).astype(self.dtype)


class LlamaAttention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, C = x.shape
        H, KV, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        dense = lambda feats, name: nn.Dense(
            feats, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            use_bias=cfg.qkv_bias, name=name)
        q = dense(H * D, "q_proj")(x).reshape(B, T, H, D)
        k = dense(KV * D, "k_proj")(x).reshape(B, T, KV, D)
        v = dense(KV * D, "v_proj")(x).reshape(B, T, KV, D)
        pos = jnp.arange(T)[None, :]
        q = apply_rope(q, pos, cfg.rope_theta)
        k = apply_rope(k, pos, cfg.rope_theta)

        impl = cfg.attention_impl
        if impl == "auto":
            impl = ("flash" if jax.default_backend() == "tpu"
                    and jax.device_count() == 1 else "xla")
        if impl == "flash":
            from deepspeed_tpu.ops.kernels import flash_attention
            y = flash_attention(q, k, v, causal=True, layout="BTHD")
            if cfg.sliding_window is not None and T > cfg.sliding_window:
                raise NotImplementedError(
                    "sliding window not yet supported on the flash path")
        elif impl == "xla":
            if KV != H:
                k = jnp.repeat(k, H // KV, axis=2)
                v = jnp.repeat(v, H // KV, axis=2)
            mask = None
            if cfg.sliding_window is not None:
                i = jnp.arange(T)[:, None]
                j = jnp.arange(T)[None, :]
                mask = (j > i - cfg.sliding_window)[None, None]
            y = jax.nn.dot_product_attention(q, k, v, mask=mask,
                                             is_causal=True)
        else:
            raise ValueError(f"attention_impl must be 'auto', 'flash' or "
                             f"'xla', got {cfg.attention_impl!r}")
        y = y.reshape(B, T, H * D)
        return nn.Dense(C, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                        use_bias=False, name="o_proj")(y)


class LlamaMLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, name: nn.Dense(
            feats, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            use_bias=False, name=name)
        gate = dense(cfg.intermediate_size, "gate_proj")(x)
        up = dense(cfg.intermediate_size, "up_proj")(x)
        return dense(cfg.hidden_size, "down_proj")(nn.silu(gate) * up)


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = x + LlamaAttention(cfg, name="attn")(
            RMSNorm(cfg.rms_eps, cfg.dtype, name="input_norm")(x))
        x = x + LlamaMLP(cfg, name="mlp")(
            RMSNorm(cfg.rms_eps, cfg.dtype, name="post_attn_norm")(x))
        return x


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, return_hidden: bool = False):
        cfg = self.cfg
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="embed")
        x = embed(tokens)
        from ._lm_utils import constrain_activations
        x = constrain_activations(x)
        block_cls = nn.remat(LlamaBlock) if cfg.remat else LlamaBlock
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layer_{i}")(x)
        x = RMSNorm(cfg.rms_eps, jnp.float32, name="final_norm")(x)
        if return_hidden:
            # training loss path: the caller fuses the LM head into the
            # chunked/streaming cross-entropy instead of [B, T, V] logits
            return x
        if cfg.tie_embeddings:
            return embed.attend(x.astype(jnp.float32))
        head = nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, use_bias=False,
                        name="lm_head")
        return head(x.astype(jnp.float32))


def make_model(cfg: LlamaConfig):
    """(model, init_fn, loss_fn) with the engine's ``(params, batch, rng)``
    loss contract — batch = {"tokens": [B, T+1] int32}."""
    model = Llama(cfg)

    def init_fn(rng, batch_size: int = 2, seq_len: Optional[int] = None):
        T = seq_len or min(cfg.max_seq_len, 64)
        return model.init(rng, jnp.zeros((batch_size, T), jnp.int32))["params"]

    def loss_fn(params, batch, rng):
        from ._lm_utils import lm_head_xent
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        # final_norm emits fp32; cast back to the compute dtype so the
        # unembed chunk/tile matmuls ride the bf16 MXU path (f32 accum
        # happens inside the xent implementations regardless)
        hidden = model.apply({"params": params}, inputs,
                             return_hidden=True).astype(cfg.dtype)
        if cfg.tie_embeddings:
            return lm_head_xent(hidden, params["embed"]["embedding"],
                                targets, cfg)
        # untied: the NATURAL [C, V] Dense kernel — the dispatch contracts
        # it directly (chunked) or transposes once per step (fused)
        return lm_head_xent(hidden, params["lm_head"]["kernel"], targets,
                            cfg, head_layout="cv")

    return model, init_fn, loss_fn
