"""OPT causal transformer (flax.linen).

Parity target: the reference's v2 inference OPT containers
(``inference/v2/model_implementations/opt/``) and v1 OPT injection policy
(``module_inject/containers/opt.py``): learned positional embeddings with
the OPT +2 offset, pre-LN decoder blocks, biased projections, ReLU MLP,
final LayerNorm, tied LM head by default.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    max_seq_len: int = 2048
    num_layers: int = 12
    num_heads: int = 12
    hidden_size: int = 768
    ffn_dim: int = 3072
    layer_norm_eps: float = 1e-5
    do_layer_norm_before: bool = True      # False on opt-350m (post-LN)
    word_embed_proj_dim: Optional[int] = None   # opt-350m: 512 != hidden
    tie_embeddings: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    #: OPT's learned positions start at index 2 (pad-token legacy)
    POSITION_OFFSET = 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("ffn_dim", 128)
        return OPTConfig(**kw)


class OPTAttention(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.num_heads, cfg.head_dim
        dense = lambda name: nn.Dense(
            C, dtype=cfg.dtype, param_dtype=cfg.param_dtype, use_bias=True,
            name=name)
        q = dense("q_proj")(x).reshape(B, T, H, D)
        k = dense("k_proj")(x).reshape(B, T, H, D)
        v = dense("v_proj")(x).reshape(B, T, H, D)
        y = jax.nn.dot_product_attention(q, k, v, is_causal=True)
        return dense("out_proj")(y.reshape(B, T, C))


class OPTBlock(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name)
        attn_ln = ln("self_attn_layer_norm")
        if cfg.do_layer_norm_before:                  # pre-LN (most OPTs)
            x = x + OPTAttention(cfg, name="self_attn")(attn_ln(x))
        else:                                          # post-LN (opt-350m)
            x = attn_ln(x + OPTAttention(cfg, name="self_attn")(x))
        mlp_ln = ln("final_layer_norm")
        h = mlp_ln(x) if cfg.do_layer_norm_before else x
        h = nn.Dense(cfg.ffn_dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="fc1")(h)
        h = nn.relu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="fc2")(h)
        x = x + h
        return x if cfg.do_layer_norm_before else mlp_ln(x)


class OPT(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        B, T = tokens.shape
        embed_dim = cfg.word_embed_proj_dim or cfg.hidden_size
        embed = nn.Embed(cfg.vocab_size, embed_dim, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="embed_tokens")
        pos = nn.Embed(cfg.max_seq_len + cfg.POSITION_OFFSET,
                       cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="embed_positions")
        x = embed(tokens)
        if embed_dim != cfg.hidden_size:               # opt-350m project_in
            x = nn.Dense(cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="project_in")(x)
        x = x + pos(jnp.arange(T) + cfg.POSITION_OFFSET)
        from ._lm_utils import constrain_activations
        x = constrain_activations(x)
        block_cls = nn.remat(OPTBlock) if cfg.remat else OPTBlock
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layer_{i}")(x)
        if cfg.do_layer_norm_before:                   # post-LN has no final
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                             param_dtype=cfg.param_dtype,
                             name="final_layer_norm")(x)
        if embed_dim != cfg.hidden_size:               # opt-350m project_out
            x = nn.Dense(embed_dim, use_bias=False, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="project_out")(x)
        if cfg.tie_embeddings:
            return embed.attend(x.astype(jnp.float32))
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, use_bias=False,
                        name="lm_head")(x.astype(jnp.float32))


def make_model(cfg: OPTConfig):
    from ._lm_utils import make_causal_lm
    return make_causal_lm(OPT(cfg), cfg)
