"""OPT causal transformer (flax.linen).

Parity target: the reference's v2 inference OPT containers
(``inference/v2/model_implementations/opt/``) and v1 OPT injection policy
(``module_inject/containers/opt.py``): learned positional embeddings with
the OPT +2 offset, pre-LN decoder blocks, biased projections, ReLU MLP,
final LayerNorm, tied LM head by default.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    max_seq_len: int = 2048
    num_layers: int = 12
    num_heads: int = 12
    hidden_size: int = 768
    ffn_dim: int = 3072
    layer_norm_eps: float = 1e-5
    tie_embeddings: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    #: OPT's learned positions start at index 2 (pad-token legacy)
    POSITION_OFFSET = 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("ffn_dim", 128)
        return OPTConfig(**kw)


class OPTAttention(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.num_heads, cfg.head_dim
        dense = lambda name: nn.Dense(
            C, dtype=cfg.dtype, param_dtype=cfg.param_dtype, use_bias=True,
            name=name)
        q = dense("q_proj")(x).reshape(B, T, H, D)
        k = dense("k_proj")(x).reshape(B, T, H, D)
        v = dense("v_proj")(x).reshape(B, T, H, D)
        y = jax.nn.dot_product_attention(q, k, v, is_causal=True)
        return dense("out_proj")(y.reshape(B, T, C))


class OPTBlock(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name)
        x = x + OPTAttention(cfg, name="self_attn")(
            ln("self_attn_layer_norm")(x))
        h = ln("final_layer_norm")(x)
        h = nn.Dense(cfg.ffn_dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="fc1")(h)
        h = nn.relu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="fc2")(h)
        return x + h


class OPT(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        B, T = tokens.shape
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="embed_tokens")
        pos = nn.Embed(cfg.max_seq_len + cfg.POSITION_OFFSET,
                       cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="embed_positions")
        x = embed(tokens) + pos(jnp.arange(T) + cfg.POSITION_OFFSET)
        block_cls = nn.remat(OPTBlock) if cfg.remat else OPTBlock
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layer_{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype,
                         name="final_layer_norm")(x)
        if cfg.tie_embeddings:
            return embed.attend(x.astype(jnp.float32))
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, use_bias=False,
                        name="lm_head")(x.astype(jnp.float32))


def make_model(cfg: OPTConfig):
    model = OPT(cfg)

    def init_fn(rng, batch_size: int = 2, seq_len: Optional[int] = None):
        T = seq_len or min(cfg.max_seq_len, 64)
        return model.init(rng, jnp.zeros((batch_size, T), jnp.int32))["params"]

    def loss_fn(params, batch, rng):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = model.apply({"params": params}, inputs)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return nll.mean()

    return model, init_fn, loss_fn
