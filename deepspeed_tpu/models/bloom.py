"""BLOOM causal transformer (flax.linen).

Parity target: the reference's BLOOM v1-injection container
(``module_inject/containers/bloom.py``, policy ``replace_policy.py``):
ALiBi attention (no positional embeddings), fused per-head-interleaved
query_key_value projection, embedding LayerNorm
(``word_embeddings_layernorm``), sequential pre-LN residual blocks, biased
GELU MLP, tied unembed.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ._lm_utils import alibi_bias


@dataclasses.dataclass(frozen=True)
class BloomConfig:
    vocab_size: int = 250880
    max_seq_len: int = 2048            # ALiBi: no hard positional limit
    num_layers: int = 30
    num_heads: int = 32
    hidden_size: int = 4096
    layer_norm_eps: float = 1e-5
    tie_embeddings: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def tiny(**kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("hidden_size", 64)
        return BloomConfig(**kw)


class BloomAttention(nn.Module):
    cfg: BloomConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.num_heads, cfg.head_dim
        # q/k/v as separate Dense params; the HF loader splits BLOOM's fused
        # per-head-interleaved query_key_value into these (hf_loader
        # _split_bloom_fused)
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            use_bias=True, name=name)
        q = dense(H * D, "q_proj")(x).reshape(B, T, H, D)
        k = dense(H * D, "k_proj")(x).reshape(B, T, H, D)
        v = dense(H * D, "v_proj")(x).reshape(B, T, H, D)
        bias = alibi_bias(H, T, T).astype(x.dtype)
        y = jax.nn.dot_product_attention(q, k, v, bias=bias, is_causal=True)
        return dense(C, "dense")(y.reshape(B, T, C))


class BloomBlock(nn.Module):
    cfg: BloomConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name)
        x = x + BloomAttention(cfg, name="self_attention")(
            ln("input_layernorm")(x))
        h = ln("post_attention_layernorm")(x)
        h = nn.Dense(4 * cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="dense_h_to_4h")(h)
        h = nn.gelu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="dense_4h_to_h")(h)
        return x + h


class Bloom(nn.Module):
    cfg: BloomConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="word_embeddings")
        from ._lm_utils import constrain_activations
        x = constrain_activations(embed(tokens))
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype,
                         name="word_embeddings_layernorm")(x)
        block_cls = nn.remat(BloomBlock) if cfg.remat else BloomBlock
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layer_{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         param_dtype=cfg.param_dtype, name="ln_f")(x)
        if cfg.tie_embeddings:
            return embed.attend(x.astype(jnp.float32))
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, use_bias=False,
                        name="lm_head")(x.astype(jnp.float32))


def make_model(cfg: BloomConfig):
    from ._lm_utils import make_causal_lm
    return make_causal_lm(Bloom(cfg), cfg)
