"""``dstpu`` CLI — the launcher front-end.

Parity with the reference's ``deepspeed`` CLI (``launcher/runner.py:419``):
resolve the host set (hostfile / --include / --exclude / --num_nodes), pick a
multinode runner, and fan the user script out — or run locally. SPMD note
(SURVEY.md §7 stage 1): JAX wants ONE process per host; there is no per-GPU
process tree to manage, so the per-node spawner (reference ``launch.py:133``)
reduces to env setup + exec for the common case, and local multi-process
spawning exists for CPU-mesh testing.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional

from .hostfile import filter_hosts, parse_hostfile
from .multinode_runner import RUNNERS, local_worker_env

DEFAULT_COORD_PORT = 7777


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dstpu",
        description="deepspeed_tpu launcher: run a training script across "
                    "one or more TPU hosts")
    p.add_argument("--hostfile", type=str, default=None,
                   help="path to a 'host slots=N' hostfile")
    p.add_argument("--include", type=str, default="",
                   help="host filter, e.g. 'worker-0@worker-1:0'")
    p.add_argument("--exclude", type=str, default="",
                   help="inverse host filter")
    p.add_argument("--num_nodes", type=int, default=-1,
                   help="cap the number of hosts used")
    p.add_argument("--num_procs", type=int, default=1,
                   help="local processes to spawn when no hostfile is given "
                        "(CPU-mesh testing)")
    p.add_argument("--master_addr", type=str, default=None,
                   help="coordinator address (default: first host)")
    p.add_argument("--master_port", type=int, default=DEFAULT_COORD_PORT)
    p.add_argument("--launcher", type=str, default="ssh",
                   choices=sorted(RUNNERS))
    p.add_argument("--export", action="append", default=[],
                   metavar="K=V", help="extra env to export to workers")
    p.add_argument("user_script", type=str)
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p


def resolve_hosts(args) -> Optional[List[str]]:
    if args.hostfile is None:
        return None
    with open(args.hostfile) as f:
        hosts = parse_hostfile(f.read())
    hosts = filter_hosts(hosts, args.include, args.exclude)
    names = list(hosts)
    if args.num_nodes > 0:
        names = names[:args.num_nodes]
    return names


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    export_env = dict(kv.split("=", 1) for kv in args.export)
    hosts = resolve_hosts(args)

    # a hostfile naming a single REMOTE host still needs remote dispatch;
    # only no-hostfile or an explicitly local host runs in-place
    local_names = {"localhost", "127.0.0.1", os.uname().nodename}
    if hosts is None or (len(hosts) == 1 and hosts[0] in local_names):
        # single host: spawn num_procs local workers (1 = plain exec)
        if args.num_procs <= 1:
            env = dict(os.environ)
            env.update(export_env)
            cmd = [sys.executable, "-u", args.user_script, *args.user_args]
            return subprocess.call(cmd, env=env)
        coord = f"localhost:{args.master_port}"
        procs = []
        for pid in range(args.num_procs):
            env = local_worker_env(pid, args.num_procs, coord)
            env.update(export_env)
            procs.append(subprocess.Popen(
                [sys.executable, "-u", args.user_script, *args.user_args],
                env=env))
        rc = 0
        for proc in procs:
            rc = proc.wait() or rc
        return rc

    coordinator = f"{args.master_addr or hosts[0]}:{args.master_port}"
    runner = RUNNERS[args.launcher](hosts, coordinator, args.user_script,
                                    args.user_args, export_env)
    procs = [subprocess.Popen(cmd) for cmd in runner.commands()]
    rc = 0
    for proc in procs:
        rc = proc.wait() or rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
