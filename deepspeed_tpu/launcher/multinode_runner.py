"""Multi-node runner command builders.

Parity with the reference's runner zoo (``launcher/multinode_runner.py``:
``PDSHRunner:51``, ``OpenMPIRunner:118``, ``SlurmRunner:336`` …), re-targeted
at SPMD JAX: one worker *process per host* (not per accelerator), each given
``DSTPU_COORDINATOR`` / ``DSTPU_NUM_PROCESSES`` / ``DSTPU_PROCESS_ID`` which
``deepspeed_tpu.comm.init_distributed`` feeds to
``jax.distributed.initialize``. Builders return argv lists so they are
testable without SSH/MPI present.
"""

from __future__ import annotations

import os
import shlex
import sys
from typing import Dict, List, Sequence

ENV_COORD = "DSTPU_COORDINATOR"
ENV_NPROC = "DSTPU_NUM_PROCESSES"
ENV_PID = "DSTPU_PROCESS_ID"


class MultiNodeRunner:
    name = "base"

    def __init__(self, hosts: Sequence[str], coordinator: str,
                 user_script: str, user_args: Sequence[str],
                 export_env: Dict[str, str] | None = None):
        self.hosts = list(hosts)
        self.coordinator = coordinator
        self.user_script = user_script
        self.user_args = list(user_args)
        self.export_env = dict(export_env or {})

    def _worker_cmd(self, pid: int) -> str:
        env = {ENV_COORD: self.coordinator,
               ENV_NPROC: str(len(self.hosts)),
               ENV_PID: str(pid), **self.export_env}
        exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        args = " ".join(shlex.quote(a) for a in self.user_args)
        # run in the launch directory on the remote side — sshd starts in
        # $HOME, which would break relative script/data paths (the reference
        # runner similarly prefixes `cd CWD`)
        cwd = shlex.quote(os.getcwd())
        return (f"cd {cwd} && env {exports} {sys.executable} -u "
                f"{shlex.quote(self.user_script)} {args}").rstrip()

    def commands(self) -> List[List[str]]:
        """One argv per host."""
        raise NotImplementedError


class PDSHRunner(MultiNodeRunner):
    name = "pdsh"

    def commands(self) -> List[List[str]]:
        # pdsh fans out one command; rank comes from matching %h is not
        # possible per-rank, so emit one pdsh invocation per host
        return [["pdsh", "-S", "-w", host, self._worker_cmd(pid)]
                for pid, host in enumerate(self.hosts)]


class SSHRunner(MultiNodeRunner):
    name = "ssh"

    def commands(self) -> List[List[str]]:
        return [["ssh", "-o", "StrictHostKeyChecking=no", host,
                 self._worker_cmd(pid)]
                for pid, host in enumerate(self.hosts)]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun path: ranks discovered via OMPI env (comm.mpi_discovery), so a
    single mpirun handles rank assignment."""
    name = "openmpi"

    def commands(self) -> List[List[str]]:
        cmd = ["mpirun", "-np", str(len(self.hosts)),
               "--host", ",".join(f"{h}:1" for h in self.hosts),
               "-x", f"{ENV_COORD}={self.coordinator}"]
        for k, v in self.export_env.items():
            cmd += ["-x", f"{k}={v}"]
        cmd += [sys.executable, "-u", self.user_script, *self.user_args]
        return [cmd]


class SlurmRunner(MultiNodeRunner):
    """srun path: SLURM_PROCID/SLURM_NTASKS are read by init_distributed's
    discovery, so one srun covers all ranks."""
    name = "slurm"

    def commands(self) -> List[List[str]]:
        # env values go through `env` on the remote side, not --export:
        # srun splits --export on commas, corrupting any value containing one
        cmd = ["srun", "-N", str(len(self.hosts)),
               "--ntasks-per-node=1",
               f"--nodelist={','.join(self.hosts)}",
               "--export=ALL"]
        envs = {ENV_COORD: self.coordinator, **self.export_env}
        cmd += ["env"] + [f"{k}={v}" for k, v in envs.items()]
        cmd += [sys.executable, "-u", self.user_script, *self.user_args]
        return [cmd]


RUNNERS = {r.name: r for r in
           (PDSHRunner, SSHRunner, OpenMPIRunner, SlurmRunner)}


def local_worker_env(pid: int, nproc: int, coordinator: str) -> Dict[str, str]:
    """Env for a locally spawned worker (testing / single-host multiproc)."""
    env = dict(os.environ)
    env.update({ENV_COORD: coordinator, ENV_NPROC: str(nproc),
                ENV_PID: str(pid)})
    return env
