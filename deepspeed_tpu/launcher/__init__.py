"""Launcher CLI + multinode runners (parity: reference ``launcher/``)."""
from .hostfile import HostfileError, filter_hosts, parse_hostfile
from .multinode_runner import RUNNERS, MultiNodeRunner
