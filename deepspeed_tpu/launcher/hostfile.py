"""Hostfile parsing + host filtering.

Parity with the reference launcher's hostfile handling
(``launcher/runner.py:213`` ``parse_resource_filter`` /
``parse_inclusion_exclusion``): lines of ``hostname slots=N``, filtered by
``--include``/``--exclude`` expressions like ``worker-0:0,2@worker-1`` —
except on TPU a "slot" is a host-process (one per host, SPMD), so slot
filters select hosts, not GPUs.
"""

from __future__ import annotations

import collections
import re
from typing import Dict, List, Optional


class HostfileError(ValueError):
    pass


def parse_hostfile(text: str) -> "collections.OrderedDict[str, int]":
    """``host slots=N`` per line; '#' comments; returns {host: slots}."""
    hosts: "collections.OrderedDict[str, int]" = collections.OrderedDict()
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        m = re.match(r"^(\S+)(?:\s+slots=(\d+))?$", line)
        if not m:
            raise HostfileError(f"hostfile line {ln}: cannot parse {raw!r}")
        host, slots = m.group(1), int(m.group(2) or 1)
        if host in hosts:
            raise HostfileError(f"hostfile line {ln}: duplicate host {host}")
        hosts[host] = slots
    if not hosts:
        raise HostfileError("hostfile is empty")
    return hosts


def _parse_filter(expr: str) -> Dict[str, Optional[List[int]]]:
    """``host1:0,2@host2`` -> {host1: [0, 2], host2: None (all slots)}.
    Slot lists are deduplicated; malformed entries raise HostfileError."""
    out: Dict[str, Optional[List[int]]] = {}
    for part in filter(None, expr.split("@")):
        if ":" in part:
            host, slots = part.split(":", 1)
            try:
                out[host] = sorted({int(s) for s in slots.split(",")})
            except ValueError:
                raise HostfileError(
                    f"bad slot filter {part!r}: expected host:i,j,…")
        else:
            out[part] = None
    return out


def _check_slot_indices(filt: Dict[str, Optional[List[int]]],
                        hosts: "collections.OrderedDict[str, int]",
                        flag: str):
    for h, slots in filt.items():
        if slots is None:
            continue
        bad = [s for s in slots if s < 0 or s >= hosts[h]]
        if bad:
            raise HostfileError(
                f"{flag} slot indices {bad} out of range for host {h} "
                f"(slots={hosts[h]})")


def filter_hosts(hosts: "collections.OrderedDict[str, int]",
                 include: str = "", exclude: str = ""
                 ) -> "collections.OrderedDict[str, int]":
    if include and exclude:
        raise HostfileError("--include and --exclude are mutually exclusive")
    result = collections.OrderedDict(hosts)
    if include:
        inc = _parse_filter(include)
        unknown = set(inc) - set(hosts)
        if unknown:
            raise HostfileError(f"--include references unknown hosts {unknown}")
        _check_slot_indices(inc, hosts, "--include")
        result = collections.OrderedDict(
            (h, len(s) if s is not None else hosts[h])
            for h, s in ((h, inc[h]) for h in hosts if h in inc))
    elif exclude:
        exc = _parse_filter(exclude)
        unknown = set(exc) - set(hosts)
        if unknown:
            raise HostfileError(f"--exclude references unknown hosts {unknown}")
        _check_slot_indices(exc, hosts, "--exclude")
        for h, slots in exc.items():
            if slots is None:
                result.pop(h, None)
            else:
                remaining = hosts[h] - len(slots)
                if remaining <= 0:
                    result.pop(h, None)
                else:
                    result[h] = remaining
    return result
